"""Corpus analyzer entry point — drop-in replacement for the reference's
``program/preparation/user_corpus.py`` (reference analyze_repository :157 / main: per-project seed-corpus introduction times via git log -S + GitHub PR merge times, write project_corpus_analysis.csv).  The engine lives in
``tse1m_tpu.collect`` and is driven through ``tse1m_tpu.cli collect``
with the reference's output layout (``data/processed_data/csv/``,
repo clone at ``data/collect_data/repos/oss-fuzz``); extra CLI flags
(e.g. --data-dir, --workers) pass through."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from tse1m_tpu.cli import main as _cli_main  # noqa: E402


def main(argv=None):
    extra = list(sys.argv[1:] if argv is None else argv)
    return _cli_main(["collect", "corpus", *extra])


if __name__ == "__main__":
    sys.exit(main())
