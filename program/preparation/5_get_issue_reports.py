"""Issue scraper entry point — drop-in replacement for the reference's
``program/preparation/5_get_issue_reports.py`` (reference :342 main(): multi-process Selenium scrape of the OSS-Fuzz issue tracker with resume + merge).  The engine lives in
``tse1m_tpu.collect`` and is driven through ``tse1m_tpu.cli collect``
with the reference's output layout (``data/processed_data/csv/``,
repo clone at ``data/collect_data/repos/oss-fuzz``); extra CLI flags
(e.g. --data-dir, --workers) pass through."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from tse1m_tpu.cli import main as _cli_main  # noqa: E402


def main(argv=None):
    extra = list(sys.argv[1:] if argv is None else argv)
    return _cli_main(["collect", "issues", *extra])


if __name__ == "__main__":
    sys.exit(main())
