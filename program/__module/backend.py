"""Thin dispatcher module (north star, BASELINE.json): research-question
scripts import this to get the configured backend without knowing whether
pandas or jax_tpu answers.  Mirrors the reference's ``program/__module``
import pattern (rq1_detection_rate.py:12-17)."""

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tse1m_tpu.backend import get_backend  # noqa: E402,F401
from tse1m_tpu.config import load_config  # noqa: E402,F401
