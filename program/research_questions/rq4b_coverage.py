"""RQ4b entry point — drop-in replacement for the reference's
``program/research_questions/rq4b_coverage.py``; the engine lives in
``tse1m_tpu.analysis.rq4b`` and is selected by envFile.ini's backend key."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tse1m_tpu.analysis.rq4b import run_rq4b  # noqa: E402
from tse1m_tpu.config import load_config  # noqa: E402


def main():
    run_rq4b(load_config())


if __name__ == "__main__":
    main()
