"""RQ2 coverage-trend entry point — drop-in replacement for the reference's
``program/research_questions/rq2_coverage_count.py``."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tse1m_tpu.analysis.rq2_trends import run_rq2_trends  # noqa: E402
from tse1m_tpu.config import load_config  # noqa: E402


def main():
    run_rq2_trends(load_config())


if __name__ == "__main__":
    main()
