"""RQ4a entry point — drop-in replacement for the reference's
``program/research_questions/rq4a_bug.py``; the engine lives in
``tse1m_tpu.analysis.rq4a`` and is selected by envFile.ini's backend key."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tse1m_tpu.analysis.rq4a import run_rq4a  # noqa: E402
from tse1m_tpu.config import load_config  # noqa: E402


def main():
    run_rq4a(load_config())


if __name__ == "__main__":
    main()
