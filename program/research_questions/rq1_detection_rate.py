"""RQ1 entry point — drop-in replacement for the reference's
``program/research_questions/rq1_detection_rate.py``; the engine lives in
``tse1m_tpu.analysis.rq1`` and is selected by envFile.ini's backend key.
The reference's TEST_MODE switch (rq1_detection_rate.py:20) is the
``test_mode`` config key / ``TSE1M_TEST_MODE`` env var, both handled by
``load_config``."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tse1m_tpu.analysis.rq1 import run_rq1  # noqa: E402
from tse1m_tpu.config import load_config  # noqa: E402


def main():
    run_rq1(load_config())


if __name__ == "__main__":
    main()
