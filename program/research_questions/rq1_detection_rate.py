"""RQ1 entry point — drop-in replacement for the reference's
``program/research_questions/rq1_detection_rate.py``; the engine lives in
``tse1m_tpu.analysis.rq1`` and is selected by envFile.ini's backend key."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tse1m_tpu.analysis.rq1 import run_rq1  # noqa: E402
from tse1m_tpu.config import load_config  # noqa: E402

# Reference TEST_MODE switch (rq1_detection_rate.py:20), overridable via env.
TEST_MODE = os.environ.get("TSE1M_TEST_MODE", "").lower() in ("1", "true", "yes")


def main():
    cfg = load_config()
    cfg.test_mode = cfg.test_mode or TEST_MODE
    run_rq1(cfg)


if __name__ == "__main__":
    main()
