"""The shared retry engine.

One implementation of the loop every I/O seat needs: bounded attempts,
exponential backoff with full jitter (the AWS-architecture result — full
jitter minimizes contention among recovering clients), a wall-clock
deadline across *all* attempts, an exception allowlist, and server
``Retry-After`` hints.  The reference hand-rolls this per script
(2_get_buildlog_metadata.py:106-108, 3_get_coverage_data.py:73-74);
the rebuild previously hand-rolled it once in ``HttpFetcher``; now there
is exactly one engine and it is exercised under injected faults in tests.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable

from ..utils.logging import get_logger
from .faults import InjectedFault
from .watchdog import deadline_clock

log = get_logger("resilience.retry")


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff/budget policy applied by :func:`retry_call`."""

    max_attempts: int = 4
    base_delay: float = 0.25          # first backoff step, seconds
    max_delay: float = 30.0           # per-sleep cap
    deadline: float | None = None     # wall-clock budget over all attempts
    jitter: bool = True               # full jitter: sleep ~ U(0, step)
    retry_on: tuple = (Exception,)    # exception allowlist (isinstance)

    def step(self, attempt: int) -> float:
        """Deterministic (pre-jitter) backoff for the given 0-based
        attempt number."""
        return min(self.max_delay, self.base_delay * (2 ** attempt))


class RetryError(RuntimeError):
    """All attempts exhausted (or the deadline passed).  ``__cause__`` is
    the final underlying exception; ``attempts`` is how many were made."""

    def __init__(self, message: str, attempts: int):
        super().__init__(message)
        self.attempts = attempts


@dataclass
class RetryStats:
    """Observability for callers/tests: what the engine actually did."""

    attempts: int = 0
    sleeps: list = field(default_factory=list)


def retry_call(
    fn: Callable,
    *args,
    policy: RetryPolicy | None = None,
    site: str = "",
    should_retry: Callable[[BaseException], bool] | None = None,
    on_retry: Callable[[BaseException, int], None] | None = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    clock: Callable[[], float] = deadline_clock,
    stats: RetryStats | None = None,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``, retrying per ``policy``.

    - Only exceptions matching ``policy.retry_on`` (and, if given, for
      which ``should_retry(exc)`` is true) are retried; anything else
      propagates immediately.
    - ``on_retry(exc, attempt)`` runs before each re-attempt — the seat's
      recovery hook (e.g. DB reconnect after a dropped connection).
    - An exception may carry a ``retry_after`` attribute (seconds) — the
      transport sets it from HTTP ``Retry-After`` — which raises the next
      sleep to at least that, still capped by the remaining deadline.
    - On exhaustion raises :class:`RetryError` from the last exception,
      so callers see both the summary and the root cause.

    ``sleep``/``rng``/``clock`` are injectable for deterministic tests.
    """
    # Function-level import: the telemetry plane sits above this module
    # in the import graph (observability.latency reads deadline_clock).
    from ..observability import tracing

    policy = policy or RetryPolicy()
    rng = rng or random
    start = clock()
    label = site or getattr(fn, "__name__", "call")
    last: BaseException | None = None
    attempts = 0
    for attempt in range(policy.max_attempts):
        attempts = attempt + 1
        if stats is not None:
            stats.attempts = attempts
        try:
            # Each attempt is its own child span, so a trace shows the
            # retry ladder (and which attempt an injected fault hit)
            # instead of one opaque wall.
            with tracing.span(f"attempt.{label}",
                              attempt=attempts) as sp:
                try:
                    return fn(*args, **kwargs)
                except BaseException as e:
                    sp.set_tag("error", type(e).__name__)
                    if isinstance(e, InjectedFault):
                        sp.set_tag("fault", "injected")
                    raise
        except policy.retry_on as e:
            if should_retry is not None and not should_retry(e):
                raise
            last = e
        delay = policy.step(attempt)
        if policy.jitter:
            delay = rng.uniform(0, delay)
        hint = getattr(last, "retry_after", None)
        if hint is not None:
            delay = max(delay, float(hint))
        if policy.deadline is not None:
            remaining = policy.deadline - (clock() - start)
            if remaining <= 0 or (attempt + 1 >= policy.max_attempts):
                break
            if delay > remaining:
                # Sleeping past the deadline cannot help; spend what's
                # left (the last attempt may still get lucky).
                delay = remaining
        elif attempt + 1 >= policy.max_attempts:
            break
        log.warning("%s: attempt %d/%d failed (%s: %s); retrying in %.2fs",
                    label, attempts,
                    policy.max_attempts, type(last).__name__, last, delay)
        from ..observability import metrics as obs_metrics

        obs_metrics.counter("retries_total", site=label).inc()
        if on_retry is not None:
            on_retry(last, attempt)
        if stats is not None:
            stats.sleeps.append(delay)
        if delay > 0:
            sleep(delay)
    raise RetryError(
        f"{site or getattr(fn, '__name__', 'call')}: giving up after "
        f"{attempts} attempts: {type(last).__name__}: {last}",
        attempts) from last
