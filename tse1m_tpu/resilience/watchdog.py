"""Watchdog supervision: per-stage heartbeat deadlines for the long run.

Continuous fuzzing means the pipeline runs unattended for days, and the
failure the retry engine cannot see is the one that never raises: a
tunneled H2D link that silently stalls (BENCH_r05 measured the same
transfer at 9.7-16.7 s run to run — a hung socket looks identical until
you bound it), a device compute that never completes, a DB statement
wedged behind a lock.  This module turns "hung" into a first-class,
recoverable failure:

- :func:`deadline_clock` — THE clock for every deadline in this plane
  (monotonic; immune to NTP steps).  graftlint's ``watchdog-clock`` rule
  forbids raw wall-clock calls here, so a deadline can never jump
  backwards or forwards with the system clock.
- :func:`run_with_deadline` — run a callable on a reaper-able worker
  thread; past the budget the attempt is *cancelled* (abandoned — the
  caller retries with a fresh attempt) and :class:`StallError` raised.
- :func:`deadline_guard` — absolute deadline for in-thread work that owns
  a cooperative cancel hook (e.g. ``sqlite3.Connection.interrupt`` for a
  hung DB statement).
- :class:`StageWatchdog` — adaptive per-stage budgets: the H2D bound
  derives from the link's *measured* rate (seeded from the persisted
  link probe, then EWMA-updated from every completed chunk), device
  compute and DB statements get absolute deadlines.  ``guarded_call``
  combines the deadline with bounded stall-retries and records every
  cancellation as a degradation event (observability plane ->
  ``run_manifest.json`` / bench ``degradation_*`` keys).

Chaos seats: the fault plane's ``stall`` kind (resilience/faults.py)
sleeps at a production seat — ``pipeline.h2d``, ``pipeline.compute`` —
so tests force a hang through the real code path and assert the
watchdog's recovery reproduces the uninterrupted run's labels.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable

from ..utils.logging import get_logger

log = get_logger("resilience.watchdog")


def deadline_clock() -> float:
    """The watchdog plane's one clock (seconds, monotonic).  Every budget,
    deadline and stall decision in this plane must read time through this
    helper — enforced by graftlint's ``watchdog-clock`` rule — so a
    wall-clock step (NTP, DST, operator `date`) can never fire or starve
    a watchdog."""
    return time.monotonic()


class StallError(RuntimeError):
    """An attempt exceeded its watchdog deadline and was cancelled."""

    def __init__(self, site: str, budget_s: float):
        super().__init__(f"{site}: no heartbeat within {budget_s:.2f}s "
                         "budget; attempt cancelled")
        self.site = site
        self.budget_s = budget_s


class Deadline:
    """An absolute deadline anchored at construction time."""

    def __init__(self, budget_s: float):
        self.budget_s = float(budget_s)
        self._t0 = deadline_clock()

    def elapsed(self) -> float:
        return deadline_clock() - self._t0

    def remaining(self) -> float:
        return self.budget_s - self.elapsed()

    def expired(self) -> bool:
        return self.remaining() <= 0.0


def run_with_deadline(fn: Callable, budget_s: float, site: str):
    """Run ``fn()`` on a daemon worker thread; raise :class:`StallError`
    when it does not complete within ``budget_s``.

    A thread cannot be killed, so "cancel" means *abandon*: the stalled
    attempt keeps running detached (daemon — it cannot block process
    exit) and its eventual result is discarded; the caller retries with a
    fresh attempt.  Side-effect discipline is therefore on the caller:
    only guard operations whose duplicate completion is harmless (an
    idempotent device_put, a read).  Exceptions from ``fn`` re-raise
    here unchanged."""
    if budget_s is None or budget_s <= 0:
        return fn()
    box: dict = {}
    # The worker joins the caller's contextvars (a copy — cheap, and
    # writes stay thread-local): the guarded work keeps the caller's
    # active trace span, so a watchdog-guarded serve request still
    # lands in the client's trace.
    ctx = contextvars.copy_context()

    def worker() -> None:
        try:
            box["result"] = ctx.run(fn)
        except BaseException as e:  # graftlint: disable=broad-except -- relayed verbatim (incl. InjectedFault) via `raise box["error"]` below
            box["error"] = e

    t = threading.Thread(target=worker, daemon=True,
                         name=f"tse1m-watchdog:{site}")
    t.start()
    t.join(budget_s)
    if t.is_alive():
        raise StallError(site, budget_s)
    if "error" in box:
        raise box["error"]
    return box.get("result")


@contextmanager
def deadline_guard(budget_s: float, on_timeout: Callable[[], None],
                   site: str = ""):
    """Absolute deadline for in-thread work with a cooperative cancel.

    Arms a timer that calls ``on_timeout()`` (e.g.
    ``sqlite3.Connection.interrupt``) once ``budget_s`` elapses while the
    body is still running; the interrupted operation then fails in-thread
    with its own exception.  The timeout hook never fires after the body
    has completed (completion flag checked under a lock before firing),
    so a near-miss cannot interrupt a *later* statement."""
    if budget_s is None or budget_s <= 0:
        yield
        return
    state = {"done": False, "fired": False}
    lock = threading.Lock()

    def fire() -> None:
        with lock:
            if state["done"]:
                return
            state["fired"] = True
        from ..observability import record_degradation

        record_degradation("deadline_interrupt", site=site,
                           detail={"budget_s": budget_s})
        log.warning("%s: deadline %.2fs exceeded; interrupting", site,
                    budget_s)
        on_timeout()

    timer = threading.Timer(budget_s, fire)
    timer.daemon = True
    timer.start()
    try:
        yield
    finally:
        with lock:
            state["done"] = True
        timer.cancel()


# -- device-failure classification -------------------------------------------

# Message markers meaning "the device/link itself is gone" across PJRT
# backends and the tunneled-link transport (mirrors db.connection's
# _DISCONNECT_MARKERS for the DB plane).
_DEVICE_LOSS_MARKERS = (
    "device_lost", "device lost", "failed to connect", "socket closed",
    "connection reset", "connection refused", "broken pipe",
    "deadline exceeded", "unavailable", "rpc failed", "internal: stream",
)


def is_device_loss(e: BaseException) -> bool:
    """True when the failure means the accelerator (or its link) died —
    retrying on the same device is pointless; fail over instead."""
    if isinstance(e, (ConnectionError, StallError)):
        return True
    msg = str(e).lower()
    return any(m in msg for m in _DEVICE_LOSS_MARKERS)


def is_resource_exhausted(e: BaseException) -> bool:
    """True for XLA/PJRT out-of-memory failures (and injected ones — the
    fault plane raises InjectedFault carrying the same marker, so the
    production classifier needs no test-only branch)."""
    return "RESOURCE_EXHAUSTED" in str(e)


# -- adaptive per-stage budgets ----------------------------------------------

def watchdog_enabled() -> bool:
    return os.environ.get("TSE1M_WATCHDOG", "1") not in ("0", "false", "")


class StageWatchdog:
    """Adaptive heartbeat budgets per pipeline stage.

    The budget for a payload of ``nbytes`` is
    ``max(min_budget, factor * nbytes / rate)`` where ``rate`` is an EWMA
    of the stage's measured bytes/s — seeded from the persisted link
    probe when available (utils/calibration.py ``wire.h2d_MBps``), then
    updated by every completed call, so the bound tracks the link this
    process actually has.  Stages without a byte dimension (compute, DB)
    use the absolute ``min_budget`` alone.

    Env knobs: ``TSE1M_WATCHDOG`` (0 disables the plane),
    ``TSE1M_WATCHDOG_MIN_BUDGET_S`` (floor, default 30),
    ``TSE1M_WATCHDOG_FACTOR`` (slack over the expected wall, default 8),
    ``TSE1M_WATCHDOG_MAX_STALLS`` (cancelled attempts per call before
    the StallError surfaces, default 2)."""

    _EWMA_ALPHA = 0.5

    def __init__(self, min_budget_s: float | None = None,
                 factor: float | None = None,
                 max_stalls: int | None = None,
                 seed_rates: dict | None = None) -> None:
        env = os.environ.get
        self.enabled = watchdog_enabled()
        self.min_budget_s = float(
            env("TSE1M_WATCHDOG_MIN_BUDGET_S", 30.0)
            if min_budget_s is None else min_budget_s)
        self.factor = float(env("TSE1M_WATCHDOG_FACTOR", 8.0)
                            if factor is None else factor)
        self.max_stalls = int(env("TSE1M_WATCHDOG_MAX_STALLS", 2)
                              if max_stalls is None else max_stalls)
        self._lock = threading.Lock()
        self._rate: dict[str, float] = dict(seed_rates or {})  # bytes/s

    def observe(self, stage: str, seconds: float, nbytes: int) -> None:
        """Fold one completed call's measured rate into the stage EWMA."""
        if seconds <= 0 or nbytes <= 0:
            return
        rate = nbytes / seconds
        with self._lock:
            prev = self._rate.get(stage)
            self._rate[stage] = (rate if prev is None else
                                 self._EWMA_ALPHA * rate
                                 + (1 - self._EWMA_ALPHA) * prev)

    def budget_for(self, stage: str, nbytes: int = 0) -> float:
        """Seconds of heartbeat budget for one call; 0 = unguarded."""
        if not self.enabled:
            return 0.0
        with self._lock:
            rate = self._rate.get(stage)
        if nbytes > 0 and rate:
            return max(self.min_budget_s, self.factor * nbytes / rate)
        return self.min_budget_s

    def guarded_call(self, stage: str, fn: Callable, nbytes: int = 0,
                     site: str = ""):
        """``fn()`` under the stage deadline, with bounded stall-retries.

        Each cancelled attempt is recorded as a ``stall_retry``
        degradation event; past ``max_stalls`` cancellations the
        StallError surfaces to the caller's ladder (device failover /
        abort).  Completed calls feed the rate EWMA."""
        site = site or stage
        if not self.enabled:
            return fn()
        from ..observability import record_degradation

        stalls = 0
        while True:
            budget = self.budget_for(stage, nbytes)
            t0 = deadline_clock()
            try:
                result = run_with_deadline(fn, budget, site)
            except StallError as e:
                stalls += 1
                record_degradation(
                    "stall_retry", site=site,
                    detail={"budget_s": round(e.budget_s, 3),
                            "attempt": stalls, "nbytes": int(nbytes)})
                if stalls > self.max_stalls:
                    # Terminal breach — the stall ladder is exhausted
                    # and the error will climb to failover/abort; leave
                    # the black box while this thread still can.
                    from ..observability.flight import dump_flight

                    dump_flight("deadline_breach", site=site,
                                extra={"budget_s": round(e.budget_s, 3),
                                       "stalls": stalls})
                    raise
                log.warning("%s: stalled attempt %d cancelled (budget "
                            "%.2fs); retrying", site, stalls, e.budget_s)
                continue
            self.observe(stage, deadline_clock() - t0, nbytes)
            return result


# -- per-request-class budgets (online serving plane) -------------------------
#
# The serving daemon (tse1m_tpu/serve) answers two very different request
# classes from one process: queries must stay interactive (tens of ms)
# while ingest batches may legitimately spend seconds on the device
# ladder.  One shared watchdog budget would either strangle ingest or
# never catch a wedged query, so each class carries its own deadline —
# read here, on the same monotonic clock as every other budget in this
# plane, and overridable per deployment via TSE1M_SERVE_<CLASS>_BUDGET_S.

_REQUEST_BUDGET_DEFAULTS_S = {
    "query": 0.25,    # 5x the 50 ms p99 SLO: a violation is a wedge,
    #                   not jitter — the SLO layer degrades before this
    "ingest": 120.0,  # covers a cold-compile first batch on the ladder
    "status": 5.0,
}


def request_budget_s(request_class: str) -> float:
    """Watchdog budget (seconds) for one serve request class; 0 disables
    (same contract as StageWatchdog budgets)."""
    if not watchdog_enabled():
        return 0.0
    env = os.environ.get(f"TSE1M_SERVE_{request_class.upper()}_BUDGET_S")
    if env is not None:
        return float(env)
    return _REQUEST_BUDGET_DEFAULTS_S.get(request_class, 30.0)


__all__ = ["Deadline", "StageWatchdog", "StallError", "deadline_clock",
           "deadline_guard", "is_device_loss", "is_resource_exhausted",
           "request_budget_s", "run_with_deadline", "watchdog_enabled"]
