"""Unified resilience layer: one retry engine, one fault-injection plane,
one run-to-completion orchestrator (SURVEY §5 A3/A4).

The source study survived years of flaky external services — GCS
pagination, daily coverage servers, a Selenium-scraped tracker — across
~1.19M build logs.  The rebuild previously had robustness *seats*
(transport retries, checkpoint resume) but no code path ever exercised
them under an actual failure.  This package makes recovery a tested
property:

- ``retry.retry_call`` / ``RetryPolicy``: exponential backoff + full
  jitter + deadline + exception allowlist; honors server ``Retry-After``
  hints carried on exceptions.  Used by the HTTP transport, both DB
  drivers, and both checkpointers.
- ``faults.fault_point`` / ``FaultPlan``: a deterministic, seeded fault
  injector.  Production I/O seats call ``fault_point("site")``; with no
  plan installed (the default) that is a no-op, so prod code carries zero
  test-only branches.  A plan (JSON via ``TSE1M_FAULT_PLAN``, or
  installed in-process) makes the *production* path raise, delay, drop
  connections, tear writes, or SIGKILL the process at chosen sites.
- ``runner.StepRunner``: run-to-completion orchestration for ``cli all``
  — each step isolated, per-step status/attempts/traceback recorded in
  ``run_manifest.json``, survivors complete, exit code reflects partial
  failure.
- ``watchdog``: heartbeat deadlines for failures that never raise —
  stalled transfers cancelled + retried under adaptive budgets, hung
  device compute / DB statements bounded by absolute deadlines, every
  recovery recorded as a degradation event (observability plane).
"""

from __future__ import annotations

import os

from .coordinator import (HeartbeatWriter, HostLostError, PeerMonitor,
                          PodSupervisor, resume_heartbeats,
                          suspend_heartbeats)
from .faults import (FaultPlan, FaultRule, InjectedConnectionDrop,
                     InjectedFault, active_plan, clear_plan, fault_point,
                     install_plan, reraise_if_fault)
from .retry import RetryError, RetryPolicy, retry_call
from .runner import StepRunner
from .watchdog import (Deadline, StageWatchdog, StallError, deadline_clock,
                       deadline_guard, is_device_loss, is_resource_exhausted,
                       request_budget_s, run_with_deadline, watchdog_enabled)

__all__ = [
    "Deadline", "FaultPlan", "FaultRule", "HeartbeatWriter",
    "HostLostError", "InjectedConnectionDrop", "InjectedFault",
    "PeerMonitor", "PodSupervisor", "RetryError", "RetryPolicy",
    "StageWatchdog", "StallError", "StepRunner", "active_plan",
    "clear_plan", "deadline_clock", "deadline_guard", "fault_point",
    "install_plan", "io_retry_policy", "is_device_loss",
    "is_resource_exhausted", "request_budget_s", "reraise_if_fault",
    "resume_heartbeats", "retry_call", "run_with_deadline",
    "suspend_heartbeats", "watchdog_enabled",
]


def io_retry_policy(**overrides) -> RetryPolicy:
    """The default policy for local-I/O seats (checkpoint writes, DB
    statements): a few fast attempts, bounded backoff.  Env-tunable so an
    operator can harden a flaky NFS mount without code changes:
    ``TSE1M_RETRY_ATTEMPTS``, ``TSE1M_RETRY_BASE_DELAY``,
    ``TSE1M_RETRY_MAX_DELAY``, ``TSE1M_RETRY_DEADLINE``.
    """
    kw = dict(
        max_attempts=int(os.environ.get("TSE1M_RETRY_ATTEMPTS", 4)),
        base_delay=float(os.environ.get("TSE1M_RETRY_BASE_DELAY", 0.05)),
        max_delay=float(os.environ.get("TSE1M_RETRY_MAX_DELAY", 2.0)),
    )
    if "TSE1M_RETRY_DEADLINE" in os.environ:
        kw["deadline"] = float(os.environ["TSE1M_RETRY_DEADLINE"])
    kw.update(overrides)
    return RetryPolicy(**kw)
