"""Run-to-completion orchestration for multi-step commands (``cli all``).

Each step runs isolated: a failure is recorded (status, attempts, short
error, full traceback) and the remaining steps still run.  The manifest
is rewritten atomically after *every* step, so even a SIGKILL mid-run
leaves an accurate partial record on disk.  ``exit_code()`` reflects
partial failure — previously ``cli all`` aborted every remaining RQ on
the first exception and a missing module still exited 0.
"""
# graftlint: disable-file=nondeterminism -- time.time() here stamps manifest telemetry (started_at/wall_s), never replay control flow

from __future__ import annotations

import json
import os
import time
import traceback
from dataclasses import asdict, dataclass, field

from .retry import RetryError, RetryPolicy, retry_call
from ..utils.logging import get_logger

log = get_logger("resilience.runner")


@dataclass
class StepRecord:
    name: str
    status: str = "pending"   # pending | ok | failed | missing | skipped
    attempts: int = 0
    wall_s: float = 0.0
    error: str | None = None      # one-line summary
    traceback: str | None = None  # full text, failures only
    detail: str | None = None     # e.g. why a step was skipped/missing
    # Per-stage pipeline telemetry (observability.StageRecorder as_dict:
    # stage_*_s / stage_*_mb / h2d_overlap_fraction) when the step's body
    # recorded any — e.g. a cluster step's encode/h2d/compute/d2h split.
    stages: dict | None = None
    # Structured step output: a step function that returns a dict gets it
    # embedded verbatim (e.g. the graftlint step's finding counts and
    # sanitizer self-check); a failing step may attach one via a
    # ``step_result`` attribute on the raised exception.
    result: dict | None = None
    # Degradation events the step survived (observability plane: stall
    # retries, chunk halvings, device failovers, quarantined store
    # shards) — the run manifest is the long-run operator's ledger of
    # what the supervision plane absorbed.  None when the step ran clean.
    degradations: list | None = None


class StepRunner:
    """Run named steps to completion, recording each into a JSON manifest.

    ``policy`` (optional) retries each step through the shared engine —
    for idempotent steps only; the default is one attempt, because an RQ
    that half-wrote artifacts should surface, not loop.
    """

    def __init__(self, manifest_path: str | None,
                 policy: RetryPolicy | None = None):
        self.manifest_path = manifest_path
        self.policy = policy or RetryPolicy(max_attempts=1)
        self.steps: list[StepRecord] = []
        self.started_at = time.time()
        if manifest_path:
            # Crash dumps land next to the manifest they annotate (an
            # explicit set_flight_dir / TSE1M_FLIGHT_DIR still wins).
            from ..observability.flight import get_flight_dir, set_flight_dir

            if get_flight_dir() is None:
                set_flight_dir(os.path.dirname(manifest_path) or ".")
        # Extra top-level manifest fields (e.g. the pod path's membership
        # "epoch" — observability/merge.py tags each fragment's steps
        # with it so a mid-run membership change stays attributable).
        self.meta: dict = {}

    def _record(self, rec: StepRecord) -> StepRecord:
        self.steps.append(rec)
        self._write()
        return rec

    def run(self, name: str, fn, *args, **kwargs) -> StepRecord:
        """Run one step isolated; never raises (the record carries the
        failure)."""
        from ..observability import pop_degradation_events, pop_last_stages
        from ..observability.flight import dump_flight
        from ..observability.tracing import span

        rec = StepRecord(name=name, status="running")
        self.steps.append(rec)
        t0 = time.time()
        attempts = [0]
        pop_last_stages()  # drop a predecessor's stages; only telemetry
        #                    recorded BY this step may attach to it
        pop_degradation_events()  # same isolation for degradation events

        def attempt():
            attempts[0] += 1
            return fn(*args, **kwargs)

        try:
            with span(f"step.{name}"):
                ret = retry_call(attempt, policy=self.policy,
                                 site=f"step:{name}")
            rec.status = "ok"
            if isinstance(ret, dict):
                rec.result = ret
        except BaseException as e:  # noqa: BLE001 — isolation is the point
            cause = e.__cause__ if isinstance(e, RetryError) and e.__cause__ else e
            res = getattr(cause, "step_result", None)
            if isinstance(res, dict):
                rec.result = res
            rec.error = f"{type(cause).__name__}: {cause}".strip().rstrip(":")
            rec.status = "failed"
            rec.traceback = traceback.format_exc()
            log.error("step %s failed after %d attempt(s): %s", name,
                      attempts[0], rec.error)
            dump_flight("step_failed", site=f"step:{name}",
                        extra={"error": rec.error,
                               "attempts": attempts[0]})
            if isinstance(e, KeyboardInterrupt):
                rec.wall_s = round(time.time() - t0, 3)
                rec.attempts = attempts[0]
                self._write()
                raise
        rec.attempts = attempts[0]
        rec.wall_s = round(time.time() - t0, 3)
        rec.stages = pop_last_stages()
        rec.degradations = pop_degradation_events() or None
        self._write()
        return rec

    def record_missing(self, name: str, detail: str) -> StepRecord:
        """A requested step whose implementation is absent — previously a
        silent log line and exit 0."""
        return self._record(StepRecord(name=name, status="missing",
                                       detail=detail))

    def set_meta(self, **fields) -> None:
        """Attach extra top-level manifest fields and rewrite the
        manifest (e.g. the pod membership epoch, once known)."""
        self.meta.update(fields)
        self._write()

    def record_skipped(self, name: str, detail: str) -> StepRecord:
        return self._record(StepRecord(name=name, status="skipped",
                                       detail=detail))

    # -- outcome ------------------------------------------------------------

    @property
    def failed(self) -> list[StepRecord]:
        return [s for s in self.steps if s.status in ("failed", "missing")]

    def exit_code(self) -> int:
        return 1 if self.failed or not self.steps else 0

    def summary(self) -> dict:
        by = {}
        for s in self.steps:
            by[s.status] = by.get(s.status, 0) + 1
        return by

    def _write(self) -> None:
        if not self.manifest_path:
            return
        from ..observability import degradation_counts
        from ..observability.export import metrics_snapshot
        from ..observability.tracing import pinned_trace, spans_recorded

        events = [e for s in self.steps for e in (s.degradations or [])]
        payload = {
            "started_at": self.started_at,
            "wall_seconds": round(time.time() - self.started_at, 3),
            "ok": not self.failed,
            "summary": self.summary(),
            # kind -> count over every step: the one-glance answer to
            # "what did the supervision plane absorb this run".
            "degradation_counts": degradation_counts(events),
            # Telemetry plane: the run's trace id (pod runs pin the
            # negotiated nonce, so every fragment carries the same id)
            # and this process's metrics registry — merge.py folds the
            # fragments' snapshots into the merged manifest.
            "trace_id": pinned_trace(),
            "spans_recorded": spans_recorded(),
            "metrics": metrics_snapshot(),
            **self.meta,
            "steps": [asdict(s) for s in self.steps],
        }
        os.makedirs(os.path.dirname(self.manifest_path) or ".",
                    exist_ok=True)
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, default=str)
        os.replace(tmp, self.manifest_path)
