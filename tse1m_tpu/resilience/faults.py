"""Deterministic, seeded fault-injection plane.

Production I/O seats call ``fault_point("dotted.site", path=...)``.  With
no plan active — the production default — that is a dict lookup and a
return; there are no test-only branches in prod code.  Tests (or an
operator running a game-day) activate a :class:`FaultPlan` either
in-process (``install_plan`` / ``FaultPlan.active()``) or across process
boundaries via ``TSE1M_FAULT_PLAN=<plan.json>``, and the *production*
code paths then run under injected failures.

Instrumented sites (grep for ``fault_point(`` to audit):

- ``http.fetch``                 one HTTP request attempt (transport.py)
- ``db.connect`` / ``db.execute``  connection wrapper (db/connection.py)
- ``pglib.exec``                 raw libpq statement (db/pglib.py)
- ``checkpoint.csv.flush``       collector batch write (collect/checkpoint.py)
- ``checkpoint.cluster.save``    cluster shard write (cluster/checkpoint.py)

Fault kinds:

- ``raise``:  raise :class:`InjectedFault` (or a named exception class)
- ``connection_drop``: raise :class:`InjectedConnectionDrop` (a
  ``ConnectionError`` subclass, so generic disconnect classifiers fire)
- ``delay``:  sleep ``delay_s`` seconds, then pass through
- ``torn_write``: truncate the file at the seat's ``path`` to
  ``truncate_fraction`` of its bytes, then raise — a crash mid-write
- ``kill``:   ``SIGKILL`` the current process — the chaos-test hammer
- ``stall``:  sleep ``stall_s`` seconds, then pass through — a hung
  link/device/statement, the failure that never raises.  The watchdog
  plane (resilience/watchdog.py) is what turns this into a recoverable
  cancellation; without a watchdog the seat genuinely hangs, which is
  the point.
- ``hostloss``: suspend this process's pod heartbeats
  (resilience/coordinator.suspend_heartbeats), then sleep ``stall_s`` —
  a wedged host that is alive but silent.  Peers declare it lost through
  the production heartbeat monitor and fail its digest range over;
  ``kill`` covers the dead-process variant of the same failure.
- ``zombie``: ``hostloss`` that WAKES UP — suspend heartbeats, sleep
  until ``stall_s`` elapses or the file at ``wake_path`` appears (the
  deterministic game-day trigger: the chaos harness touches it once the
  survivor has re-dealt the wedged host's range), then RESUME
  heartbeats and pass through.  The woken writer continues at a
  production seat with its digest-range lease superseded — the failure
  mode the epoch leases exist to fence (it must self-fence via
  LeaseSupersededError, never double-write).
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
import signal
import time
from dataclasses import asdict, dataclass, field

from ..utils.logging import get_logger

log = get_logger("resilience.faults")


class InjectedFault(Exception):
    """A transient failure injected by the fault plane."""


class InjectedConnectionDrop(ConnectionError, InjectedFault):
    """An injected dropped connection (classified like a real one)."""


_KINDS = ("raise", "connection_drop", "delay", "torn_write", "kill",
          "stall", "hostloss", "zombie")


@dataclass
class FaultRule:
    """One per-site rule.  ``site`` is an fnmatch pattern against the seat
    name; the rule fires for the matching calls numbered
    ``[after_calls, after_calls + times)`` (per-rule counter), each time
    with probability ``probability`` drawn from the plan's seeded RNG."""

    site: str
    kind: str = "raise"
    times: int = 1                 # how many calls fire; -1 = every call
    after_calls: int = 0           # skip this many matching calls first
    probability: float = 1.0       # per-eligible-call chance (seeded RNG)
    message: str = "injected fault"
    delay_s: float = 0.05          # kind=delay
    stall_s: float = 30.0          # kind=stall (a hang, not a hiccup)
    truncate_fraction: float = 0.5  # kind=torn_write
    wake_path: str | None = None   # kind=zombie: wake early on this file
    _seen: int = field(default=0, repr=False, compare=False)
    _fired: int = field(default=0, repr=False, compare=False)

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"expected one of {_KINDS}")


class FaultPlan:
    """An ordered set of :class:`FaultRule`\\ s plus a seeded RNG.

    The first matching, still-eligible rule fires per call.  ``fired`` is
    the observable log of (site, kind) events for test assertions."""

    def __init__(self, rules: list[FaultRule], seed: int = 0):
        self.rules = list(rules)
        self.rng = random.Random(seed)
        self.seed = seed
        self.fired: list[tuple[str, str]] = []

    # -- (de)serialization --------------------------------------------------

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        rules = [FaultRule(**r) for r in d.get("rules", [])]
        return cls(rules, seed=int(d.get("seed", 0)))

    @classmethod
    def from_json(cls, path: str) -> "FaultPlan":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    def to_dict(self) -> dict:
        rules = []
        for r in self.rules:
            d = asdict(r)
            d.pop("_seen"), d.pop("_fired")
            rules.append(d)
        return {"seed": self.seed, "rules": rules}

    def save(self, path: str) -> str:
        from ..utils.atomic import atomic_write

        with atomic_write(path) as f:
            json.dump(self.to_dict(), f, indent=2)
        return path

    # -- firing -------------------------------------------------------------

    def fire(self, site: str, path: str | None = None) -> None:
        for rule in self.rules:
            if not fnmatch.fnmatch(site, rule.site):
                continue
            rule._seen += 1
            if rule._seen <= rule.after_calls:
                continue
            if rule.times >= 0 and rule._fired >= rule.times:
                continue
            if rule.probability < 1.0 and self.rng.random() >= rule.probability:
                continue
            rule._fired += 1
            self.fired.append((site, rule.kind))
            log.warning("fault plane: %s at %s (fire %d)", rule.kind, site,
                        rule._fired)
            # Function-level import: the metrics plane imports the
            # watchdog clock from this package.
            from ..observability import metrics as obs_metrics

            obs_metrics.counter("fault_injections_total", site=site,
                                kind=rule.kind).inc()
            self._apply(rule, site, path)
            return  # at most one rule fires per call

    def _apply(self, rule: FaultRule, site: str, path: str | None) -> None:
        if rule.kind == "delay":
            time.sleep(rule.delay_s)
            return
        if rule.kind == "stall":
            time.sleep(rule.stall_s)
            return
        if rule.kind == "hostloss":
            from .coordinator import suspend_heartbeats

            suspend_heartbeats()
            time.sleep(rule.stall_s)
            return
        if rule.kind == "zombie":
            from .coordinator import resume_heartbeats, suspend_heartbeats

            suspend_heartbeats()
            remaining = rule.stall_s
            while remaining > 0:
                if rule.wake_path and os.path.exists(rule.wake_path):
                    break
                slice_s = min(0.25, remaining)
                time.sleep(slice_s)
                remaining -= slice_s
            resume_heartbeats()
            log.warning("fault plane: zombie at %s woke after wedge "
                        "(heartbeats resumed)", site)
            return
        if rule.kind == "kill":
            # Last words: SIGKILL leaves no handler to run, so the
            # flight recorder writes its dump BEFORE the signal — the
            # terminal span names this seat, which is how a post-mortem
            # identifies what killed the process.
            from ..observability.flight import dump_flight

            dump_flight("fault.kill", site=site)
            os.kill(os.getpid(), signal.SIGKILL)
            # SIGKILL delivery can be asynchronous; never fall through
            # and surface some *other* fault kind as a catchable
            # exception while the signal is in flight.
            raise SystemExit(f"fault plane: SIGKILL at {site}")
        if rule.kind == "torn_write" and path and os.path.exists(path):
            size = os.path.getsize(path)
            keep = int(size * rule.truncate_fraction)
            with open(path, "rb+") as f:
                f.truncate(keep)
            log.warning("fault plane: tore %s to %d/%d bytes", path, keep,
                        size)
        if rule.kind == "connection_drop":
            raise InjectedConnectionDrop(f"{rule.message} at {site}")
        raise InjectedFault(f"{rule.message} at {site}")

    # -- context-manager installation ---------------------------------------

    def active(self) -> "_Activation":
        """``with plan.active(): ...`` installs the plan in-process."""
        return _Activation(self)


class _Activation:
    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        install_plan(self.plan)
        return self.plan

    def __exit__(self, *exc) -> None:
        clear_plan()


# -- process-global plan ------------------------------------------------------

_plan: FaultPlan | None = None
_env_loaded = False


def install_plan(plan: FaultPlan) -> None:
    global _plan, _env_loaded
    _plan = plan
    _env_loaded = True  # an explicit install wins over the env plan


def clear_plan() -> None:
    global _plan, _env_loaded
    _plan = None
    _env_loaded = True


def active_plan() -> FaultPlan | None:
    """The installed plan, loading ``TSE1M_FAULT_PLAN`` on first use."""
    global _plan, _env_loaded
    if not _env_loaded:
        _env_loaded = True
        path = os.environ.get("TSE1M_FAULT_PLAN")
        if path:
            try:
                _plan = FaultPlan.from_json(path)
                log.warning("fault plan active from %s: %d rules", path,
                            len(_plan.rules))
            except Exception as e:
                raise RuntimeError(
                    f"TSE1M_FAULT_PLAN={path!r} could not be loaded: {e}"
                ) from e
    return _plan


def fault_point(site: str, path: str | None = None) -> None:
    """The single hook production I/O seats call.  No active plan: no-op."""
    plan = active_plan()
    if plan is not None:
        plan.fire(site, path=path)


def reraise_if_fault(exc: BaseException) -> None:
    """Fault-transparency guard for handlers that must stay broad.

    A seat like the issue scraper's client-restart loop genuinely has to
    catch *anything* (Selenium raises arbitrary driver exceptions), but a
    broad handler that also eats :class:`InjectedFault` makes the chaos
    tests blind at that seat.  Calling this first keeps the handler broad
    for real failures while injected faults propagate — graftlint's
    ``broad-except`` rule recognises the call as fault-safe."""
    if isinstance(exc, InjectedFault):
        raise exc
