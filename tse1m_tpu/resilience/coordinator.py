"""Pod-scale supervision: peer heartbeats, epoch membership + failover.

The PR 5 watchdog bounds every stage *inside* one process; the failure it
cannot see is a whole host going away — SIGKILLed by the scheduler, wedged
in a kernel hang, or partitioned off the network.  This module turns
"lost host" into a first-class, recoverable failure, the same shape
fault-tolerant multi-host training stacks use (elastic membership +
re-execution of the lost worker's partition, the MapReduce recipe):

- :class:`HeartbeatWriter` — every process beats a monotonically
  increasing ``seq`` into ``hb_<pid>.json`` under a shared directory
  (atomic tmp+rename, so a reader never sees a torn beat).  Beats carry
  NO timestamps: wall clocks are not comparable across hosts, and the
  watchdog plane forbids them anyway (graftlint ``watchdog-clock``).
- :class:`PeerMonitor` — declares a peer lost when its ``seq`` has not
  advanced within ``timeout_s`` measured on the LOCAL
  :func:`~.watchdog.deadline_clock`.  Only local monotonic deltas are
  ever compared, so NTP steps on either host cannot fire or starve the
  monitor.  Loss declarations latch PER EPOCH: a host lost in epoch N
  can be alive again in epoch N+1 (:meth:`PeerMonitor.advance_epoch`),
  but only by beating a genuinely NEW run nonce — a stale heartbeat
  file replaying an already-seen nonce, or a regressed seq under the
  current nonce, never counts as an advance (the replay guard).
- :class:`MembershipLedger` — ``membership.json`` under the pod dir: a
  monotonic **epoch**, the member set, and the range → owner deal.
  Epochs advance on loss AND on recovery; the re-deal is ELASTIC — only
  ranges whose owner left (or that rebalance onto a re-admitted member)
  change writers, everything else keeps its owner, so a recovered host
  re-admits at the next epoch boundary without a full rerun.
- **Epoch leases** — one ``lease_NNNN.json`` per digest range under the
  sharded store root (atomic tmp+rename; monotonic epoch + run nonce,
  NO wall timestamps — fencing is by epoch comparison, never by clock).
  Every ``ShardedSignatureStore`` writer must hold the current-epoch
  lease before appending; a zombie that wakes after its range was
  reassigned finds its lease superseded and self-fences
  (:class:`LeaseSupersededError` → read-only demotion, recorded as a
  degradation event) instead of double-writing.
- :class:`PodSupervisor` — heartbeat writer + monitor, plus
  :meth:`guarded`: run a cross-host phase on a reaper-able thread while
  polling the monitor — a dead peer turns an infinite wait into
  :class:`HostLostError` within one heartbeat timeout.  The caller
  (cli's pod cluster step) then fails over: the lowest-id survivor
  advances the membership epoch (promoting itself to leader when
  process 0 is among the lost — the pod plane has no dependency on the
  XLA coordination service, so leader death is one more reassignment,
  not a pod-wide fence) and re-executes with the lost hosts' digest
  ranges re-dealt.  Every declaration/reassignment/promotion fires a
  degradation event into the merged pod ``run_manifest.json``.

The fault plane's ``hostloss`` kind (resilience/faults.py) wedges a host
forever for the chaos tests; the ``zombie`` kind wedges it and then
RESUMES it — the writer that wakes at a production seat after its range
was reassigned, exactly the failure the leases fence.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import threading
import time
from typing import Callable

from ..utils.atomic import atomic_write
from ..utils.logging import get_logger
from .watchdog import deadline_clock

log = get_logger("resilience.coordinator")

# graftspec binding: the epoch-lease protocol this module implements
# is modeled by tse1m_tpu/spec/lease.py; the lint conformance pass
# holds the two together.
SPEC_MODELS = ("lease",)

_HB_PREFIX = "hb_"
_XCH_PREFIX = "xch_"


def heartbeat_interval_s() -> float:
    return float(os.environ.get("TSE1M_HEARTBEAT_INTERVAL_S", 0.5))


def heartbeat_timeout_s() -> float:
    return float(os.environ.get("TSE1M_HEARTBEAT_TIMEOUT_S", 10.0))


class HostLostError(RuntimeError):
    """Peer host(s) declared lost (heartbeat timeout / dead collective)."""

    def __init__(self, lost: list, site: str = ""):
        self.lost = sorted(int(p) for p in lost)
        self.site = site
        super().__init__(
            f"{site or 'pod'}: host(s) {self.lost} declared lost — no "
            "heartbeat within the timeout; their digest ranges reassign "
            "to survivors and their rows recompute")


class LeaseSupersededError(RuntimeError):
    """This writer's epoch lease on a digest range was superseded — a
    later epoch re-dealt the range to another process while this one was
    wedged.  The holder must self-fence: demote to read-only and stop
    appending (a zombie double-write would corrupt the single-writer
    invariant the range depends on)."""

    def __init__(self, range_id: int, held: dict, current: dict | None):
        self.range_id = int(range_id)
        self.held = dict(held)
        self.current = dict(current) if current else None
        cur = (f"epoch {current.get('epoch')} owned by process "
               f"{current.get('owner')}" if current else "absent")
        super().__init__(
            f"lease on digest range {self.range_id} superseded: this "
            f"writer holds epoch {held.get('epoch')} as process "
            f"{held.get('owner')}, but the on-disk lease is {cur} — the "
            "range was re-dealt while this process was wedged; demoting "
            "to read-only (zero further appends) instead of double-"
            "writing")
        # Fencing is a crash-class event for this writer: count it and
        # leave a flight dump while the process can still explain
        # itself (a fenced zombie typically exits soon after).
        # Function-level imports — the telemetry plane sits above this
        # module in the import graph.
        from ..observability import metrics as obs_metrics
        from ..observability.flight import dump_flight

        obs_metrics.counter("lease_superseded_total").inc()
        dump_flight("lease_superseded",
                    site=f"lease.range{self.range_id}",
                    extra={"held": self.held, "current": self.current})


# The fault plane's hostloss kind flips this: a wedged host stays alive
# but stops beating, so peers declare it lost through the production
# heartbeat path (zero test-only branches in the monitor).
_suspended = threading.Event()

# Latches when ANY monitor in this process declares a host lost: the
# jax.distributed runtime is poisoned from that moment (its Shutdown
# barrier can never pass without the dead task) and the process must
# leave through hard_exit_if_host_lost.
_loss_seen = threading.Event()


def saw_host_loss() -> bool:
    return _loss_seen.is_set()


# Failover scope note: the pod plane carries its own process identity
# (parallel/multihost.pod_process_env) and never initializes the XLA
# coordination service, so there is no client to LOG(FATAL) the
# survivors when process 0 dies — leader loss is detected by the same
# file heartbeats as any worker loss, and the lowest-id survivor
# promotes itself over the shared-filesystem exchange plane (advances
# the membership epoch, re-executes, merges the manifest fragments).
# The mesh (non-pod) multi-host path still runs under jax.distributed;
# hard_exit_if_host_lost remains its only safe exit after a loss.


def hard_exit_if_host_lost(code: int) -> int:
    """Exit NOW via ``os._exit`` when this run declared a host lost (and
    is actually distributed); otherwise return ``code`` for the normal
    return path.

    Once a pod peer is dead, the XLA coordination client cannot
    disconnect: ``client.shutdown()`` waits at a Shutdown barrier the
    dead task will never join and LOG(FATAL)s the survivor — an exit
    code of -SIGABRT from the process that *survived* the failover.
    All durable state (manifests, store shards, labels) is written with
    atomic renames before the callers invoke this, so skipping the
    interpreter's atexit teardown loses nothing."""
    import jax

    if _loss_seen.is_set() and jax.process_count() > 1:
        log.warning("pod: host loss was declared this run — exiting "
                    "without jax.distributed teardown (code %d)", code)
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(code)
    return code


def suspend_heartbeats() -> None:
    _suspended.set()


def resume_heartbeats() -> None:
    _suspended.clear()


def heartbeat_path(directory: str, process_id: int) -> str:
    return os.path.join(directory, f"{_HB_PREFIX}{int(process_id):03d}.json")


class HeartbeatWriter:
    """Beat ``seq`` into this process's heartbeat file from a daemon
    thread.  Atomic writes only — a peer's read never races a beat."""

    def __init__(self, directory: str, process_id: int,
                 interval_s: float | None = None) -> None:
        self.directory = directory
        self.process_id = int(process_id)
        self.interval_s = (heartbeat_interval_s()
                           if interval_s is None else float(interval_s))
        self._lock = threading.Lock()
        self._seq = 0
        # Per-run nonce: a fresh run restarts seq at 1, which a stale
        # heartbeat file from a previous run (higher seq) would otherwise
        # mask forever — any nonce change counts as an advance.
        self._run_id = os.urandom(8).hex()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        os.makedirs(directory, exist_ok=True)

    @property
    def run_id(self) -> str:
        """This run's heartbeat nonce (fresh per HeartbeatWriter)."""
        return self._run_id

    def beat_once(self) -> int:
        from ..observability.tracing import pinned_trace

        with self._lock:
            self._seq += 1
            seq = self._seq
        with atomic_write(heartbeat_path(self.directory,
                                         self.process_id)) as f:
            # The pod-wide trace id rides every beat: a heartbeat file
            # found after a crash names the trace its process belonged
            # to (readers ignore unknown keys).
            json.dump({"process_id": self.process_id, "seq": seq,
                       "run": self._run_id,
                       "trace": pinned_trace()}, f)
        return seq

    def _run(self) -> None:
        while not self._stop.is_set():
            if not _suspended.is_set():
                try:
                    self.beat_once()
                except OSError as e:
                    log.warning("heartbeat write failed (%s); peers may "
                                "declare this host lost", e)
            self._stop.wait(self.interval_s)

    def start(self) -> "HeartbeatWriter":
        if self._thread is None:
            self.beat_once()  # visible before any peer's grace expires
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"tse1m-heartbeat:{self.process_id}")
            with self._lock:
                self._thread = t
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()


class PeerMonitor:
    """Track peers' heartbeat seqs; declare lost on no advance within
    ``timeout_s`` of the LOCAL deadline_clock.

    Loss declarations latch PER EPOCH: within one membership epoch a
    host that resumes beating after the declaration stays lost (its
    range was already reassigned); :meth:`advance_epoch` opens the next
    epoch, where the host may re-admit — but only by beating a NEW run
    nonce.  The replay guard rejects resurrection by stale state: a
    heartbeat file carrying an already-seen (rolled-back) nonce, or a
    regressed seq under the current nonce, never counts as an advance."""

    def __init__(self, directory: str, n_processes: int, process_id: int,
                 timeout_s: float | None = None,
                 peers: list | None = None) -> None:
        self.directory = directory
        self.process_id = int(process_id)
        # ``peers`` overrides the dense 0..n_processes-1 assumption for
        # asymmetric topologies — e.g. a serve router (not itself a
        # shard writer) watching the shard daemons' heartbeat ids.
        self.peers = (sorted(int(p) for p in peers
                             if int(p) != self.process_id)
                      if peers is not None
                      else [p for p in range(int(n_processes))
                            if p != self.process_id])
        self.timeout_s = (heartbeat_timeout_s()
                          if timeout_s is None else float(timeout_s))
        now = deadline_clock()
        self._lock = threading.Lock()
        # peer -> (last (run, seq) seen, deadline_clock() at last advance).
        # Absent files get the full grace window from monitor start.
        self._seen = {p: ((None, -1), now) for p in self.peers}
        # Replay guard: every nonce ever observed per peer.  A beat whose
        # nonce is in this set but is not the peer's CURRENT nonce is a
        # rollback (a stale file resurfacing), never an advance.
        self._nonces: dict[int, set] = {p: set() for p in self.peers}
        self.epoch = 0
        self._lost: set[int] = set()          # current-epoch latch
        self._lost_history: set[int] = set()  # prior epochs (observability)

    def _read_beat(self, peer: int):
        """(run nonce, seq) of the peer's last beat, or None."""
        try:
            with open(heartbeat_path(self.directory, peer),
                      encoding="utf-8") as f:
                d = json.load(f)
            return (d.get("run"), int(d["seq"]))
        except (OSError, ValueError, KeyError):
            return None

    def _advanced(self, peer: int, beat) -> bool:
        """The replay guard: does this beat prove the peer is alive?"""
        if beat is None:
            return False
        run, seq = beat
        last_run, last_seq = self._seen[peer][0]
        if run == last_run:
            return seq > last_seq  # a regressed seq is a stale file
        # A nonce change is an advance only when the nonce is genuinely
        # new — replaying a previously seen nonce (a restored backup, an
        # NFS cache serving an old generation) must not resurrect a host.
        return run not in self._nonces[peer]

    def poll(self) -> list:
        """Refresh peer state; returns the (epoch-latched) lost list."""
        now = deadline_clock()
        with self._lock:
            for peer in self.peers:
                if peer in self._lost:
                    continue
                beat = self._read_beat(peer)
                (last_run, last_seq), last_t = self._seen[peer]
                if self._advanced(peer, beat):
                    self._seen[peer] = (beat, now)
                    if beat[0] is not None:
                        self._nonces[peer].add(beat[0])
                elif now - last_t > self.timeout_s:
                    self._lost.add(peer)
                    _loss_seen.set()
                    log.warning(
                        "pod: host %d declared lost in epoch %d (no "
                        "heartbeat advance in %.1fs, last seq %d)", peer,
                        self.epoch, self.timeout_s, last_seq)
                    from ..observability import record_degradation

                    record_degradation(
                        "host_lost", site="coordinator",
                        detail={"process": int(peer),
                                "epoch": int(self.epoch),
                                "timeout_s": self.timeout_s,
                                "last_seq": int(last_seq)})
            return sorted(self._lost)

    def advance_epoch(self, epoch: int | None = None) -> int:
        """Open the next membership epoch: current-epoch loss latches
        clear (a host lost in epoch N may be alive in epoch N+1) and
        every peer gets a fresh grace window.  The replay guard's nonce
        memory persists across epochs — readmission requires a beat
        under a genuinely new nonce, never a stale file."""
        with self._lock:
            self.epoch = int(epoch) if epoch is not None else self.epoch + 1
            self._lost_history |= self._lost
            self._lost.clear()
            now = deadline_clock()
            for p in self.peers:
                self._seen[p] = (self._seen[p][0], now)
            return self.epoch

    def ever_lost(self) -> list:
        """Hosts declared lost in ANY epoch (observability, not latch)."""
        with self._lock:
            return sorted(self._lost_history | self._lost)

    def check(self, site: str = "") -> None:
        """Raise :class:`HostLostError` when any peer is lost."""
        lost = self.poll()
        if lost:
            raise HostLostError(lost, site=site)


class PodSupervisor:
    """One per process: this process's heartbeat writer + the peer
    monitor, and the guarded-phase wrapper that converts a dead peer's
    infinite collective hang into :class:`HostLostError`."""

    _POLL_S = 0.25

    def __init__(self, directory: str, n_processes: int, process_id: int,
                 interval_s: float | None = None,
                 timeout_s: float | None = None) -> None:
        self.directory = directory
        self.n_processes = int(n_processes)
        self.process_id = int(process_id)
        self.writer = HeartbeatWriter(directory, process_id,
                                      interval_s=interval_s)
        self.monitor = PeerMonitor(directory, n_processes, process_id,
                                   timeout_s=timeout_s)

    def start(self) -> "PodSupervisor":
        self.writer.start()
        return self

    def stop(self) -> None:
        self.writer.stop()

    def survivors(self) -> list:
        lost = set(self.monitor.poll())
        return [p for p in range(self.n_processes) if p not in lost]

    def guarded(self, fn: Callable, site: str = "pod.collective"):
        """Run a cross-host phase with host-loss supervision.

        ``fn`` runs on a daemon worker thread; while it blocks (a
        collective waiting on every peer), the monitor polls — a lost
        peer raises :class:`HostLostError` here and the hung attempt is
        abandoned (the thread cannot be killed; it is daemon and its
        result is discarded — the standard watchdog cancel semantics).
        A ``fn`` that *fails* while a peer looks dead re-raises as
        :class:`HostLostError` once the monitor confirms within the
        heartbeat timeout: a collective erroring with "connection reset"
        because its peer was SIGKILLed is a host loss, not a bug."""
        box: dict = {}

        def worker() -> None:
            try:
                box["result"] = fn()
            except BaseException as e:  # graftlint: disable=broad-except -- relayed verbatim below
                box["error"] = e

        t = threading.Thread(target=worker, daemon=True,
                             name=f"tse1m-pod:{site}")
        t.start()
        while True:
            t.join(self._POLL_S)
            if not t.is_alive():
                break
            self.monitor.check(site=site)
        if "error" in box:
            err = box["error"]
            # Fence signals relay VERBATIM, before any peer-death
            # reclassification: a worker whose lease was superseded is
            # the zombie, and when the survivors finished and exited
            # their heartbeats stop too — wrapping the
            # LeaseSupersededError into HostLostError here would send
            # the fenced writer down the failover path to re-execute
            # (the exact double-write the epoch leases exist to
            # prevent).  HostLostError likewise carries its own loss
            # evidence already.
            if isinstance(err, (LeaseSupersededError, HostLostError)):
                raise err
            # Confirm (or clear) peer death before relaying: give the
            # monitor one full timeout window to observe stalled beats.
            deadline = deadline_clock() + self.monitor.timeout_s
            while deadline_clock() < deadline:
                if self.monitor.poll():
                    break
                time.sleep(self._POLL_S)
            lost = self.monitor.poll()
            if lost:
                raise HostLostError(lost, site=site) from err
            raise err
        return box.get("result")


# -- per-run exchange-dir negotiation ---------------------------------------
#
# The pod's bulk data plane is the shared store root (the sharded store
# already requires one; see cluster/store.py) — novel-tail exchanges are
# atomic files under a PER-RUN directory, because the pod dir outlives
# runs and a slow host reading a previous run's exchange file would merge
# stale signatures silently.  The per-run name is a nonce the leader
# publishes as an atomic file stamped with its own heartbeat run id: a
# peer accepts the nonce only when that stamp matches the leader's
# CURRENT heartbeat nonce, so a previous run's nonce file (stamped with
# a dead run's heartbeat id) is rejected and the peer keeps polling.
# The plane deliberately does NOT ride the jax.distributed KV service:
# the pod path never initializes the XLA coordination service at all —
# that is what lets a survivor outlive the leader instead of being
# LOG(FATAL)ed by the coordination client's error poll.

_RUN_NONCE = "run_nonce.json"


def negotiate_run_nonce(supervisor: "PodSupervisor | None" = None,
                        pod_dir: str | None = None) -> str:
    """One hex nonce shared by every process of THIS run.

    The leader (process 0) generates it and publishes it atomically under
    the pod dir, stamped with its heartbeat run id; peers poll for a
    nonce file whose stamp matches the leader's live heartbeat, checking
    the monitor between polls so a leader that dies pre-publish raises
    :class:`HostLostError` instead of a bare timeout.  Single-process
    runs mint a local nonce.

    The nonce doubles as the run's trace id: every process pins it
    (``observability.tracing.adopt_trace``), so spans from all workers —
    and the trace context stamped into heartbeats and ``fs_exchange``
    payloads — share one id without any collector."""
    nonce = _negotiate_run_nonce(supervisor, pod_dir)
    from ..observability.tracing import adopt_trace

    adopt_trace(nonce)
    return nonce


def _negotiate_run_nonce(supervisor: "PodSupervisor | None",
                         pod_dir: str | None) -> str:
    if supervisor is None or supervisor.n_processes == 1:
        return os.urandom(8).hex()
    pod_dir = pod_dir or supervisor.directory
    path = os.path.join(pod_dir, _RUN_NONCE)
    if supervisor.process_id == 0:
        nonce = os.urandom(8).hex()
        with atomic_write(path) as f:
            json.dump({"nonce": nonce,
                       "leader_run": supervisor.writer.run_id}, f)
        return nonce
    deadline = deadline_clock() + supervisor.monitor.timeout_s * 2
    while True:
        leader_run = None
        try:
            with open(heartbeat_path(pod_dir, 0), encoding="utf-8") as f:
                leader_run = json.load(f).get("run")
        except (OSError, ValueError):
            pass
        rec = None
        try:
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            pass
        if (rec and leader_run is not None
                and rec.get("leader_run") == leader_run
                and rec.get("nonce")):
            return str(rec["nonce"])
        supervisor.monitor.check(site="pod.nonce")
        if deadline_clock() > deadline:
            raise TimeoutError(
                "pod: no run nonce from process 0 within "
                f"{supervisor.monitor.timeout_s * 2:.0f}s (it is beating "
                "but has not announced a run)")
        time.sleep(0.1)


def exchange_dir(pod_dir: str, nonce: str,
                 sweep_stale: bool = False) -> str:
    """This run's exchange directory under the pod dir; process 0 passes
    ``sweep_stale=True`` to remove dead runs' exchange dirs (runs against
    one store are sequential — a surviving dir is garbage, not a peer)."""
    if sweep_stale:
        for old in glob.glob(os.path.join(pod_dir, _XCH_PREFIX + "*")):
            if os.path.basename(old) != _XCH_PREFIX + nonce:
                shutil.rmtree(old, ignore_errors=True)
    path = os.path.join(pod_dir, _XCH_PREFIX + nonce)
    os.makedirs(path, exist_ok=True)
    return path


# -- epoch leases ------------------------------------------------------------
#
# One lease file per digest range, next to the range's directory under
# the sharded store root.  A lease is {range, epoch, owner, nonce} —
# monotonic epoch from the MembershipLedger plus the holding run's nonce;
# deliberately NO timestamps of any kind (fencing is epoch comparison on
# files every host can read, so wall-clock skew between hosts can neither
# grant nor revoke a lease).  All mutations go through write_lease's
# atomic tmp+rename (a reader never sees a torn lease), enforced by the
# graftlint watchdog-clock/lease rule.

_LEASE_FMT = "lease_{:04d}.json"


def lease_path(root: str, range_id: int) -> str:
    return os.path.join(root, _LEASE_FMT.format(int(range_id)))


def read_lease(root: str, range_id: int) -> dict | None:
    """The on-disk lease for a range, or None (absent/torn — a torn
    lease reads as absent; the next acquire rewrites it)."""
    try:
        with open(lease_path(root, range_id), encoding="utf-8") as f:
            d = json.load(f)
        return {"range": int(d["range"]), "epoch": int(d["epoch"]),
                "owner": int(d["owner"]), "nonce": str(d.get("nonce", ""))}
    except (OSError, ValueError, KeyError, TypeError):
        return None


def write_lease(root: str, range_id: int, epoch: int, owner: int,
                nonce: str) -> dict:
    """THE lease mutation seat: atomic tmp+rename only (graftlint
    enforces that no lease write bypasses this helper)."""
    rec = {"range": int(range_id), "epoch": int(epoch),
           "owner": int(owner), "nonce": str(nonce)}
    with atomic_write(lease_path(root, range_id)) as f:
        json.dump(rec, f)
    return rec


def acquire_lease(root: str, range_id: int, epoch: int, owner: int,
                  nonce: str) -> dict:
    """Take (or re-take) the range's lease at ``epoch``.

    Refuses — raises :class:`LeaseSupersededError` — when the on-disk
    lease already carries a LATER epoch (this process is the zombie: the
    pod moved on without it), or the same epoch under a different owner
    (a deal bug two writers must never paper over).  A same-epoch
    re-acquire by the same owner (a clean re-run under an unchanged
    membership) refreshes the nonce."""
    held = {"epoch": int(epoch), "owner": int(owner), "nonce": str(nonce)}
    cur = read_lease(root, range_id)
    if cur is not None:
        if cur["epoch"] > int(epoch):
            raise LeaseSupersededError(range_id, held, cur)
        if cur["epoch"] == int(epoch) and cur["owner"] != int(owner):
            raise LeaseSupersededError(range_id, held, cur)
    return write_lease(root, range_id, epoch, owner, nonce)


def verify_lease(root: str, range_id: int, epoch: int, owner: int,
                 nonce: str) -> None:
    """Prove this writer still holds the range's current-epoch lease
    (called before every append).  Anything else — a later epoch, a
    different owner, a different run's nonce, or a missing/torn lease —
    raises :class:`LeaseSupersededError`: when tenure cannot be proven,
    the writer must fence, never append."""
    held = {"epoch": int(epoch), "owner": int(owner), "nonce": str(nonce)}
    cur = read_lease(root, range_id)
    if (cur is None or cur["epoch"] != int(epoch)
            or cur["owner"] != int(owner)
            or cur["nonce"] != str(nonce)):
        raise LeaseSupersededError(range_id, held, cur)


class RangeLeaseGuard:
    """One shard writer's proof of tenure over one digest range — the
    serving plane's handle on the batch plane's epoch-lease fencing.

    Constructed by :meth:`claim` (failover: advance the epoch, fencing
    whatever writer held the range) or :meth:`acquire` (bootstrap under
    a membership-ledger deal at the ledger's epoch).  ``verify`` is the
    per-durability-point check the shard ``ServeDaemon`` calls between
    its commit fault seat and the store append: a superseded writer
    raises :class:`LeaseSupersededError` there with zero rows written."""

    def __init__(self, root: str, range_id: int, epoch: int, owner: int,
                 nonce: str) -> None:
        self.root = root
        self.range_id = int(range_id)
        self.epoch = int(epoch)
        self.owner = int(owner)
        self.nonce = str(nonce)

    @classmethod
    def claim(cls, root: str, range_id: int, owner: int,
              nonce: str | None = None) -> "RangeLeaseGuard":
        """Advance-then-acquire: take the range at the epoch AFTER the
        on-disk lease's — the replacement writer's seat.  The epoch bump
        is itself the fence: the superseded holder's next ``verify``
        sees a later epoch and self-fences."""
        nonce = nonce if nonce is not None else os.urandom(8).hex()
        cur = read_lease(root, range_id)
        epoch = (int(cur["epoch"]) + 1) if cur is not None else 1
        acquire_lease(root, range_id, epoch, owner, nonce)
        return cls(root, range_id, epoch, owner, nonce)

    @classmethod
    def acquire(cls, root: str, range_id: int, epoch: int, owner: int,
                nonce: str) -> "RangeLeaseGuard":
        """Bootstrap under a :class:`MembershipLedger` deal: take the
        range at the ledger's epoch (raises if a later epoch already
        owns it — this process is the zombie)."""
        acquire_lease(root, range_id, epoch, owner, nonce)
        return cls(root, range_id, epoch, owner, nonce)

    def verify(self) -> None:
        verify_lease(self.root, self.range_id, self.epoch, self.owner,
                     self.nonce)


# -- membership ledger -------------------------------------------------------


_MEMBERSHIP = "membership.json"


class MembershipLedger:
    """``membership.json`` under the pod dir: monotonic epoch, member
    set, and the digest-range → owner deal.

    Epochs advance on loss AND on recovery, and the re-deal is elastic:
    a range keeps its owner whenever that owner is still a member and
    not over the balanced target — only orphaned ranges (owner left) and
    the minimal rebalance onto re-admitted members move, so labels and
    warm state stay put for every unmoved range.  The file is written
    atomically by exactly one process per advance (the leader at
    bootstrap, the failover survivor mid-run); peers adopt it via
    :meth:`wait_for`."""

    def __init__(self, pod_dir: str, n_ranges: int) -> None:
        self.pod_dir = pod_dir
        self.n_ranges = int(n_ranges)
        self.path = os.path.join(pod_dir, _MEMBERSHIP)
        os.makedirs(pod_dir, exist_ok=True)

    def load(self) -> dict | None:
        """The current membership record, or None (absent/torn)."""
        try:
            with open(self.path, encoding="utf-8") as f:
                d = json.load(f)
            return {"epoch": int(d["epoch"]), "nonce": str(d.get("nonce", "")),
                    "members": sorted(int(m) for m in d["members"]),
                    "owners": {int(k): int(v)
                               for k, v in d["owners"].items()},
                    "moved": sorted(int(r) for r in d.get("moved", [])),
                    "prev_members": sorted(
                        int(m) for m in d.get("prev_members", []))}
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _write(self, rec: dict) -> None:
        with atomic_write(self.path) as f:
            json.dump(rec, f)

    @staticmethod
    def _deal(prior_owners: dict | None, members: list,
              n_ranges: int) -> tuple[dict, list]:
        """Elastic re-deal: (owners, moved ranges).  Keeps every range
        with its prior owner while that owner is a member under the
        balanced target ceil(n_ranges / len(members)); orphaned and
        overflow ranges go to the least-loaded member (ties to the
        lowest pid).  Deterministic — every host computes the same deal."""
        members = sorted(int(m) for m in members)
        target = -(-int(n_ranges) // len(members))
        counts = {m: 0 for m in members}
        owners: dict = {}
        pool = []
        for r in range(int(n_ranges)):
            o = (prior_owners or {}).get(r)
            if o in counts and counts[o] < target:
                owners[r] = o
                counts[o] += 1
            else:
                pool.append(r)
        moved = []
        for r in pool:
            m = min(members, key=lambda p: (counts[p], p))
            owners[r] = m
            counts[m] += 1
            if (prior_owners or {}).get(r) != m:
                moved.append(r)
        return owners, moved

    def bootstrap(self, members: list, nonce: str) -> dict:
        """Open this run's membership: reuse the prior epoch and deal
        when the member set is unchanged, otherwise advance (a member
        set that grew is a recovery — the re-admitted host takes ranges
        back at the epoch boundary via the elastic re-deal)."""
        prior = self.load()
        members = sorted(int(m) for m in members)
        if prior is not None and prior["members"] == members:
            rec = {**prior, "nonce": str(nonce), "moved": []}
            self._write(rec)
            return rec
        if prior is None:
            reason = "bootstrap"
        elif set(members) - set(prior["members"]):
            reason = "host_readmitted"
        else:
            reason = "membership_change"
        return self._advance(prior, members, nonce, reason)

    def advance(self, members: list, nonce: str, reason: str) -> dict:
        """Force the next epoch (the failover survivor's seat)."""
        return self._advance(self.load(), sorted(int(m) for m in members),
                             nonce, reason)

    def _advance(self, prior: dict | None, members: list, nonce: str,
                 reason: str) -> dict:
        epoch = int(prior["epoch"]) + 1 if prior is not None else 0
        owners, moved = self._deal(
            prior.get("owners") if prior is not None else None,
            members, self.n_ranges)
        if prior is None:
            moved = []  # a fresh deal reassigns nothing
        rec = {"epoch": epoch, "nonce": str(nonce), "members": members,
               "owners": owners, "moved": sorted(moved),
               "prev_members": (prior or {}).get("members", [])}
        self._write(rec)
        if prior is not None:
            from ..observability import record_degradation

            record_degradation(
                "epoch_advance", site="coordinator.membership",
                detail={"epoch": epoch, "reason": reason,
                        "members": members, "moved": sorted(moved)})
            for p in sorted(set(members) - set(prior["members"])):
                record_degradation(
                    "host_readmitted", site="coordinator.membership",
                    detail={"process": int(p), "epoch": epoch})
            log.warning("pod membership epoch %d (%s): members %s, "
                        "moved ranges %s", epoch, reason, members,
                        sorted(moved))
        return rec

    def wait_for(self, nonce: str, monitor: "PeerMonitor | None" = None,
                 timeout_s: float | None = None) -> dict:
        """Adopt the membership record the leader wrote for THIS run
        (matched by nonce), polling the monitor so a leader death here
        raises :class:`HostLostError` instead of hanging."""
        budget = (timeout_s if timeout_s is not None
                  else (monitor.timeout_s * 2 if monitor is not None
                        else heartbeat_timeout_s() * 2))
        deadline = deadline_clock() + budget
        while True:
            rec = self.load()
            if rec is not None and rec["nonce"] == str(nonce):
                return rec
            if monitor is not None:
                monitor.check(site="pod.membership")
            if deadline_clock() > deadline:
                raise TimeoutError(
                    f"pod: no membership record for nonce {nonce} within "
                    f"{budget:.0f}s (the leader is beating but has not "
                    "published the epoch deal)")
            time.sleep(0.1)


__all__ = ["HeartbeatWriter", "HostLostError", "LeaseSupersededError",
           "MembershipLedger", "PeerMonitor", "PodSupervisor",
           "RangeLeaseGuard",
           "acquire_lease", "exchange_dir", "hard_exit_if_host_lost",
           "heartbeat_interval_s", "heartbeat_path", "heartbeat_timeout_s",
           "lease_path", "negotiate_run_nonce", "read_lease",
           "resume_heartbeats", "saw_host_loss", "suspend_heartbeats",
           "verify_lease", "write_lease"]
