"""Pod-scale supervision: peer heartbeats + coordinator-driven failover.

The PR 5 watchdog bounds every stage *inside* one process; the failure it
cannot see is a whole host going away — SIGKILLed by the scheduler, wedged
in a kernel hang, or partitioned off the network.  Under `jax.distributed`
that failure is maximally silent: the survivors block forever inside the
next collective, because the collective cannot know its peer is never
coming.  This module turns "lost host" into a first-class, recoverable
failure, the same shape fault-tolerant multi-host training stacks use
(elastic membership + re-execution of the lost worker's partition, the
MapReduce recipe):

- :class:`HeartbeatWriter` — every process beats a monotonically
  increasing ``seq`` into ``hb_<pid>.json`` under a shared directory
  (atomic tmp+rename, so a reader never sees a torn beat).  Beats carry
  NO timestamps: wall clocks are not comparable across hosts, and the
  watchdog plane forbids them anyway (graftlint ``watchdog-clock``).
- :class:`PeerMonitor` — declares a peer lost when its ``seq`` has not
  advanced within ``timeout_s`` measured on the LOCAL
  :func:`~.watchdog.deadline_clock`.  Only local monotonic deltas are
  ever compared, so NTP steps on either host cannot fire or starve the
  monitor.
- :class:`PodSupervisor` — owns both, plus :meth:`guarded`: run a
  cross-host phase (a collective, a barrier) on a reaper-able thread
  while polling the monitor — a dead peer turns an infinite collective
  hang into :class:`HostLostError` within one heartbeat timeout.  The
  caller (cli's pod cluster step) then fails over: the lowest-id
  survivor re-executes solo with the lost host's digest range
  reassigned (`cluster/store.ShardedSignatureStore`), every other
  survivor exits loudly.  Every declaration/reassignment/failover fires
  a degradation event into the merged pod ``run_manifest.json``.

The fault plane's ``hostloss`` kind (resilience/faults.py) wedges a host
for the chaos tests: it calls :func:`suspend_heartbeats` then sleeps at a
production seat — the process is alive but silent, exactly the failure
mode heartbeats exist to catch (``kill`` already covers the dead-process
variant).
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import threading
import time
from typing import Callable

from ..utils.atomic import atomic_write
from ..utils.logging import get_logger
from .watchdog import deadline_clock

log = get_logger("resilience.coordinator")

_HB_PREFIX = "hb_"
_XCH_PREFIX = "xch_"


def heartbeat_interval_s() -> float:
    return float(os.environ.get("TSE1M_HEARTBEAT_INTERVAL_S", 0.5))


def heartbeat_timeout_s() -> float:
    return float(os.environ.get("TSE1M_HEARTBEAT_TIMEOUT_S", 10.0))


class HostLostError(RuntimeError):
    """Peer host(s) declared lost (heartbeat timeout / dead collective)."""

    def __init__(self, lost: list, site: str = ""):
        self.lost = sorted(int(p) for p in lost)
        self.site = site
        super().__init__(
            f"{site or 'pod'}: host(s) {self.lost} declared lost — no "
            "heartbeat within the timeout; their digest ranges reassign "
            "to survivors and their rows recompute")


# The fault plane's hostloss kind flips this: a wedged host stays alive
# but stops beating, so peers declare it lost through the production
# heartbeat path (zero test-only branches in the monitor).
_suspended = threading.Event()

# Latches when ANY monitor in this process declares a host lost: the
# jax.distributed runtime is poisoned from that moment (its Shutdown
# barrier can never pass without the dead task) and the process must
# leave through hard_exit_if_host_lost.
_loss_seen = threading.Event()


def saw_host_loss() -> bool:
    return _loss_seen.is_set()


# Failover scope note: in-process failover covers lost WORKERS only.
# Process 0 hosts the XLA coordination service; when it dies, every
# survivor's error-poll thread observes the closed socket and LOG(FATAL)s
# the process within ~1 s — faster than any heartbeat could detect, and
# unstoppable from Python.  A lost leader therefore fences the whole pod
# (every worker exits), and recovery is the scheduler's respawn: a fresh
# run against the same sharded store root inherits every digest range and
# recomputes whatever the dead pod never appended (probe-as-miss), so the
# respawned labels equal an uninterrupted run's (pinned by the
# leader-death chaos test).


def hard_exit_if_host_lost(code: int) -> int:
    """Exit NOW via ``os._exit`` when this run declared a host lost (and
    is actually distributed); otherwise return ``code`` for the normal
    return path.

    Once a pod peer is dead, the XLA coordination client cannot
    disconnect: ``client.shutdown()`` waits at a Shutdown barrier the
    dead task will never join and LOG(FATAL)s the survivor — an exit
    code of -SIGABRT from the process that *survived* the failover.
    All durable state (manifests, store shards, labels) is written with
    atomic renames before the callers invoke this, so skipping the
    interpreter's atexit teardown loses nothing."""
    import jax

    if _loss_seen.is_set() and jax.process_count() > 1:
        log.warning("pod: host loss was declared this run — exiting "
                    "without jax.distributed teardown (code %d)", code)
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(code)
    return code


def suspend_heartbeats() -> None:
    _suspended.set()


def resume_heartbeats() -> None:
    _suspended.clear()


def heartbeat_path(directory: str, process_id: int) -> str:
    return os.path.join(directory, f"{_HB_PREFIX}{int(process_id):03d}.json")


class HeartbeatWriter:
    """Beat ``seq`` into this process's heartbeat file from a daemon
    thread.  Atomic writes only — a peer's read never races a beat."""

    def __init__(self, directory: str, process_id: int,
                 interval_s: float | None = None) -> None:
        self.directory = directory
        self.process_id = int(process_id)
        self.interval_s = (heartbeat_interval_s()
                           if interval_s is None else float(interval_s))
        self._lock = threading.Lock()
        self._seq = 0
        # Per-run nonce: a fresh run restarts seq at 1, which a stale
        # heartbeat file from a previous run (higher seq) would otherwise
        # mask forever — any nonce change counts as an advance.
        self._run_id = os.urandom(8).hex()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        os.makedirs(directory, exist_ok=True)

    def beat_once(self) -> int:
        with self._lock:
            self._seq += 1
            seq = self._seq
        with atomic_write(heartbeat_path(self.directory,
                                         self.process_id)) as f:
            json.dump({"process_id": self.process_id, "seq": seq,
                       "run": self._run_id}, f)
        return seq

    def _run(self) -> None:
        while not self._stop.is_set():
            if not _suspended.is_set():
                try:
                    self.beat_once()
                except OSError as e:
                    log.warning("heartbeat write failed (%s); peers may "
                                "declare this host lost", e)
            self._stop.wait(self.interval_s)

    def start(self) -> "HeartbeatWriter":
        if self._thread is None:
            self.beat_once()  # visible before any peer's grace expires
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"tse1m-heartbeat:{self.process_id}")
            with self._lock:
                self._thread = t
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()


class PeerMonitor:
    """Track peers' heartbeat seqs; declare lost on no advance within
    ``timeout_s`` of the LOCAL deadline_clock.  Lost declarations latch —
    a host that resumes beating after the declaration stays lost for this
    run (its range was already reassigned; let the next run readmit it)."""

    def __init__(self, directory: str, n_processes: int, process_id: int,
                 timeout_s: float | None = None) -> None:
        self.directory = directory
        self.process_id = int(process_id)
        self.peers = [p for p in range(int(n_processes))
                      if p != self.process_id]
        self.timeout_s = (heartbeat_timeout_s()
                          if timeout_s is None else float(timeout_s))
        now = deadline_clock()
        self._lock = threading.Lock()
        # peer -> (last (run, seq) seen, deadline_clock() at last advance).
        # Absent files get the full grace window from monitor start.
        self._seen = {p: ((None, -1), now) for p in self.peers}
        self._lost: set[int] = set()

    def _read_beat(self, peer: int):
        """(run nonce, seq) of the peer's last beat, or None."""
        try:
            with open(heartbeat_path(self.directory, peer),
                      encoding="utf-8") as f:
                d = json.load(f)
            return (d.get("run"), int(d["seq"]))
        except (OSError, ValueError, KeyError):
            return None

    def poll(self) -> list:
        """Refresh peer state; returns the (latched) lost list."""
        now = deadline_clock()
        with self._lock:
            for peer in self.peers:
                if peer in self._lost:
                    continue
                beat = self._read_beat(peer)
                (last_run, last_seq), last_t = self._seen[peer]
                advanced = beat is not None and (
                    beat[0] != last_run or beat[1] > last_seq)
                if advanced:
                    self._seen[peer] = (beat, now)
                elif now - last_t > self.timeout_s:
                    self._lost.add(peer)
                    _loss_seen.set()
                    log.warning(
                        "pod: host %d declared lost (no heartbeat advance "
                        "in %.1fs, last seq %d)", peer, self.timeout_s,
                        last_seq)
                    from ..observability import record_degradation

                    record_degradation(
                        "host_lost", site="coordinator",
                        detail={"process": int(peer),
                                "timeout_s": self.timeout_s,
                                "last_seq": int(last_seq)})
            return sorted(self._lost)

    def check(self, site: str = "") -> None:
        """Raise :class:`HostLostError` when any peer is lost."""
        lost = self.poll()
        if lost:
            raise HostLostError(lost, site=site)


class PodSupervisor:
    """One per process: this process's heartbeat writer + the peer
    monitor, and the guarded-phase wrapper that converts a dead peer's
    infinite collective hang into :class:`HostLostError`."""

    _POLL_S = 0.25

    def __init__(self, directory: str, n_processes: int, process_id: int,
                 interval_s: float | None = None,
                 timeout_s: float | None = None) -> None:
        self.directory = directory
        self.n_processes = int(n_processes)
        self.process_id = int(process_id)
        self.writer = HeartbeatWriter(directory, process_id,
                                      interval_s=interval_s)
        self.monitor = PeerMonitor(directory, n_processes, process_id,
                                   timeout_s=timeout_s)

    def start(self) -> "PodSupervisor":
        self.writer.start()
        return self

    def stop(self) -> None:
        self.writer.stop()

    def survivors(self) -> list:
        lost = set(self.monitor.poll())
        return [p for p in range(self.n_processes) if p not in lost]

    def guarded(self, fn: Callable, site: str = "pod.collective"):
        """Run a cross-host phase with host-loss supervision.

        ``fn`` runs on a daemon worker thread; while it blocks (a
        collective waiting on every peer), the monitor polls — a lost
        peer raises :class:`HostLostError` here and the hung attempt is
        abandoned (the thread cannot be killed; it is daemon and its
        result is discarded — the standard watchdog cancel semantics).
        A ``fn`` that *fails* while a peer looks dead re-raises as
        :class:`HostLostError` once the monitor confirms within the
        heartbeat timeout: a collective erroring with "connection reset"
        because its peer was SIGKILLed is a host loss, not a bug."""
        box: dict = {}

        def worker() -> None:
            try:
                box["result"] = fn()
            except BaseException as e:  # graftlint: disable=broad-except -- relayed verbatim below
                box["error"] = e

        t = threading.Thread(target=worker, daemon=True,
                             name=f"tse1m-pod:{site}")
        t.start()
        while True:
            t.join(self._POLL_S)
            if not t.is_alive():
                break
            self.monitor.check(site=site)
        if "error" in box:
            err = box["error"]
            # Confirm (or clear) peer death before relaying: give the
            # monitor one full timeout window to observe stalled beats.
            deadline = deadline_clock() + self.monitor.timeout_s
            while deadline_clock() < deadline:
                if self.monitor.poll():
                    break
                time.sleep(self._POLL_S)
            lost = self.monitor.poll()
            if lost:
                raise HostLostError(lost, site=site) from err
            raise err
        return box.get("result")


# -- per-run exchange-dir negotiation ---------------------------------------
#
# The pod's bulk data plane is the shared store root (the sharded store
# already requires one; see cluster/store.py) — novel-tail exchanges are
# atomic files under a PER-RUN directory, because the pod dir outlives
# runs and a slow host reading a previous run's exchange file would merge
# stale signatures silently.  The per-run name comes from a nonce process
# 0 publishes through the jax.distributed key-value service: that service
# lives inside process 0's run and dies with it, so a nonce read from it
# can never be a previous run's — staleness-free by construction.  (The
# heartbeat plane deliberately does NOT ride the same service: when
# process 0 dies, the KV store dies with it, and the survivors' monitor —
# plain files — is what must keep working to declare the loss.)


def _kv_client():
    from jax._src import distributed  # run-scoped KV service

    return distributed.global_state.client


_NONCE_KEY = "tse1m/pod/run_nonce"


def negotiate_run_nonce(supervisor: "PodSupervisor | None" = None) -> str:
    """One hex nonce shared by every process of THIS run.

    Process 0 generates and publishes it; peers block on the KV get in
    short slices, polling the heartbeat monitor between them so a process
    0 that dies pre-publish raises :class:`HostLostError` instead of a
    bare timeout.  Single-process runs mint a local nonce."""
    if supervisor is None or supervisor.n_processes == 1:
        return os.urandom(8).hex()
    if supervisor.process_id == 0:
        nonce = os.urandom(8).hex()
        _kv_client().key_value_set(_NONCE_KEY, nonce)
        return nonce
    deadline = deadline_clock() + supervisor.monitor.timeout_s * 2
    while True:
        try:
            return _kv_client().blocking_key_value_get(_NONCE_KEY, 1000)
        except RuntimeError as e:  # XlaRuntimeError: deadline exceeded
            supervisor.monitor.check(site="pod.nonce")
            if deadline_clock() > deadline:
                raise TimeoutError(
                    "pod: no run nonce from process 0 within "
                    f"{supervisor.monitor.timeout_s * 2:.0f}s (it is "
                    "beating but has not announced a run)") from e


def exchange_dir(pod_dir: str, nonce: str,
                 sweep_stale: bool = False) -> str:
    """This run's exchange directory under the pod dir; process 0 passes
    ``sweep_stale=True`` to remove dead runs' exchange dirs (runs against
    one store are sequential — a surviving dir is garbage, not a peer)."""
    if sweep_stale:
        for old in glob.glob(os.path.join(pod_dir, _XCH_PREFIX + "*")):
            if os.path.basename(old) != _XCH_PREFIX + nonce:
                shutil.rmtree(old, ignore_errors=True)
    path = os.path.join(pod_dir, _XCH_PREFIX + nonce)
    os.makedirs(path, exist_ok=True)
    return path


__all__ = ["HeartbeatWriter", "HostLostError", "PeerMonitor",
           "PodSupervisor", "exchange_dir", "hard_exit_if_host_lost",
           "heartbeat_interval_s", "heartbeat_path", "heartbeat_timeout_s",
           "negotiate_run_nonce", "resume_heartbeats", "saw_host_loss",
           "suspend_heartbeats"]
