"""graftlint's interprocedural passes: the pod-protocol verifier.

Whole-program analyses over the :mod:`graph` ProjectGraph, each a
fixed-point dataflow over the resolved call graph.  Every finding
carries a **witness chain** — the call path that proves it — surfaced
by ``python -m tse1m_tpu.lint --why RULE:path:line``.

- **taint** (extends ``sql-interp`` / ``retry-bypass``): SQL text and
  cursor/HTTP capability follow calls across files.  A parameter that
  flows into an ``execute``-family sink (in any callee, any file) makes
  its function a SQL sink too, so an f-string built two calls away from
  ``cursor.execute`` is flagged at the point the taint enters the
  chain.  A cursor passed into a helper makes the helper's
  ``p.execute(...)`` a raw seat even when the parameter isn't named
  ``cur``; internals of the blessed DB/transport files reached from
  outside their public wrappers (``DB.*`` / ``HttpFetcher.*``) are
  retry bypasses.
- **lease-fence**: every call path that reaches a per-range
  ``SignatureStore.append`` / ``_append_rows`` under a sharded root
  (receiver obtained via ``range_store``) must be dominated by a
  ``verify_lease`` / ``acquire_lease``-providing call; every
  ``membership.json`` / ``lease_*`` / ``hb_*`` mutation must go through
  ``MembershipLedger._write`` / ``write_lease`` /
  ``HeartbeatWriter.beat_once``; and ``LeaseSupersededError`` must
  PROPAGATE — a broad handler over a may-raise body absorbs the fence
  signal unless the original exception provably escapes (bare ``raise``
  or ``raise e``; ``raise X(...) from e`` converts the signal away and
  does not count).
- **lock-order**: the global lock-acquisition graph (``with self._lock``
  sites, canonicalized per class/module, closed over resolved calls)
  must be acyclic, and a non-reentrant Lock must never be re-acquired
  under itself.
- **fault-seat-drift**: the ``fault_point(...)`` seats declared in
  production code, the fault kinds in ``resilience/faults.py``, and the
  ``PRODUCTION_SEATS`` inventory in ``tests/ci_fault_matrix.py`` must
  agree — a new seat without a matrix entry, a dead matrix entry, or an
  unknown fault kind fails lint.
- **spec-conformance** / **verb-dispatch-drift** (graftspec's static
  layer, see the section comment above their passes): every protocol
  spec action maps to a declared code seat and vice versa, and the
  four serve dispatch surfaces agree exactly with the spec's verb
  alphabets.

Dynamic calls (``fn()`` on a bare callable parameter) stay opaque: the
passes never guess, so a finding here is a real protocol hole, not a
resolution artifact.
"""

from __future__ import annotations

import ast
import os

from .engine import Finding
from .graph import ProjectGraph

MATRIX_BASENAME = "ci_fault_matrix.py"
MATRIX_DEFAULT = os.path.join("tests", MATRIX_BASENAME)

# Blessed wrapper classes: calls INTO their methods are the sanctioned
# way to perform HTTP / DB I/O, so capability propagation stops there.
_BOUNDARY_CLASSES = ("DB", "HttpFetcher")
_BLESSED_IO_FILES = ("tse1m_tpu/collect/transport.py",
                     "tse1m_tpu/db/connection.py",
                     "tse1m_tpu/db/pglib.py")
_DB_LAYER = ("tse1m_tpu/db/connection.py", "tse1m_tpu/db/pglib.py")

# The only functions allowed to mutate the pod's protocol files.
_PROTOCOL_MUTATORS = ("write_lease", "MembershipLedger._write",
                      "HeartbeatWriter.beat_once")

_FENCE_LEAVES = ("verify_lease", "acquire_lease")
_SINK_LEAVES = ("append", "_append_rows")


def _leaf(qual: str) -> str:
    return qual.rsplit(".", 1)[-1]


def _cls_leaf(qual: str) -> str:
    """'pkg.mod.Cls.meth' -> 'Cls.meth' (best effort)."""
    parts = qual.split(".")
    return ".".join(parts[-2:]) if len(parts) >= 2 else qual


def _fmt_edge(graph: ProjectGraph, caller: str, call: dict,
              callee: str) -> str:
    return f"{graph.site(caller, call)} {_cls_leaf(caller)} -> " \
        f"{_cls_leaf(callee)}"


def _chain_witness(graph: ProjectGraph, chain: list) -> list:
    return [_fmt_edge(graph, q, c, t) for q, c, t in chain]


def _finding(graph: ProjectGraph, rule: str, qual: str, line: int,
             col: int, message: str, witness: list | None = None
             ) -> Finding:
    f = Finding(rule=rule, path=graph.fn_file.get(qual, "?"), line=line,
                col=col, message=message)
    f.witness = list(witness or [])
    return f


def _effective_params(fn: dict) -> list:
    params = list(fn["params"])
    if params and params[0] == "self" and (fn.get("cls")
                                           or "." in fn["qual"]):
        return params[1:]
    return params


def _arg_for_param(fn_callee: dict, call: dict, param: str):
    """The arg fact bound to ``param`` at this call site, or None."""
    kw = call.get("kwargs", {})
    if param in kw:
        return kw[param]
    params = _effective_params(fn_callee)
    args = call.get("args", [])
    try:
        i = params.index(param)
    except ValueError:
        return None
    return args[i] if i < len(args) else None


# -- taint: sql-interp + retry-bypass across calls ---------------------------


def _is_cursor_expr(fact: dict) -> bool:
    if fact.get("kind") == "call":
        return fact.get("callee", "").rsplit(".", 1)[-1] == "cursor"
    if fact.get("kind") == "var":
        return fact.get("type", "").rsplit(".", 1)[-1] == "cursor"
    return False


def _is_boundary(graph: ProjectGraph, qual: str) -> bool:
    """A blessed wrapper entry: DB.* / HttpFetcher.* methods (including
    their nested closures)."""
    fn = graph.functions.get(qual)
    while fn is not None:
        if fn.get("cls") in _BOUNDARY_CLASSES:
            return True
        parent = fn.get("parent")
        fn = graph.functions.get(parent) if parent else None
    return False


def taint_pass(graph: ProjectGraph) -> list:
    findings: list[Finding] = []

    # ---- SQL-text parameter summaries (backward fixed point) ----
    # sql_params[qual][param] = (sink description, next hop) for witness
    sql_params: dict[str, dict] = {}
    for qual, fn in graph.functions.items():
        for call in fn["calls"]:
            if "exec_recv" not in call:
                continue
            args = call.get("args", [])
            if args and args[0].get("kind") == "param":
                sql_params.setdefault(qual, {})[args[0]["name"]] = {
                    "line": call["line"], "next": None,
                    "seat": f"{graph.site(qual, call)} "
                            f"{call['callee']}(...)"}
    changed = True
    while changed:
        changed = False
        for qual, fn in graph.functions.items():
            for target, call in graph.calls.get(qual, ()):
                tparams = sql_params.get(target)
                if not tparams:
                    continue
                callee_fn = graph.functions.get(target)
                if callee_fn is None:
                    continue
                for tparam in list(tparams):
                    fact = _arg_for_param(callee_fn, call, tparam)
                    if fact and fact.get("kind") == "param":
                        mine = sql_params.setdefault(qual, {})
                        if fact["name"] not in mine:
                            mine[fact["name"]] = {
                                "line": call["line"],
                                "next": (target, tparam),
                                "seat": None}
                            changed = True

    def sql_witness(start_qual: str, param: str) -> list:
        out = []
        qual, p = start_qual, param
        for _ in range(12):
            info = sql_params.get(qual, {}).get(p)
            if info is None:
                break
            if info["next"] is None:
                out.append(f"{info['seat']}  [raw SQL execution]")
                break
            nq, np_ = info["next"]
            out.append(f"{graph.fn_file.get(qual, '?')}:{info['line']} "
                       f"{_cls_leaf(qual)} passes `{p}` -> "
                       f"{_cls_leaf(nq)}(`{np_}`)")
            qual, p = nq, np_
        return out

    # Tainted SQL entering a cross-function sink: flag at the entry.
    for qual, fn in graph.functions.items():
        for target, call in graph.calls.get(qual, ()):
            tparams = sql_params.get(target)
            if not tparams:
                continue
            callee_fn = graph.functions.get(target)
            if callee_fn is None:
                continue
            for tparam in tparams:
                fact = _arg_for_param(callee_fn, call, tparam)
                if fact and fact.get("kind") == "tainted-sql":
                    wit = [f"{graph.site(qual, call)} {_cls_leaf(qual)} "
                           f"passes interpolated SQL -> "
                           f"{_cls_leaf(target)}(`{tparam}`)"]
                    wit += sql_witness(target, tparam)
                    findings.append(_finding(
                        graph, "sql-interp", qual, call["line"],
                        call["col"],
                        "interpolated SQL flows into "
                        f"`{_cls_leaf(target)}({tparam}=...)`, which "
                        "executes it "
                        f"{len(wit) - 1} call(s) away — route "
                        "identifiers through db/ident.py or bind values "
                        "as parameters (--why shows the chain)",
                        witness=wit))

    # ---- cursor capability (forward fixed point) ----
    cursor_params: dict[str, set] = {}
    changed = True
    while changed:
        changed = False
        for qual, fn in graph.functions.items():
            for target, call in graph.calls.get(qual, ()):
                callee_fn = graph.functions.get(target)
                if callee_fn is None:
                    continue
                for param in _effective_params(callee_fn):
                    fact = _arg_for_param(callee_fn, call, param)
                    if fact is None:
                        continue
                    is_cur = _is_cursor_expr(fact) or (
                        fact.get("kind") == "param"
                        and fact["name"] in cursor_params.get(qual, ()))
                    if is_cur and param not in cursor_params.setdefault(
                            target, set()):
                        cursor_params[target].add(param)
                        changed = True
    for qual, params in cursor_params.items():
        fn = graph.functions[qual]
        path = graph.fn_file[qual]
        if path in _DB_LAYER:
            continue
        for call in fn["calls"]:
            if call.get("exec_recv") in params:
                rev = graph.rev_calls.get(qual, [])
                wit = [_fmt_edge(graph, cq, cc, qual)
                       for cq, cc in rev[:3]]
                findings.append(_finding(
                    graph, "retry-bypass", qual, call["line"],
                    call["col"],
                    f"laundered raw cursor execute: `{call['exec_recv']}`"
                    " is a DB cursor passed in by a caller — this "
                    "bypasses the DB retry/reconnect engine; use "
                    "DB.execute/query/run_transaction",
                    witness=wit))

    # ---- raw-I/O internals of blessed files reached from outside ----
    raw: set = set()
    raw_seat: dict[str, str] = {}
    for qual, fn in graph.functions.items():
        path = graph.fn_file[qual]
        for call in fn["calls"]:
            callee = call["callee"]
            leaf = _leaf(callee)
            head = callee.split(".", 1)[0]
            seat = None
            if head == "requests" and path != _BLESSED_IO_FILES[0]:
                seat = f"requests.{leaf}"
            elif leaf == "urlopen":
                seat = "urlopen"
            elif "exec_recv" in call and path in _DB_LAYER:
                seat = f"{call['exec_recv']}.{leaf}"
            if seat is not None:
                raw.add(qual)
                raw_seat.setdefault(
                    qual, f"{graph.site(qual, call)} {seat}(...)")
    changed = True
    while changed:
        changed = False
        for qual in list(graph.functions):
            if qual in raw or _is_boundary(graph, qual):
                continue
            for target, call in graph.calls.get(qual, ()):
                if target in raw and not _is_boundary(graph, target):
                    raw.add(qual)
                    raw_seat[qual] = raw_seat.get(target, "?")
                    changed = True
                    break
    for qual, fn in graph.functions.items():
        path = graph.fn_file[qual]
        if path in _BLESSED_IO_FILES:
            continue
        for target, call in graph.calls.get(qual, ()):
            if graph.fn_file.get(target) in _BLESSED_IO_FILES \
                    and target in raw and not _is_boundary(graph, target):
                findings.append(_finding(
                    graph, "retry-bypass", qual, call["line"],
                    call["col"],
                    f"`{_cls_leaf(target)}` is a raw-I/O internal of "
                    f"{graph.fn_file.get(target)} — calling it directly "
                    "bypasses the retry engine's public wrappers "
                    "(DB.* / HttpFetcher.*)",
                    witness=[_fmt_edge(graph, qual, call, target),
                             raw_seat.get(target, "?")]))
    return findings


# -- lease-fence: protocol dominance + exception flow ------------------------


def _fence_providers(graph: ProjectGraph) -> set:
    providers = {q for q in graph.functions if _leaf(q) in _FENCE_LEAVES}
    changed = True
    while changed:
        changed = False
        for qual in graph.functions:
            if qual in providers:
                continue
            for target, _ in graph.calls.get(qual, ()):
                if target in providers:
                    providers.add(qual)
                    changed = True
                    break
    return providers


def _range_store_sinks(graph: ProjectGraph, fn: dict) -> list:
    """Call sites in ``fn`` that append to a per-range store of a
    sharded root: ``self.range_store(r).append`` (one-level receiver
    call) or ``st.append`` where ``st`` was assigned from a
    ``range_store`` call."""
    sinks = []
    for call in fn["calls"]:
        callee = call["callee"]
        leaf = _leaf(callee)
        if leaf not in _SINK_LEAVES:
            continue
        if callee.startswith("<call:"):
            inner = callee[6:].partition(">.")[0]
            if _leaf(inner) == "range_store":
                sinks.append(call)
            continue
        head = callee.split(".", 1)[0]
        vt = fn["var_types"].get(head, "")
        if _leaf(vt) == "range_store":
            sinks.append(call)
    return sinks


def _locally_fenced(fn: dict, graph: ProjectGraph, providers: set,
                    sink_call: dict) -> bool:
    for call in fn["calls"]:
        if call["idx"] >= sink_call["idx"]:
            continue
        target = call.get("resolved")
        if target in providers or _leaf(call["callee"]) in _FENCE_LEAVES:
            return True
    return False


def _callers_fenced(graph: ProjectGraph, providers: set, qual: str,
                    seen: set, trail: list) -> list | None:
    """None when every caller path is fenced before calling into
    ``qual``; otherwise one unfenced witness path (list of edges)."""
    if qual in seen:
        return None  # cycle: treat as fenced (some acyclic path decides)
    seen = seen | {qual}
    rev = graph.rev_calls.get(qual, [])
    if not rev:
        return list(trail)  # an entry point reached with no fence
    for caller, call in rev:
        fenced_here = False
        cfn = graph.functions[caller]
        for c in cfn["calls"]:
            if c["idx"] < call["idx"] and (
                    c.get("resolved") in providers
                    or _leaf(c["callee"]) in _FENCE_LEAVES):
                fenced_here = True
                break
        if fenced_here:
            continue
        bad = _callers_fenced(graph, providers, caller, seen,
                              [(caller, call, qual)] + trail)
        if bad is not None:
            return bad
    return None


def lease_fence_pass(graph: ProjectGraph) -> list:
    findings: list[Finding] = []
    providers = _fence_providers(graph)

    # (a) unfenced per-range appends
    for qual, fn in graph.functions.items():
        for sink in _range_store_sinks(graph, fn):
            if _locally_fenced(fn, graph, providers, sink):
                continue
            bad = _callers_fenced(graph, providers, qual, set(), [])
            wit = _chain_witness(graph, bad or [])
            wit.append(f"{graph.site(qual, sink)} {_cls_leaf(qual)} "
                       f"appends via `{sink['callee']}` UNFENCED")
            findings.append(_finding(
                graph, "lease-fence", qual, sink["line"], sink["col"],
                "per-range store append not dominated by verify_lease/"
                "acquire_lease — a superseded writer could double-write "
                "its re-dealt range; verify tenure first "
                "(store._check_lease idiom)", witness=wit))

    # (b) protocol-file mutations outside the blessed seats
    for qual, fn in graph.functions.items():
        blessed = any(qual.endswith(m) for m in _PROTOCOL_MUTATORS)
        if blessed:
            continue
        for call in fn["calls"]:
            toks = call.get("path_tokens")
            if not toks:
                continue
            writes = call.get("open_write") or \
                _leaf(call["callee"]) == "atomic_write"
            if not writes:
                continue
            findings.append(_finding(
                graph, "lease-fence", qual, call["line"], call["col"],
                f"direct mutation of pod protocol file(s) {toks} — "
                "membership/lease/heartbeat state must route through "
                "MembershipLedger / write_lease / "
                "HeartbeatWriter.beat_once so epochs stay monotonic and "
                "writes atomic",
                witness=[f"{graph.site(qual, call)} "
                         f"{_cls_leaf(qual)} writes {sorted(toks)}"]))

    # (c) LeaseSupersededError must escape broad handlers
    may_raise: dict[str, list] = {}
    for qual, fn in graph.functions.items():
        for r in fn["raises"]:
            if r["name"] == "LeaseSupersededError":
                may_raise[qual] = [
                    f"{graph.fn_file[qual]}:{r['line']} "
                    f"{_cls_leaf(qual)} raises LeaseSupersededError"]
    flagged: set = set()
    changed = True
    while changed:
        changed = False
        for qual, fn in graph.functions.items():
            for target, call in graph.calls.get(qual, ()):
                if target not in may_raise:
                    continue
                handlers = [fn["broad_handlers"][h]
                            for h in call.get("handlers", ())]
                if any(h.get("explicit_lse") for h in handlers):
                    continue  # deliberately handled in place
                absorbing = [h for h in handlers
                             if not h.get("lse_escapes")]
                if absorbing:
                    h = absorbing[0]
                    key = (qual, h["line"])
                    if key not in flagged:
                        flagged.add(key)
                        wit = [_fmt_edge(graph, qual, call, target)] + \
                            may_raise[target]
                        findings.append(_finding(
                            graph, "lease-fence", qual, h["line"], 0,
                            "broad except can absorb LeaseSupersededError"
                            " raised inside its try body — the zombie "
                            "fence signal must propagate (bare `raise` / "
                            "`raise e`; `raise X from e` converts it "
                            "away), or narrow the handler",
                            witness=wit))
                    continue
                if qual not in may_raise:
                    may_raise[qual] = [
                        _fmt_edge(graph, qual, call, target)
                    ] + may_raise[target][:4]
                    changed = True
    return findings


# -- lock-order --------------------------------------------------------------


def lock_order_pass(graph: ProjectGraph) -> list:
    findings: list[Finding] = []
    # transitive lock-acquisition summaries
    acquires: dict[str, set] = {}
    for qual, fn in graph.functions.items():
        acquires[qual] = {s["token"] for s in fn["lock_sites"]}
    changed = True
    while changed:
        changed = False
        for qual in graph.functions:
            for target, _ in graph.calls.get(qual, ()):
                extra = acquires.get(target, set()) - acquires[qual]
                if extra:
                    acquires[qual] |= extra
                    changed = True
    # edges held -> acquired (with a witness site per edge)
    edges: dict[tuple, str] = {}
    for qual, fn in graph.functions.items():
        for site in fn["lock_sites"]:
            for held in site["held"]:
                edges.setdefault(
                    (held, site["token"]),
                    f"{graph.fn_file[qual]}:{site['line']} "
                    f"{_cls_leaf(qual)} takes {site['token']} while "
                    f"holding {held}")
        for target, call in graph.calls.get(qual, ()):
            if not call["locks"]:
                continue
            for acq in acquires.get(target, ()):
                for held in call["locks"]:
                    edges.setdefault(
                        (held, acq),
                        f"{graph.site(qual, call)} {_cls_leaf(qual)} "
                        f"holds {held} and calls {_cls_leaf(target)} "
                        f"which acquires {acq}")
    # self-deadlock: re-acquiring a non-reentrant Lock under itself
    kinds: dict[str, str] = {}
    for cls_qual, crec in graph.classes.items():
        for attr in crec.get("locks", []):
            kinds[f"{cls_qual}.{attr}"] = \
                crec.get("lock_kinds", {}).get(attr, "Lock")
    for (a, b), site in sorted(edges.items()):
        if a == b and kinds.get(a, "Lock") != "RLock":
            findings.append(_lock_finding(
                graph, site, f"non-reentrant lock {a} re-acquired while "
                f"already held — guaranteed deadlock", [site]))
    # cycle detection among distinct locks
    adj: dict[str, list] = {}
    for (a, b), site in edges.items():
        if a != b:
            adj.setdefault(a, []).append((b, site))
    seen_cycles: set = set()
    for start in sorted(adj):
        stack = [(start, [start], [])]
        while stack:
            node, path, sites = stack.pop()
            for nxt, site in adj.get(node, ()):
                if nxt == start:
                    cyc = tuple(sorted(path))
                    if cyc in seen_cycles:
                        continue
                    seen_cycles.add(cyc)
                    findings.append(_lock_finding(
                        graph, sites[0] if sites else site,
                        "lock-order cycle: " + " -> ".join(
                            path + [start]) + " — two threads taking "
                        "these locks in opposite orders deadlock; pick "
                        "one global order", sites + [site]))
                elif nxt not in path and len(path) < 6:
                    stack.append((nxt, path + [nxt], sites + [site]))
    return findings


def _lock_finding(graph: ProjectGraph, anchor_site: str, message: str,
                  witness: list) -> Finding:
    path, _, line = anchor_site.split(" ", 1)[0].rpartition(":")
    f = Finding(rule="lock-order", path=path, line=int(line or 1), col=0,
                message=message)
    f.witness = witness
    return f


# -- fault-seat-drift --------------------------------------------------------


def _matrix_inventory(matrix_abspath: str):
    """(seats dict name -> {kinds, line}, parse error or None)."""
    try:
        with open(matrix_abspath, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=matrix_abspath)
    except (OSError, SyntaxError) as e:
        return None, str(e)
    for node in tree.body:
        if not (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "PRODUCTION_SEATS"
                and isinstance(node.value, ast.Dict)):
            continue
        seats = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            kinds: list = []
            if isinstance(v, ast.Dict):
                for vk, vv in zip(v.keys, v.values):
                    if (isinstance(vk, ast.Constant)
                            and vk.value == "kinds"
                            and isinstance(vv, (ast.Tuple, ast.List))):
                        kinds = [e.value for e in vv.elts
                                 if isinstance(e, ast.Constant)]
            seats[k.value] = {"kinds": kinds, "line": k.lineno}
        return seats, None
    return None, "no PRODUCTION_SEATS dict"


def _declared_kinds(graph: ProjectGraph) -> set:
    """The fault kinds ``resilience/faults.py`` (or a fixture ``faults``
    module) declares in its ``_KINDS`` tuple."""
    for path, facts in graph.facts.items():
        if facts["module"].split(".")[-1] != "faults":
            continue
        kinds = facts["constants"].get("_KINDS")
        if isinstance(kinds, list):
            return set(kinds)
    return set()


def _production_sites(graph: ProjectGraph):
    """site -> (qual, call) for every fault_point seat; plus findings
    for seats whose name cannot be resolved to literals."""
    sites: dict[str, tuple] = {}
    findings: list[Finding] = []
    for qual, fn in graph.functions.items():
        for call in fn["calls"]:
            if "fault_site" in call:
                sites.setdefault(call["fault_site"], (qual, call))
            elif "fault_site_param" in call:
                param = call["fault_site_param"]
                values = set()
                # The seat name may be a parameter of an ENCLOSING
                # function (the retry-closure idiom: fault_point(site)
                # inside attempt() inside _statement(site=...)).
                owner, ofn = qual, fn
                while ofn is not None and param not in ofn["params"]:
                    owner = ofn.get("parent")
                    ofn = graph.functions.get(owner) if owner else None
                if ofn is not None:
                    default = ofn["param_defaults"].get(param)
                    if isinstance(default, str):
                        values.add(default)
                    for caller, ccall in graph.rev_calls.get(owner, ()):
                        fact = _arg_for_param(ofn, ccall, param)
                        if fact is None:
                            continue  # caller uses the default
                        if fact.get("kind") == "const" \
                                and isinstance(fact.get("value"), str):
                            values.add(fact["value"])
                        else:
                            values.add("<dynamic>")
                if not values or "<dynamic>" in values:
                    findings.append(_finding(
                        graph, "fault-seat-drift", qual, call["line"],
                        call["col"],
                        f"fault_point seat `{param}` does not resolve "
                        "to string literals — seats must be statically "
                        "enumerable for the fault matrix"))
                for v in values - {"<dynamic>"}:
                    sites.setdefault(v, (qual, call))
    return sites, findings


def fault_seat_drift_pass(graph: ProjectGraph,
                          matrix_path: str | None = None) -> list:
    matrix_rel = matrix_path or MATRIX_DEFAULT
    matrix_abs = matrix_rel if os.path.isabs(matrix_rel) \
        else os.path.join(graph.root, matrix_rel)
    matrix_disp = os.path.relpath(matrix_abs, graph.root).replace(
        os.sep, "/")
    sites, findings = _production_sites(graph)
    if not sites:
        return findings  # nothing to check (fixture set without seats)
    seats, err = _matrix_inventory(matrix_abs)
    if seats is None:
        f = Finding(rule="fault-seat-drift", path=matrix_disp, line=1,
                    col=0,
                    message="PRODUCTION_SEATS inventory missing from "
                            f"{matrix_disp} ({err}) — the fault matrix "
                            "has no machine-checked seat list")
        f.witness = []
        return findings + [f]
    kinds = _declared_kinds(graph)
    for site, (qual, call) in sorted(sites.items()):
        if site not in seats:
            findings.append(_finding(
                graph, "fault-seat-drift", qual, call["line"],
                call["col"],
                f"fault_point seat `{site}` has no PRODUCTION_SEATS "
                f"entry in {matrix_disp} — add the seat with its fault "
                "kinds and covering test so the matrix stays the source "
                "of truth",
                witness=[f"{graph.site(qual, call)} fault_point"
                         f"(\"{site}\")"]))
    for seat, rec in sorted(seats.items()):
        if seat not in sites:
            f = Finding(rule="fault-seat-drift", path=matrix_disp,
                        line=rec["line"], col=0,
                        message=f"dead matrix seat `{seat}`: no "
                                "fault_point in production code declares "
                                "it — remove the entry or restore the "
                                "seat")
            f.witness = []
            findings.append(f)
        bad_kinds = [k for k in rec["kinds"] if kinds and k not in kinds]
        if bad_kinds:
            f = Finding(rule="fault-seat-drift", path=matrix_disp,
                        line=rec["line"], col=0,
                        message=f"matrix seat `{seat}` lists unknown "
                                f"fault kind(s) {bad_kinds} — not in "
                                "resilience/faults.py _KINDS")
            f.witness = []
            findings.append(f)
    return findings


# -- graftspec conformance: the specs are load-bearing -----------------------
#
# A protocol spec (tse1m_tpu/spec/*.py, marked by a module-level
# ``SPEC_NAME`` constant) declares one ``seat`` per action:
# ``fault:<site>`` / ``verb:<op>`` / ``call:<leaf>`` / ``model:<tag>``.
# ``spec-conformance`` holds both directions over the FileFacts graph:
# every non-model seat must resolve to real code (a production
# fault_point, a dispatch verb, a named function), and every fault
# seat in a module that binds itself to specs via ``SPEC_MODELS``
# must be claimed by one of them — dead spec actions and unmodeled
# fault seats both fail lint.  ``verb-dispatch-drift`` is the verb
# alphabet's exact-agreement check across all four serve surfaces.

_VERB_SURFACES = (
    # (alphabet constant, class leaf, method, how verbs are read)
    ("SERVER_VERBS", "ServeServer", "_dispatch_op", "str_eqs"),
    ("ROUTER_VERBS", "RouterServer", "_dispatch_op", "str_eqs"),
    ("FORWARD_VERBS", "LocalTransport", "__call__", "str_eqs"),
    ("CLIENT_VERBS", "ServeClient", None, "request"),
)


def _spec_modules(graph: ProjectGraph) -> dict:
    """spec name -> (path, facts) for every module declaring a
    ``SPEC_NAME`` string constant."""
    out: dict = {}
    for path, facts in sorted(graph.facts.items()):
        name = facts["constants"].get("SPEC_NAME")
        if isinstance(name, str):
            out.setdefault(name, (path, facts))
    return out


def _spec_actions(facts: dict) -> list:
    """(qual, call, action_name or None, seat) for every ``Action(...)``
    construction in one spec module's facts."""
    out = []
    for fn in facts["functions"]:
        for call in fn["calls"]:
            if call["callee"].rsplit(".", 1)[-1] != "Action":
                continue
            args = call.get("args", [])
            name = None
            if args and args[0].get("kind") == "const":
                name = args[0].get("value")
            seat_fact = call.get("kwargs", {}).get("seat")
            out.append((fn["qual"], call, name, seat_fact))
    return out


def _dispatch_verbs(graph: ProjectGraph):
    """surface alphabet-constant name -> list of (qual, fn, verbs)."""
    surfaces: dict = {name: [] for name, _c, _m, _h in _VERB_SURFACES}
    for const, cls, meth, how in _VERB_SURFACES:
        for qual, fn in sorted(graph.functions.items()):
            if how == "str_eqs":
                if fn.get("cls") != cls or fn["name"] != meth:
                    continue
                verbs = set(fn.get("str_eqs", {}).get("op", []))
                surfaces[const].append((qual, fn, verbs))
            else:  # ServeClient: const first arg of self.request(...)
                if fn.get("cls") != cls:
                    continue
                verbs = set()
                for call in fn["calls"]:
                    if call["callee"] != "self.request":
                        continue
                    args = call.get("args", [])
                    if args and args[0].get("kind") == "const" \
                            and isinstance(args[0].get("value"), str):
                        verbs.add(args[0]["value"])
                if verbs:
                    surfaces[const].append((qual, fn, verbs))
    # A client's verbs live one per method: merge them per class.
    merged = []
    client = surfaces["CLIENT_VERBS"]
    if client:
        anchor = min(client, key=lambda t: t[1]["line"])
        allverbs = set().union(*(v for _q, _f, v in client))
        merged.append((anchor[0], anchor[1], allverbs))
    surfaces["CLIENT_VERBS"] = merged
    return surfaces


def _verbs_alphabets(graph: ProjectGraph):
    """(path, constants) of the spec verb-alphabet module, or None."""
    for path, facts in sorted(graph.facts.items()):
        if isinstance(facts["constants"].get("SERVER_VERBS"), list):
            return path, facts["constants"]
    return None


def verb_dispatch_drift_pass(graph: ProjectGraph) -> list:
    findings: list[Finding] = []
    surfaces = _dispatch_verbs(graph)
    if not any(surfaces.values()):
        return findings  # fixture set without serve surfaces
    alphabets = _verbs_alphabets(graph)
    if alphabets is None:
        qual, fn, _v = next(s for lst in surfaces.values()
                            for s in lst)
        return [_finding(
            graph, "verb-dispatch-drift", qual, fn["line"], 0,
            "serve dispatch surfaces exist but no spec verb alphabet "
            "module (SERVER_VERBS/...) is in the linted set — the "
            "verb protocol has no machine-checked source of truth")]
    alpha_path, consts = alphabets
    for const, _cls, _meth, _how in _VERB_SURFACES:
        alphabet = consts.get(const)
        for qual, fn, verbs in surfaces[const]:
            if not isinstance(alphabet, list):
                findings.append(_finding(
                    graph, "verb-dispatch-drift", qual, fn["line"], 0,
                    f"dispatch surface `{_cls_leaf(qual)}` has no "
                    f"`{const}` alphabet in {alpha_path}"))
                continue
            missing = sorted(set(alphabet) - verbs)
            extra = sorted(verbs - set(alphabet))
            if not missing and not extra:
                continue
            drift = []
            if missing:
                drift.append("missing " + ", ".join(missing))
            if extra:
                drift.append("handles undeclared "
                             + ", ".join(extra))
            findings.append(_finding(
                graph, "verb-dispatch-drift", qual, fn["line"], 0,
                f"`{_cls_leaf(qual)}` drifted from the spec verb "
                f"alphabet `{const}`: {'; '.join(drift)} — change "
                f"{alpha_path} and every surface together",
                witness=[f"{graph.site(qual)} handles: "
                         + (", ".join(sorted(verbs)) or "<none>"),
                         f"{alpha_path} {const}: "
                         + ", ".join(alphabet)]))
    return findings


def spec_conformance_pass(graph: ProjectGraph) -> list:
    findings: list[Finding] = []
    specs = _spec_modules(graph)
    if not specs:
        return findings  # no spec modules in the linted set
    sites, _seat_findings = _production_sites(graph)
    surfaces = _dispatch_verbs(graph)
    dispatch_verbs = set()
    for lst in surfaces.values():
        for _q, _f, verbs in lst:
            dispatch_verbs |= verbs
    code_leaves = {_leaf(q) for q in graph.functions}
    claimed: dict[str, set] = {}  # spec name -> fault sites it models

    def _label(name, call):
        return f"action {name!r}" if name else \
            f"action at col {call['col']}"

    for spec_name, (_path, facts) in sorted(specs.items()):
        claimed[spec_name] = set()
        for qual, call, name, seat_fact in _spec_actions(facts):
            if seat_fact is None:
                continue  # defaulted seat (model:env)
            if seat_fact.get("kind") != "const" \
                    or not isinstance(seat_fact.get("value"), str):
                findings.append(_finding(
                    graph, "spec-conformance", qual, call["line"],
                    call["col"],
                    f"spec `{spec_name}` {_label(name, call)}: seat "
                    "must be a string literal — conformance needs "
                    "statically enumerable seats"))
                continue
            seat = seat_fact["value"]
            kind, _sep, ref = seat.partition(":")
            if kind == "model":
                continue
            if kind == "fault":
                claimed[spec_name].add(ref)
                if ref not in sites:
                    findings.append(_finding(
                        graph, "spec-conformance", qual, call["line"],
                        call["col"],
                        f"dead spec action: `{spec_name}` "
                        f"{_label(name, call)} claims fault seat "
                        f"`{ref}` but no production fault_point "
                        "declares it"))
            elif kind == "verb":
                if ref not in dispatch_verbs:
                    findings.append(_finding(
                        graph, "spec-conformance", qual, call["line"],
                        call["col"],
                        f"dead spec action: `{spec_name}` "
                        f"{_label(name, call)} models verb `{ref}` "
                        "but no dispatch surface handles it"))
            elif kind == "call":
                if ref not in code_leaves:
                    findings.append(_finding(
                        graph, "spec-conformance", qual, call["line"],
                        call["col"],
                        f"dead spec action: `{spec_name}` "
                        f"{_label(name, call)} references "
                        f"`{ref}` but no such function exists"))
            else:  # unknown kind (the DSL would reject it at runtime)
                findings.append(_finding(
                    graph, "spec-conformance", qual, call["line"],
                    call["col"],
                    f"spec `{spec_name}` {_label(name, call)} has "
                    f"unknown seat kind `{kind}:` (want fault:/verb:/"
                    "call:/model:)"))

    # Reverse direction: modules that bind themselves to specs must
    # have every fault seat claimed by one of them.
    for path, facts in sorted(graph.facts.items()):
        models = facts["constants"].get("SPEC_MODELS")
        if not isinstance(models, list):
            continue
        mod_claimed: set = set()
        anchor = facts["functions"][0]
        for m in models:
            if m not in specs:
                findings.append(_finding(
                    graph, "spec-conformance", anchor["qual"], 1, 0,
                    f"{path} declares SPEC_MODELS spec `{m}` but no "
                    "module carries SPEC_NAME = "
                    f"{m!r}"))
                continue
            mod_claimed |= claimed.get(m, set())
        for fn in facts["functions"]:
            for call in fn["calls"]:
                site = call.get("fault_site")
                if site is None or site in mod_claimed:
                    continue
                findings.append(_finding(
                    graph, "spec-conformance", fn["qual"],
                    call["line"], call["col"],
                    f"fault seat `{site}` is absent from every spec "
                    f"this module declares ({', '.join(models)}) — "
                    "model the failure or drop the SPEC_MODELS "
                    "binding",
                    witness=[f"{graph.site(fn['qual'], call)} "
                             f"fault_point(\"{site}\")"]))
    return findings


# -- snapshot-publish / atomic-swap (graftrace's static layer) ---------------
#
# The serve/store planes' lock-free reads are safe only under a
# publish-then-never-mutate discipline: a snapshot (LiveClusterIndex,
# the store's _IndexSnapshot) is fully constructed, published by ONE
# reference store, and never touched again.  The runtime layers
# (trace/explore.py schedules, the lockset detector) validate what a
# run happens to execute; these passes prove the discipline statically:
#
# - ``snapshot-publish``: classes marked immutable-after-publish
#   (``@dataclass(frozen=True)`` or ``__immutable_after_publish__``)
#   must never be mutated outside their own constructors — no attribute
#   store, no in-place array op (``obj.arr[i] = ...``, ``+=``), no
#   mutating method call (``.sort()``/``.append()``/``.fill()``), no
#   numpy in-place sink (``np.minimum.at(obj.arr, ...)``, ``out=``).
#   Mutation through a helper is chased across calls: a function that
#   mutates a parameter makes every call site passing a protected
#   object a finding, with the witness chain down to the mutation seat.
# - ``atomic-swap``: attributes declared ``__publish_slots__`` (or
#   holding a protected class) may only be REBOUND whole — never
#   ``.append``-ed, item-assigned, aug-assigned, multi-target-assigned,
#   or mutated through an alias (``d = self._snap; d.base = ...``).

_INPLACE_MUTATORS = frozenset((
    "append", "extend", "insert", "remove", "clear", "sort", "reverse",
    "fill", "put", "itemset", "resize", "partition", "setdefault",
    "update", "popitem", "add", "discard", "setflags"))
_NP_HEADS = ("np", "numpy", "jnp")


def _protected_classes(graph: ProjectGraph) -> set:
    return {cq for cq, crec in graph.classes.items()
            if crec.get("frozen") or crec.get("immutable_after_publish")}


def _class_of_ctor(graph: ProjectGraph, module: str,
                   dotted: str) -> str | None:
    """Resolve a constructor / classmethod-constructor dotted expression
    (``LiveClusterIndex(...)``, ``LiveClusterIndex.empty(...)``) to the
    class qual it instantiates."""
    q = graph._resolve_dotted(module, dotted)
    if q is None:
        return None
    if q in graph.classes:
        return q
    owner = q.rsplit(".", 1)[0]
    return owner if owner in graph.classes else None


def _own_class(graph: ProjectGraph, fn: dict) -> str | None:
    cls = fn.get("cls")
    if cls is None and fn.get("parent"):
        cls = graph.functions.get(fn["parent"], {}).get("cls")
    if cls is None:
        return None
    return f"{graph.module_of(fn['qual'])}.{cls}"


def _recv_class(graph: ProjectGraph, fn: dict, recv: str,
                depth: int = 0) -> str | None:
    """Class qual of the object a dotted receiver expression denotes
    (best effort: self, self.attr, annotated params, ctor-typed vars,
    one alias hop)."""
    if depth > 3 or not recv:
        return None
    module = graph.module_of(fn["qual"])
    head, _, rest = recv.partition(".")
    if rest and rest.count(".") >= 1:
        return None  # deeper chains stay opaque
    if head == "self":
        own = _own_class(graph, fn)
        if own is None:
            return None
        if not rest:
            return own
        at = graph.classes.get(own, {}).get("attr_types", {}).get(rest)
        if at:
            return _class_of_ctor(graph, own.rsplit(".", 1)[0], at)
        return None
    if rest:
        base = _recv_class(graph, fn, head, depth + 1)
        if base is None:
            return None
        at = graph.classes.get(base, {}).get("attr_types", {}).get(rest)
        if at:
            return _class_of_ctor(graph, base.rsplit(".", 1)[0], at)
        return None
    ann = fn.get("param_annotations", {}).get(head)
    if ann:
        c = _class_of_ctor(graph, module, ann)
        if c:
            return c
    vt = fn["var_types"].get(head)
    if vt:
        c = _class_of_ctor(graph, module, vt)
        if c:
            return c
    alias = fn.get("var_aliases", {}).get(head)
    if alias and alias != recv:
        return _recv_class(graph, fn, alias, depth + 1)
    return None


def _is_ctor(qual: str, cls_qual: str) -> bool:
    return qual in {f"{cls_qual}.{m}"
                    for m in ("__init__", "__post_init__", "__new__")}


def _mut_call_targets(fn: dict):
    """(call, obj_expr, attr) for in-place mutator calls: ``obj.attr
    .sort()`` -> (obj, attr); ``obj.update()`` -> (obj, '')."""
    for call in fn["calls"]:
        callee = call["callee"]
        if callee.startswith("<call:"):
            continue
        parts = callee.split(".")
        if len(parts) < 2 or parts[-1] not in _INPLACE_MUTATORS:
            continue
        if len(parts) >= 3:
            yield call, ".".join(parts[:-2]), parts[-2]
        yield call, ".".join(parts[:-1]), ""


def snapshot_publish_pass(graph: ProjectGraph) -> list:
    findings: list[Finding] = []
    protected = _protected_classes(graph)
    if not protected:
        return findings

    def flag(qual, line, col, what, witness):
        findings.append(_finding(
            graph, "snapshot-publish", qual, line, col,
            f"{what} — this class is immutable-after-publish (lock-free "
            "readers hold references to published snapshots); build new "
            "arrays and publish a fresh instance by one reference swap",
            witness=witness))

    # ---- direct mutations + per-function param-mutation summaries ----
    # mut_params[qual][param] = {"seat": ..., "next": (target, param)}
    mut_params: dict[str, dict] = {}
    for qual, fn in graph.functions.items():
        eff = set(_effective_params(fn))

        def note_param(recv: str, seat: str) -> None:
            head = recv.split(".")[0]
            if head in eff:
                mut_params.setdefault(qual, {}).setdefault(
                    head, {"seat": seat, "next": None})

        for w in fn["attr_writes"]:
            recv, attr, kind = w["recv"], w["attr"], w["kind"]
            target = recv if not attr else f"{recv}.{attr}"
            seat = f"{graph.fn_file[qual]}:{w['line']} " \
                f"{_cls_leaf(qual)} {kind}-writes `{target}`"
            cls = _recv_class(graph, fn, recv)
            if cls in protected and not _is_ctor(qual, cls):
                what = {"store": f"attribute store on published "
                                 f"`{recv}.{attr}`",
                        "item": f"in-place element write to "
                                f"`{target}[...]`",
                        "aug": f"in-place augmented write to `{target}`"}
                flag(qual, w["line"], w["col"], what[kind], [seat])
            if attr and kind in ("store", "item", "aug"):
                note_param(recv, seat)
        for call, obj, attr in _mut_call_targets(fn):
            cls = _recv_class(graph, fn, obj)
            if cls in protected and not _is_ctor(qual, cls):
                tgt = f"{obj}.{attr}" if attr else obj
                flag(qual, call["line"], call["col"],
                     f"mutating call `{call['callee']}(...)` on "
                     f"published `{tgt}`",
                     [f"{graph.site(qual, call)} {_cls_leaf(qual)} calls "
                      f"{call['callee']}(...)"])
            if attr:
                note_param(obj, f"{graph.site(qual, call)} "
                                f"{_cls_leaf(qual)} calls "
                                f"{call['callee']}(...)")
        # numpy in-place sinks: ufunc .at(...) and out= kwargs
        for call in fn["calls"]:
            callee = call["callee"]
            facts = []
            if callee.split(".")[0] in _NP_HEADS \
                    and callee.rsplit(".", 1)[-1] == "at" \
                    and call.get("args"):
                facts.append(call["args"][0])
            out_fact = call.get("kwargs", {}).get("out")
            if out_fact is not None and (callee.split(".")[0] in _NP_HEADS
                                         or "." in callee):
                facts.append(out_fact)
            for fact in facts:
                if fact.get("kind") != "attr":
                    continue
                expr = fact["expr"]
                obj = expr.rsplit(".", 1)[0] if "." in expr else expr
                cls = _recv_class(graph, fn, obj)
                if cls in protected and not _is_ctor(qual, cls):
                    flag(qual, call["line"], call["col"],
                         f"numpy in-place op `{callee}` targets "
                         f"published `{expr}`",
                         [f"{graph.site(qual, call)} {_cls_leaf(qual)} "
                          f"calls {callee}(...)"])

    # ---- interprocedural: protected objects entering mutating params ----
    changed = True
    while changed:
        changed = False
        for qual, fn in graph.functions.items():
            for target, call in graph.calls.get(qual, ()):
                tparams = mut_params.get(target)
                callee_fn = graph.functions.get(target)
                if not tparams or callee_fn is None:
                    continue
                for tparam in list(tparams):
                    fact = _arg_for_param(callee_fn, call, tparam)
                    if fact is None:
                        continue
                    if fact.get("kind") == "param":
                        mine = mut_params.setdefault(qual, {})
                        if fact["name"] not in mine:
                            mine[fact["name"]] = {
                                "seat": None,
                                "next": (target, tparam, call)}
                            changed = True

    def mut_witness(start_qual: str, param: str) -> list:
        out: list = []
        qual, p = start_qual, param
        for _ in range(12):
            info = mut_params.get(qual, {}).get(p)
            if info is None:
                break
            if info["next"] is None:
                out.append(info["seat"])
                break
            nq, np_, ncall = info["next"]
            out.append(f"{graph.site(qual, ncall)} {_cls_leaf(qual)} "
                       f"passes `{p}` -> {_cls_leaf(nq)}(`{np_}`)")
            qual, p = nq, np_
        return out

    for qual, fn in graph.functions.items():
        for target, call in graph.calls.get(qual, ()):
            tparams = mut_params.get(target)
            callee_fn = graph.functions.get(target)
            if not tparams or callee_fn is None:
                continue
            for tparam in tparams:
                fact = _arg_for_param(callee_fn, call, tparam)
                if fact is None:
                    continue
                expr = None
                if fact.get("kind") == "attr":
                    expr = fact["expr"]
                elif fact.get("kind") == "var":
                    expr = fact["name"]
                if expr is None:
                    continue
                cls = _recv_class(graph, fn, expr)
                if cls in protected and not _is_ctor(qual, cls):
                    wit = [f"{graph.site(qual, call)} {_cls_leaf(qual)} "
                           f"passes published `{expr}` -> "
                           f"{_cls_leaf(target)}(`{tparam}`)"]
                    wit += mut_witness(target, tparam)
                    flag(qual, call["line"], call["col"],
                         f"published `{expr}` flows into "
                         f"`{_cls_leaf(target)}({tparam}=...)`, which "
                         f"mutates it {len(wit) - 1} call(s) away",
                         wit)
    return findings


def _publish_slots(graph: ProjectGraph) -> dict:
    """class qual -> slot attr set: declared ``__publish_slots__`` plus
    attrs whose constructor-assigned type is a protected class."""
    protected = _protected_classes(graph)
    slots: dict[str, set] = {}
    for cq, crec in graph.classes.items():
        s = set(crec.get("publish_slots", []))
        module = cq.rsplit(".", 1)[0]
        for attr, t in crec.get("attr_types", {}).items():
            if _class_of_ctor(graph, module, t) in protected:
                s.add(attr)
        if s:
            slots[cq] = s
    return slots


def atomic_swap_pass(graph: ProjectGraph) -> list:
    findings: list[Finding] = []
    slots = _publish_slots(graph)
    if not slots:
        return findings

    def flag(qual, line, col, what, witness):
        findings.append(_finding(
            graph, "atomic-swap", qual, line, col,
            f"{what} — published references are updated by rebinding "
            "the one attribute to a freshly built value (`self.x = "
            "new`), never read-modify-write: a concurrent reader must "
            "see the old snapshot or the new one, nothing in between",
            witness=witness))

    def slot_of(fn: dict, expr: str):
        """(owner class, slot, via-alias) when ``expr`` denotes a
        publish slot: 'self._snap', 'obj._snap', or an alias var."""
        resolved = expr
        via = None
        head = expr.split(".")[0]
        if "." not in expr:
            alias = fn.get("var_aliases", {}).get(head)
            if alias:
                resolved, via = alias, expr
        if "." not in resolved:
            return None
        base, attr = resolved.rsplit(".", 1)
        cls = _recv_class(graph, fn, base)
        if cls in slots and attr in slots[cls]:
            return cls, attr, via
        return None

    for qual, fn in graph.functions.items():
        for w in fn["attr_writes"]:
            recv, attr, kind = w["recv"], w["attr"], w["kind"]
            target = recv if not attr else f"{recv}.{attr}"
            seat = f"{graph.fn_file[qual]}:{w['line']} " \
                f"{_cls_leaf(qual)} {kind}-writes `{target}`"
            # (a) non-atomic update OF the slot itself
            owner = _recv_class(graph, fn, recv) if attr else None
            if owner in slots and attr in slots[owner]:
                if kind in ("aug", "item"):
                    flag(qual, w["line"], w["col"],
                         f"in-place {kind} update of published "
                         f"reference `{target}`", [seat])
                elif w.get("multi"):
                    flag(qual, w["line"], w["col"],
                         f"multi-target assignment publishes `{target}` "
                         "non-atomically", [seat])
            # (b) mutation THROUGH the slot (or an alias of it)
            hit = slot_of(fn, recv)
            if hit is not None:
                cls, slot, via = hit
                wit = [seat]
                if via is not None:
                    wit.append(f"`{via}` aliases "
                               f"`{_cls_leaf(cls)}.{slot}` "
                               "(published reference)")
                flag(qual, w["line"], w["col"],
                     f"mutation through published reference "
                     f"`{_cls_leaf(cls)}.{slot}`", wit)
        seen_mut: set = set()
        for call, obj, attr in _mut_call_targets(fn):
            # mutator on the slot (`self._snap.append(...)`), through it
            # (`self._snap.deltas.append(...)`), or via an alias var.
            hit = slot_of(fn, obj)
            if hit is None and attr:
                hit = slot_of(fn, f"{obj}.{attr}")
            if hit is None:
                continue
            cls, slot, via = hit
            key = (call["line"], cls, slot)
            if key in seen_mut:
                continue
            seen_mut.add(key)
            wit = [f"{graph.site(qual, call)} {_cls_leaf(qual)} calls "
                   f"{call['callee']}(...)"]
            if via is not None:
                wit.append(f"`{via}` aliases `{_cls_leaf(cls)}.{slot}` "
                           "(published reference)")
            flag(qual, call["line"], call["col"],
                 f"in-place mutator `{call['callee'].rsplit('.', 1)[-1]}"
                 f"()` on published reference `{_cls_leaf(cls)}.{slot}`",
                 wit)
    return findings


# -- registry ----------------------------------------------------------------

# pass name -> (rules it emits, callable(graph, matrix_path) -> findings)
PROJECT_PASSES = {
    "taint": (("sql-interp", "retry-bypass"),
              lambda graph, matrix_path=None: taint_pass(graph)),
    "lease-fence": (("lease-fence",),
                    lambda graph, matrix_path=None:
                    lease_fence_pass(graph)),
    "lock-order": (("lock-order",),
                   lambda graph, matrix_path=None:
                   lock_order_pass(graph)),
    "fault-seat-drift": (("fault-seat-drift",),
                         fault_seat_drift_pass),
    "snapshot-publish": (("snapshot-publish",),
                         lambda graph, matrix_path=None:
                         snapshot_publish_pass(graph)),
    "atomic-swap": (("atomic-swap",),
                    lambda graph, matrix_path=None:
                    atomic_swap_pass(graph)),
    "spec-conformance": (("spec-conformance",),
                         lambda graph, matrix_path=None:
                         spec_conformance_pass(graph)),
    "verb-dispatch-drift": (("verb-dispatch-drift",),
                            lambda graph, matrix_path=None:
                            verb_dispatch_drift_pass(graph)),
}

PROJECT_RULES = ("sql-interp", "retry-bypass", "lease-fence",
                 "lock-order", "fault-seat-drift", "snapshot-publish",
                 "atomic-swap", "spec-conformance",
                 "verb-dispatch-drift")


def run_project_passes(graph: ProjectGraph,
                       wanted_rules: set | None = None,
                       matrix_path: str | None = None) -> list:
    """Run every project pass whose emitted rules intersect
    ``wanted_rules`` (all of them when None)."""
    findings: list[Finding] = []
    for _name, (emits, fn) in PROJECT_PASSES.items():
        if wanted_rules is not None and not (set(emits) & wanted_rules):
            continue
        out = fn(graph, matrix_path=matrix_path)
        if wanted_rules is not None:
            out = [f for f in out if f.rule in wanted_rules]
        findings.extend(out)
    return findings


__all__ = ["MATRIX_DEFAULT", "PROJECT_PASSES", "PROJECT_RULES",
           "atomic_swap_pass", "fault_seat_drift_pass",
           "lease_fence_pass", "lock_order_pass", "run_project_passes",
           "snapshot_publish_pass", "spec_conformance_pass",
           "taint_pass", "verb_dispatch_drift_pass"]
