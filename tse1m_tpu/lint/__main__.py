"""``python -m tse1m_tpu.lint`` — run graftlint over the repo."""

import sys

from .engine import main

if __name__ == "__main__":
    sys.exit(main())
