"""The graftlint rule catalog (rationale per rule in LINTING.md).

Every rule is a pure function ``FileSource -> list[Finding]`` over the
parsed AST; the engine resolves suppressions and the baseline.  Rules are
tuned to THIS repo's failure modes — they prefer a small number of
high-signal findings over generic-linter breadth, and each encodes an
invariant some PR actually shipped:

- ``broad-except``        fault transparency (resilience plane, PR 1)
- ``nonatomic-write``     atomic tmp+rename writes (checkpointers, PR 1)
- ``sql-interp``          validated SQL identifiers (db/ident.py)
- ``host-in-jit``         no host ops / traced-value control flow in
                          jit/shard_map/pallas bodies (silent recompiles
                          or device->host syncs)
- ``wire-layer``          host<->device transfers only in the blessed
                          wire layer (cluster/encode.py + pipeline.py,
                          PR 2)
- ``unlocked-shared-state``  lock-owning classes/modules must mutate
                          shared state under their lock (producer-thread
                          overlap, PR 2)
- ``retry-bypass``        all HTTP/DB I/O through the retry engine (PR 1)
- ``nondeterminism``      no wall-clock/global-RNG in chaos-replayed
                          planes (seeded fault plans must replay)
- ``watchdog-clock``      the supervision plane reads time only through
                          resilience.watchdog.deadline_clock (one
                          monotonic time base for every deadline)
- ``span-discipline``     tracing spans close deterministically: ``with
                          span(...)`` (or enter_context), and manual
                          ``start_span`` only under a finally-``.end()``
"""

from __future__ import annotations

import ast
import re

from .engine import FileSource, Finding


def _f(src: FileSource, node: ast.AST, message: str) -> Finding:
    return Finding(rule="", path=src.path, line=getattr(node, "lineno", 1),
                   col=getattr(node, "col_offset", 0), message=message)


def _parents(tree: ast.AST) -> dict:
    out = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _dotted(node: ast.AST) -> str:
    """'jax.device_put' for Attribute chains, 'open' for Names, '' else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _enclosing_function(node: ast.AST, parents: dict):
    while node is not None:
        node = parents.get(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node
    return None


# -- 1. broad-except ---------------------------------------------------------

_BROAD = {"Exception", "BaseException"}
_FAULT_GUARDS = {"reraise_if_fault"}


def _is_broad(type_node) -> bool:
    if type_node is None:  # bare `except:` — also swallows KeyboardInterrupt
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    name = _dotted(type_node)
    return name.rsplit(".", 1)[-1] in _BROAD


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    """The handler is fault-transparent: it re-raises (bare ``raise``,
    conditionally is fine — that is exactly the prescribed
    ``if isinstance(e, InjectedFault): raise`` guard), chains a new
    exception (``raise X(...) from e`` propagates loudly), or routes
    through ``resilience.reraise_if_fault``."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise) and (node.exc is None
                                            or node.cause is not None):
            return True
        if isinstance(node, ast.Call):
            if _dotted(node.func).rsplit(".", 1)[-1] in _FAULT_GUARDS:
                return True
    return False


def broad_except(src: FileSource) -> list[Finding]:
    out = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Try):
            continue
        for h in node.handlers:
            if _is_broad(h.type) and not _handler_reraises(h):
                kind = ("bare except" if h.type is None
                        else f"except {_dotted(h.type) or '...'}")
                out.append(_f(src, h,
                              f"{kind} can swallow resilience.InjectedFault"
                              " — narrow it, re-raise faults (`if "
                              "isinstance(e, InjectedFault): raise` / "
                              "resilience.reraise_if_fault(e)), or "
                              "suppress with a reason"))
    return out


# -- 2. nonatomic-write ------------------------------------------------------

def _is_tmp_target(arg: ast.AST) -> bool:
    """The open() target is already a tmp-file the caller will rename."""
    if isinstance(arg, ast.Name) and "tmp" in arg.id.lower():
        return True
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value.endswith(".tmp")
    if isinstance(arg, ast.BinOp):  # path + ".tmp"
        return _is_tmp_target(arg.right) or _is_tmp_target(arg.left)
    if isinstance(arg, ast.Attribute) and "tmp" in arg.attr.lower():
        return True
    if isinstance(arg, ast.Call):  # tmp_path(...), .with_suffix(".tmp")
        inner = _dotted(arg.func).rsplit(".", 1)[-1].lower()
        if "tmp" in inner:
            return True
        return any(_is_tmp_target(a) for a in arg.args)
    return False


def nonatomic_write(src: FileSource) -> list[Finding]:
    parents = _parents(src.tree)
    out = []
    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "open" and len(node.args) >= 2):
            continue
        mode = node.args[1]
        if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)
                and "w" in mode.value):
            continue
        if _is_tmp_target(node.args[0]):
            continue
        fn = _enclosing_function(node, parents)
        scope = fn if fn is not None else src.tree
        renames = any(
            isinstance(n, ast.Call)
            and _dotted(n.func) in ("os.replace", "os.rename")
            for n in ast.walk(scope))
        if renames:
            continue
        out.append(_f(src, node,
                      "non-atomic write-mode open() — a crash mid-write "
                      "leaves a torn file; write to `path + \".tmp\"` then "
                      "os.replace (see collect/checkpoint.py), or suppress "
                      "with a reason"))
    return out


# -- 3. sql-interp -----------------------------------------------------------

_SQL_RE = re.compile(
    r"\b(SELECT|INSERT|UPDATE|DELETE|CREATE|DROP|ALTER|COPY|PRAGMA|SET)\b")
# Interpolations that cannot inject: the db/ident.py helpers, integer
# coercion, and db/queries.py's qmark placeholder-list builder.
_SQL_BLESSED = {"quote_ident", "validate_ident", "col_list", "int", "_in"}


def _blessed_expr(node: ast.AST, env: dict, depth: int = 0) -> bool:
    """True when the interpolated expression cannot inject: constants,
    the blessed helpers, placeholder-list composition (``",".join("?" *
    len(cols))``), and names assigned (in the same scope) from blessed
    expressions."""
    if depth > 6:
        return False
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        bound = env.get(node.id)
        return bound is not None and _blessed_expr(bound, env, depth + 1)
    if isinstance(node, ast.Call):
        name = _dotted(node.func).rsplit(".", 1)[-1]
        if name in _SQL_BLESSED or name == "len":
            return True
        if (isinstance(node.func, ast.Attribute) and node.func.attr == "join"
                and isinstance(node.func.value, ast.Constant)):
            return all(_blessed_expr(a, env, depth + 1) for a in node.args)
        return False
    if isinstance(node, ast.BinOp):
        return (_blessed_expr(node.left, env, depth + 1)
                and _blessed_expr(node.right, env, depth + 1))
    if isinstance(node, ast.IfExp):
        return (_blessed_expr(node.body, env, depth + 1)
                and _blessed_expr(node.orelse, env, depth + 1))
    if isinstance(node, (ast.GeneratorExp, ast.ListComp)):
        return _blessed_expr(node.elt, env, depth + 1)
    if isinstance(node, ast.JoinedStr):
        return all(_blessed_expr(v.value, env, depth + 1)
                   for v in node.values
                   if isinstance(v, ast.FormattedValue))
    return False


def _scope_env(scope: ast.AST) -> dict:
    """name -> assigned expression, for single-name assignments in the
    scope (simple local dataflow; reassignment keeps the LAST binding,
    which is the common builder pattern here)."""
    env: dict = {}
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            env[node.targets[0].id] = node.value
        elif isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Name):
            env[node.target.id] = None  # composed further — unknown
    return env


_SQL_MSG = ("SQL string built by interpolation — route identifiers through "
            "db/ident.py (quote_ident/validate_ident/col_list) or bind "
            "values as parameters")


def sql_interp(src: FileSource) -> list[Finding]:
    parents = _parents(src.tree)
    envs: dict = {}

    def env_for(node: ast.AST) -> dict:
        scope = _enclosing_function(node, parents) or src.tree
        if id(scope) not in envs:
            envs[id(scope)] = _scope_env(scope)
        return envs[id(scope)]

    out = []
    for node in ast.walk(src.tree):
        if isinstance(node, ast.JoinedStr):
            literal = "".join(v.value for v in node.values
                              if isinstance(v, ast.Constant)
                              and isinstance(v.value, str))
            if not _SQL_RE.search(literal):
                continue
            env = env_for(node)
            bad = [v for v in node.values
                   if isinstance(v, ast.FormattedValue)
                   and not _blessed_expr(v.value, env)]
            if bad:
                out.append(_f(src, node, _SQL_MSG))
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "format"
              and isinstance(node.func.value, ast.Constant)
              and isinstance(node.func.value.value, str)
              and _SQL_RE.search(node.func.value.value)):
            env = env_for(node)
            if not all(_blessed_expr(a, env) for a in node.args) or not all(
                    _blessed_expr(k.value, env) for k in node.keywords):
                out.append(_f(src, node, _SQL_MSG))
        elif (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod)
              and isinstance(node.left, ast.Constant)
              and isinstance(node.left.value, str)
              and _SQL_RE.search(node.left.value)):
            env = env_for(node)
            right = (node.right.elts if isinstance(node.right, ast.Tuple)
                     else [node.right])
            if not all(_blessed_expr(r, env) for r in right):
                out.append(_f(src, node, _SQL_MSG))
    return out


# -- 4. host-in-jit ----------------------------------------------------------

def _jit_call_target(call: ast.Call):
    """(is_jit_wrap, static_argnames) for jax.jit(...) / jit(...) /
    partial(jax.jit, ...) call nodes."""
    name = _dotted(call.func).rsplit(".", 1)[-1]
    if name == "jit":
        return True, _static_argnames(call)
    if name == "partial" and call.args:
        inner = call.args[0]
        if _dotted(inner).rsplit(".", 1)[-1] == "jit":
            return True, _static_argnames(call)
    return False, ()


def _static_argnames(call: ast.Call) -> tuple:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant))
    return ()


def _collect_traced_functions(src: FileSource) -> dict:
    """name -> static_argnames for functions whose BODY is traced:
    jit-decorated, jit-wrapped at module level, shard_map-decorated, or
    passed as a pallas_call kernel."""
    traced: dict[str, tuple] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                name = _dotted(dec).rsplit(".", 1)[-1]
                if name in ("jit", "shard_map"):
                    traced[node.name] = ()
                elif isinstance(dec, ast.Call):
                    is_jit, statics = _jit_call_target(dec)
                    dec_name = _dotted(dec.func).rsplit(".", 1)[-1]
                    if is_jit or dec_name == "shard_map":
                        traced[node.name] = statics
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            is_jit, statics = _jit_call_target(call)
            inner = None
            if _dotted(call.func).rsplit(".", 1)[-1] == "jit" and call.args:
                inner = call.args[0]
            elif (_dotted(call.func).rsplit(".", 1)[-1] == "partial"
                  and len(call.args) >= 2):
                inner = call.args[1]
            if is_jit and isinstance(inner, ast.Name):
                traced[inner.id] = statics
        elif isinstance(node, ast.Call):
            if _dotted(node.func).rsplit(".", 1)[-1] == "pallas_call":
                if node.args:
                    kern = node.args[0]
                    if isinstance(kern, ast.Name):
                        traced.setdefault(kern.id, ())
                    elif (isinstance(kern, ast.Call) and kern.args
                          and isinstance(kern.args[0], ast.Name)):
                        traced.setdefault(kern.args[0].id, ())
    return traced


def host_in_jit(src: FileSource) -> list[Finding]:
    traced = _collect_traced_functions(src)
    if not traced:
        return []
    out = []
    for node in ast.walk(src.tree):
        if not (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in traced):
            continue
        statics = set(traced[node.name])
        args = node.args
        params = {a.arg for a in (args.posonlyargs + args.args
                                  + args.kwonlyargs)}
        # Keyword-only params default to static in this codebase's idiom
        # (block_n/interpret style knobs); positional params are traced
        # unless named in static_argnames.
        dyn = params - statics - {a.arg for a in args.kwonlyargs} - {"self"}
        for inner in ast.walk(node):
            if isinstance(inner, ast.Attribute):
                base = inner.value
                if isinstance(base, ast.Name) and base.id in ("np", "numpy"):
                    out.append(_f(src, inner,
                                  f"host numpy (`np.{inner.attr}`) inside "
                                  f"traced body `{node.name}` — runs at "
                                  "trace time / forces a host sync; use "
                                  "jnp or hoist to the call site"))
            elif isinstance(inner, ast.Call):
                fn_name = _dotted(inner.func)
                if (fn_name in ("float", "int", "bool") and inner.args
                        and not isinstance(inner.args[0], ast.Constant)
                        and not (isinstance(inner.args[0], ast.Name)
                                 and inner.args[0].id in statics)):
                    out.append(_f(src, inner,
                                  f"host `{fn_name}()` on a value inside "
                                  f"traced body `{node.name}` — implicit "
                                  "device->host transfer (or a tracer "
                                  "error); keep it on device or mark the "
                                  "arg static"))
                elif (isinstance(inner.func, ast.Attribute)
                      and inner.func.attr == "item"):
                    out.append(_f(src, inner,
                                  f"`.item()` inside traced body "
                                  f"`{node.name}` — blocking device->host "
                                  "transfer"))
            elif isinstance(inner, (ast.If, ast.While)):
                names = {n.id for n in ast.walk(inner.test)
                         if isinstance(n, ast.Name)}
                hot = names & dyn
                if hot:
                    out.append(_f(src, inner,
                                  "Python control flow on traced value(s) "
                                  f"{sorted(hot)} inside `{node.name}` — "
                                  "recompiles per value (or tracer error); "
                                  "use jnp.where/lax.cond or mark static"))
    return out


# -- 5. wire-layer -----------------------------------------------------------

# The blessed wire layer: the ONLY seats allowed to move bytes across the
# host<->device link.  Everything else must feed through them so wire
# accounting (StageRecorder h2d/d2h bytes) and the adaptive encoder can't
# be bypassed.  Wire v3 admits the entropy codec and the host prefilter
# as the only new seats (their frames/masks ARE wire format; today both
# stay host-side and route their puts through pipeline.py, but the
# format modules are part of the plane they define).  The batched
# scoring plane (kernels/score.py) is the one kernel module with its own
# seat: its double-buffered chunk staging IS the topk scan's transfer
# path (the other kernels/ modules stay transfer-free and keep firing).
_WIRE_LAYER = ("tse1m_tpu/cluster/encode.py", "tse1m_tpu/cluster/pipeline.py",
               "tse1m_tpu/cluster/entropy.py",
               "tse1m_tpu/cluster/prefilter.py",
               "tse1m_tpu/cluster/kernels/score.py")


def wire_layer(src: FileSource) -> list[Finding]:
    if src.path in _WIRE_LAYER:
        return []
    out = []
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name.rsplit(".", 1)[-1] in ("device_put", "device_get"):
                out.append(_f(src, node,
                              f"`{name}` outside the wire layer "
                              f"({', '.join(_WIRE_LAYER)}) — transfers "
                              "bypass wire accounting and the adaptive "
                              "encoder; route through the pipeline or "
                              "baseline with a reason"))
    return out


# -- 5b. scheme-parity -------------------------------------------------------

# The signature-scheme registry (cluster/schemes.py) is the ONLY dispatch
# point for signature computation: every consumer routes through it so
# host oracle, device reference, pallas variant, prefilter and serve-side
# MinHash can never disagree about which kernel family a run uses — the
# bit-parity contract the store/checkpoint policy tuple pins.  The raw
# kernels are implementation detail of these modules alone.
_SCHEME_KERNEL_MODULES = (
    "tse1m_tpu/cluster/schemes.py",
    "tse1m_tpu/cluster/minhash.py",
    "tse1m_tpu/cluster/minhash_pallas.py",
    "tse1m_tpu/cluster/host.py",
)
_SCHEME_KERNEL_CALLS = {
    "minhash_signatures", "cminhash_signatures",
    "host_signatures", "host_cminhash_signatures",
    "minhash_and_keys", "minhash_and_keys_pallas",
    "minhash_and_keys_packed", "cminhash_and_keys",
}


def scheme_parity(src: FileSource) -> list[Finding]:
    if src.path in _SCHEME_KERNEL_MODULES:
        return []
    out = []
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name.rsplit(".", 1)[-1] in _SCHEME_KERNEL_CALLS:
                out.append(_f(src, node,
                              f"raw signature kernel call `{name}` "
                              "outside the scheme registry "
                              "(cluster/schemes.py) — a module that "
                              "hard-codes one kernel family silently "
                              "breaks bit-parity the moment a run "
                              "selects another scheme; dispatch through "
                              "schemes.scheme_sig_and_keys / "
                              "scheme_host_signatures / "
                              "scheme_signatures_traced, or baseline "
                              "with a reason"))
    return out


# -- 6. unlocked-shared-state ------------------------------------------------

def _is_lock_ctor(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _dotted(node.func).rsplit(".", 1)[-1] in ("Lock", "RLock"))


def _self_attr_written(target: ast.AST) -> str | None:
    """'x' for targets self.x / self.x[...] — the shared attr mutated."""
    node = target
    while isinstance(node, (ast.Subscript, ast.Starred)):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def _under_lock(node: ast.AST, parents: dict, lock_names: set) -> bool:
    while node is not None:
        node = parents.get(node)
        if isinstance(node, ast.With):
            for item in node.items:
                expr = item.context_expr
                attr = None
                if isinstance(expr, ast.Attribute):
                    attr = expr.attr
                elif isinstance(expr, ast.Name):
                    attr = expr.id
                elif isinstance(expr, ast.Call):
                    attr = _dotted(expr.func).rsplit(".", 1)[-1]
                if attr in lock_names:
                    return True
    return False


def unlocked_shared_state(src: FileSource) -> list[Finding]:
    parents = _parents(src.tree)
    out = []
    # Class-owned locks: any self-attribute mutation outside __init__ must
    # hold the lock (the class declared its state shared by creating one).
    for cls in ast.walk(src.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    name = _self_attr_written(t)
                    if name:
                        locks.add(name)
        if not locks:
            continue
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if meth.name == "__init__":
                continue
            for node in ast.walk(meth):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    attr = _self_attr_written(t)
                    if attr and attr not in locks and not _under_lock(
                            node, parents, locks):
                        out.append(_f(src, node,
                                      f"`self.{attr}` mutated outside "
                                      f"`with self.{next(iter(locks))}` in "
                                      f"lock-owning class {cls.name} — "
                                      "racy with the producer thread"))
    # Module-level locks guarding globals.
    mod_locks = set()
    guarded: set = set()
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            mod_locks |= {t.id for t in node.targets
                          if isinstance(t, ast.Name)}
    if mod_locks:
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                names = {t.id for t in targets if isinstance(t, ast.Name)}
                if names and _under_lock(node, parents, mod_locks):
                    guarded |= names
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    names = {t.id for t in targets
                             if isinstance(t, ast.Name)} & guarded
                    if names and not _under_lock(node, parents, mod_locks):
                        out.append(_f(src, node,
                                      f"global(s) {sorted(names)} mutated "
                                      "outside the module lock that guards "
                                      "them elsewhere"))
    return out


# -- 7. retry-bypass ---------------------------------------------------------

_TRANSPORT = "tse1m_tpu/collect/transport.py"
_DB_LAYER = ("tse1m_tpu/db/connection.py", "tse1m_tpu/db/pglib.py")
_HTTP_FNS = {"get", "post", "put", "head", "delete", "request", "Session"}


def retry_bypass(src: FileSource) -> list[Finding]:
    out = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        base = func.value
        if (src.path != _TRANSPORT
                and isinstance(base, ast.Name) and base.id == "requests"
                and func.attr in _HTTP_FNS):
            out.append(_f(src, node,
                          f"direct `requests.{func.attr}` bypasses the "
                          "retry engine — use collect.transport."
                          "HttpFetcher (backoff, Retry-After, fault "
                          "injection seats)"))
        if func.attr == "urlopen":
            out.append(_f(src, node,
                          "`urlopen` bypasses the retry engine — use "
                          "collect.transport.HttpFetcher"))
        if (src.path not in _DB_LAYER
                and func.attr in ("execute", "executemany", "executescript")):
            is_cursor = (
                (isinstance(base, ast.Attribute) and base.attr == "cursor")
                or (isinstance(base, ast.Name)
                    and base.id in ("cursor", "cur")))
            if is_cursor:
                out.append(_f(src, node,
                              "raw cursor execute bypasses the DB retry/"
                              "reconnect engine — use DB.execute/query/"
                              "executeMany/run_transaction"))
    return out


# -- 8. nondeterminism -------------------------------------------------------

# Planes replayed under seeded fault plans / chaos tests: wall-clock and
# global-RNG reads there make a replay diverge from the recorded run.
_REPLAY_PLANES = ("tse1m_tpu/resilience/", "tse1m_tpu/collect/",
                  "tse1m_tpu/db/", "tse1m_tpu/cluster/")
_RANDOM_OK = {"Random", "SystemRandom", "getstate", "seed"}


def nondeterminism(src: FileSource) -> list[Finding]:
    if not src.path.startswith(_REPLAY_PLANES):
        return []
    out = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name in ("time.time", "time.time_ns"):
            out.append(_f(src, node,
                          f"wall clock `{name}()` in a chaos-replayed "
                          "plane — use time.monotonic for intervals, or "
                          "suppress if this is pure telemetry"))
        elif name in ("datetime.now", "datetime.utcnow", "date.today",
                      "datetime.datetime.now", "datetime.date.today"):
            out.append(_f(src, node,
                          f"`{name}()` in a chaos-replayed plane — pass "
                          "the date in from the caller so a replay sees "
                          "the recorded value"))
        elif (name.startswith("random.")
              and name.split(".", 1)[1] not in _RANDOM_OK
              and name.count(".") == 1):
            out.append(_f(src, node,
                          f"global-RNG `{name}()` in a chaos-replayed "
                          "plane — draw from a seeded random.Random "
                          "(resilience.faults idiom)"))
        elif (name.startswith("np.random.") or name.startswith(
                "numpy.random.")) and not name.endswith("default_rng"):
            out.append(_f(src, node,
                          f"legacy global `{name}()` — use a seeded "
                          "np.random.default_rng"))
    return out


# -- 9. watchdog-clock -------------------------------------------------------
#
# The supervision plane's invariant (watchdog PR): every deadline, budget
# and stall decision reads time through resilience.watchdog.deadline_clock
# — one monotonic clock for the whole plane.  A raw clock call in deadline
# logic forks the time base: a wall-clock seat can jump with NTP/DST and
# fire (or starve) a watchdog, and even a second monotonic seat makes the
# plane's arithmetic unauditable.  Scope: the watchdog module itself plus
# any function whose name claims deadline/watchdog/stall — or, since the
# elastic-membership PR, heartbeat/lease — semantics.
#
# The lease extension adds a second check in the same scope: lease files
# (the pod's zombie fence, resilience/coordinator.py) must only ever be
# mutated through the atomic-write helper — a raw `open(..., "w")` in a
# lease/heartbeat function can leave a TORN lease that a reader
# misparses as absent and re-acquires, letting two writers hold one
# range.  Wall-clock time in a lease is the same class of bug (clocks
# are not comparable across hosts; fencing is by epoch only), and the
# clock half of this rule already covers it once the name markers do.

_WATCHDOG_PLANE = ("tse1m_tpu/resilience/watchdog.py",
                   "tse1m_tpu/resilience/coordinator.py",
                   "tse1m_tpu/observability/latency.py",
                   # graftprof: the sampler timestamps stacks and the
                   # lock-wait recorder times acquires on the same axis
                   # the SLO math compares against; the regression gate
                   # judges walls measured on it.  A second clock in
                   # either file makes profile/flight/bench timelines
                   # unalignable.
                   "tse1m_tpu/observability/profiling.py",
                   "tse1m_tpu/observability/regress.py")
# The serving plane (PR 10) lives in the clock discipline wholesale: its
# SLO decisions, latency histograms and admission windows all compare
# against watchdog budgets, so a raw clock anywhere in tse1m_tpu/serve/
# forks the time base the p99 is measured on.
_WATCHDOG_PLANE_PREFIXES = ("tse1m_tpu/serve/",)
_CLOCK_CALLS = {"time.time", "time.time_ns", "time.monotonic",
                "time.monotonic_ns", "time.perf_counter",
                "time.perf_counter_ns", "time.clock_gettime"}
_WATCHDOG_NAME_MARKERS = ("deadline", "watchdog", "stall", "heartbeat",
                          "lease", "slo", "admission")
_LEASE_NAME_MARKERS = ("lease", "heartbeat")


def _open_write_mode(node: ast.Call) -> bool:
    """True when this is an `open(...)` call with a writable mode."""
    if _dotted(node.func) != "open":
        return False
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
        return False
    return any(c in mode.value for c in "wa+x")


def watchdog_clock(src: FileSource) -> list[Finding]:
    out = []
    parents = None
    in_plane = (src.path in _WATCHDOG_PLANE
                or src.path.startswith(_WATCHDOG_PLANE_PREFIXES))
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        is_clock = _dotted(node.func) in _CLOCK_CALLS
        is_write = _open_write_mode(node)
        if not (is_clock or is_write):
            continue
        if parents is None:
            parents = _parents(src.tree)
        fn = _enclosing_function(node, parents)
        fname = fn.name if fn is not None else ""
        if is_clock:
            if fname == "deadline_clock":
                continue  # THE helper — the plane's one blessed raw-clock seat
            if in_plane or any(m in fname.lower()
                               for m in _WATCHDOG_NAME_MARKERS):
                out.append(_f(src, node,
                              f"raw clock `{_dotted(node.func)}()` in the "
                              "watchdog plane — read time through "
                              "resilience.watchdog.deadline_clock so every "
                              "deadline shares one monotonic time base"))
        elif in_plane or any(m in fname.lower()
                             for m in _LEASE_NAME_MARKERS):
            out.append(_f(src, node,
                          "raw writable `open()` in lease/heartbeat code "
                          "— every lease or heartbeat mutation goes "
                          "through utils.atomic.atomic_write (see "
                          "resilience.coordinator.write_lease) so a "
                          "reader never sees a torn file"))
    return out


# -- 11. span-discipline (telemetry plane) -----------------------------------
#
# A span that never closes is worse than no span: it sits in the ring
# forever "in flight", its duration is garbage, and every span opened
# after it misparents under a context that should have popped.  The
# tracing API is shaped so this cannot happen — ``span()`` is a context
# manager — and this rule keeps call sites on that shape: ``span(...)``
# must be the context expression of a ``with`` (or handed to an
# ExitStack via ``enter_context``), and the manual escape hatch
# ``start_span(...)`` is legal only inside a function that guarantees
# ``.end()`` in a ``finally`` (the shape tracing.span itself uses).

_SPAN_CALL_NAMES = {"span", "start_span"}


def _fn_finalizes_end(fn: ast.AST) -> bool:
    """True when ``fn`` contains a Try whose finalbody calls ``.end()``."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        for fin in node.finalbody:
            for sub in ast.walk(fin):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "end"):
                    return True
    return False


def span_discipline(src: FileSource) -> list[Finding]:
    out = []
    parents = None
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func).rsplit(".", 1)[-1]
        if name not in _SPAN_CALL_NAMES:
            continue
        if parents is None:
            parents = _parents(src.tree)
        if name == "span":
            par = parents.get(node)
            if isinstance(par, ast.withitem):
                continue
            if (isinstance(par, ast.Call)
                    and _dotted(par.func).rsplit(".", 1)[-1]
                    == "enter_context"):
                continue
            out.append(_f(src, node,
                          "`span(...)` outside a `with` — a span object "
                          "that escapes its context can stay open forever "
                          "(garbage duration, misparented children); use "
                          "`with span(...)` or "
                          "`stack.enter_context(span(...))`"))
        else:
            fn = _enclosing_function(node, parents)
            if fn is not None and _fn_finalizes_end(fn):
                continue
            out.append(_f(src, node,
                          "manual `start_span(...)` without a guaranteed "
                          "close — the enclosing function must call "
                          "`.end()` in a `finally` (or use `with "
                          "span(...)`, which cannot leak)"))
    return out


# -- 12. prof-overhead (profiling plane) --------------------------------------
#
# A profiler must never be able to hang or outlive the process it
# observes.  Two checkable shapes enforce that (graftprof PR):
# (a) every thread the profiling plane spawns is constructed with a
# literal ``daemon=True`` — a non-daemon sampler blocks interpreter
# exit, so the observed process cannot die until its observer does, and
# a computed daemon flag is an unauditable maybe; (b) a plane file that
# spawns threads must reference the ``TSE1M_PROFILING`` kill switch
# somewhere, so an operator can amputate ALL sampling with one env var
# when the profiler itself becomes the problem.  Scope: the profiling
# module, plus any function or class whose name claims sampler/profiler
# semantics anywhere in the tree.

_PROF_PLANE = ("tse1m_tpu/observability/profiling.py",)
_PROF_NAME_MARKERS = ("sampler", "profiler")
_PROF_KILL_SWITCH = "TSE1M_PROFILING"


def _enclosing_names(node: ast.AST, parents: dict) -> str:
    """Lowercased, space-joined names of every enclosing function and
    class — the scope a profiling-plane thread spawn is judged by."""
    names = []
    while node is not None:
        node = parents.get(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.append(node.name.lower())
    return " ".join(names)


def prof_overhead(src: FileSource) -> list[Finding]:
    out = []
    in_plane = src.path in _PROF_PLANE
    parents = None
    plane_spawns = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        if _dotted(node.func).rsplit(".", 1)[-1] != "Thread":
            continue
        if parents is None:
            parents = _parents(src.tree)
        if not (in_plane or any(m in _enclosing_names(node, parents)
                                for m in _PROF_NAME_MARKERS)):
            continue
        plane_spawns.append(node)
        daemon_literal_true = any(
            kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in node.keywords)
        if not daemon_literal_true:
            out.append(_f(src, node,
                          "profiling-plane `Thread(...)` without a literal "
                          "`daemon=True` — a non-daemon sampler thread "
                          "blocks interpreter exit, so the observed "
                          "process cannot die until its observer does"))
    if plane_spawns and _PROF_KILL_SWITCH not in src.text:
        out.append(_f(src, plane_spawns[0],
                      "profiling code spawns threads but never consults "
                      f"the `{_PROF_KILL_SWITCH}` kill switch — the "
                      "operator must be able to amputate all sampling "
                      "with one env var when the profiler itself becomes "
                      "the problem"))
    return out


# -- 13. serve-write-plane (sharded serve topology) ---------------------------
#
# The sharded serve plane's zero-lost-ack proof is only auditable if the
# write plane has exactly one kind of inhabitant: the shard writer
# daemon.  Two path-scoped checks hold that shape (sharded-serve PR):
#
# (a) the ROUTER (serve/router.py) is STATELESS — it never constructs a
#     ``SignatureStore``, never calls a store mutator, and never opens a
#     file writable.  A router that spills state grows a second
#     durability seat the failover contract does not cover: an ingest is
#     acked iff the OWNING SHARD's manifest committed, and the router
#     must be killable/replaceable at any instant with no recovery step.
#     (The one file a router writes — its own port file — goes through
#     ``utils.atomic.atomic_write``, which this rule does not flag.)
#
# (b) a READ REPLICA (serve/replicate.py) joins the read plane only: its
#     store handle is constructed with a literal ``read_only=True``, it
#     never calls a store mutator, and the served view advances ONLY
#     through the adoption path — ``refresh()`` and the ``__init__``/
#     ``_rebuild`` seats it drives.  An adoption write anywhere else
#     (a ``_generation_adopted`` assignment or a ``_rebuild()`` call in
#     query/status/ad-hoc code) could publish a generation whose
#     manifest has not committed, turning a bounded-STALENESS replica
#     into a torn-VIEW one.  (Writable ``open()`` is legal here: the
#     shard streamer legitimately copies frames into the replica's
#     directory — CRC-verified, manifest committed last.)

_ROUTER_WRITE_PLANE = ("tse1m_tpu/serve/router.py",)
_REPLICA_WRITE_PLANE = ("tse1m_tpu/serve/replicate.py",)
_STORE_MUTATORS = {"append", "save_state", "journal_record", "commit_state",
                   "evict", "scrub", "quarantine", "compact"}
_ADOPTION_SEATS = {"__init__", "_rebuild", "refresh"}


def serve_write_plane(src: FileSource) -> list[Finding]:
    in_router = src.path in _ROUTER_WRITE_PLANE
    in_replica = src.path in _REPLICA_WRITE_PLANE
    if not (in_router or in_replica):
        return []
    out = []
    parents = _parents(src.tree)
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            leaf = name.rsplit(".", 1)[-1]
            recv = name.rsplit(".", 1)[0] if "." in name else ""
            if leaf == "SignatureStore":
                if in_router:
                    out.append(_f(src, node,
                                  "the router opens a store — the router is "
                                  "STATELESS: durability lives entirely at "
                                  "the shard writers (an ack is durable iff "
                                  "the owning shard's manifest committed), "
                                  "and a router-side store is a durability "
                                  "seat the failover proof does not cover"))
                elif not any(kw.arg == "read_only"
                             and isinstance(kw.value, ast.Constant)
                             and kw.value.value is True
                             for kw in node.keywords):
                    out.append(_f(src, node,
                                  "replica store handle without a literal "
                                  "`read_only=True` — a replica is excluded "
                                  "from the write plane BY CONSTRUCTION; a "
                                  "writable handle here could append to a "
                                  "range the lease plane dealt to a writer"))
            elif leaf in _STORE_MUTATORS and "store" in recv.lower():
                out.append(_f(src, node,
                              f"store mutator `{name}()` in the "
                              f"{'router' if in_router else 'replica'} — "
                              "only the range's single writer daemon may "
                              "mutate store state; the read plane serves "
                              "streamed committed generations only"))
            elif in_router and _open_write_mode(node):
                out.append(_f(src, node,
                              "writable `open()` in the router — the router "
                              "holds no durable state (its port file goes "
                              "through utils.atomic.atomic_write); spilled "
                              "router state breaks the kill-anytime "
                              "failover contract"))
            elif in_replica and leaf == "_rebuild":
                fn = _enclosing_function(node, parents)
                if fn is None or fn.name not in _ADOPTION_SEATS:
                    out.append(_f(src, node,
                                  "`_rebuild()` outside the adoption path — "
                                  "replica state advances only via "
                                  "refresh() (or __init__), after the "
                                  "streamed manifest committed; adopting "
                                  "elsewhere can publish a torn view"))
        elif in_replica and isinstance(node, (ast.Assign, ast.AugAssign,
                                              ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            if any((isinstance(t, ast.Attribute)
                    and t.attr == "_generation_adopted")
                   or (isinstance(t, ast.Name)
                       and t.id == "_generation_adopted")
                   for t in targets):
                fn = _enclosing_function(node, parents)
                if fn is None or fn.name not in _ADOPTION_SEATS:
                    out.append(_f(src, node,
                                  "`_generation_adopted` assigned outside "
                                  "refresh()/__init__/_rebuild — the "
                                  "adopted generation may only move when "
                                  "every file its manifest references is "
                                  "in place (the stream commits the "
                                  "manifest LAST)"))
    return out


RULES = {
    "broad-except": broad_except,
    "nonatomic-write": nonatomic_write,
    "sql-interp": sql_interp,
    "host-in-jit": host_in_jit,
    "wire-layer": wire_layer,
    "scheme-parity": scheme_parity,
    "unlocked-shared-state": unlocked_shared_state,
    "retry-bypass": retry_bypass,
    "nondeterminism": nondeterminism,
    "watchdog-clock": watchdog_clock,
    "span-discipline": span_discipline,
    "prof-overhead": prof_overhead,
    "serve-write-plane": serve_write_plane,
}

__all__ = ["RULES"]
