"""graftlint — the repo's self-hosted static-analysis + runtime sanitizer
plane.

PR 1 and PR 2 introduced invariants that nothing enforced: atomic
tmp+rename writes, fault transparency (``resilience.InjectedFault`` must
never be swallowed), validated SQL identifiers, a single blessed wire
layer for host<->device transfers, locked shared state, one retry
engine, and deterministic replay.  Regressions against any of these only
surfaced as chaos-test flakes.  This package is the cheap mechanical
check that keeps those expensive properties true as the code grows (the
b-bit-minwise argument applied to correctness tooling):

- :mod:`engine` — AST rule engine: per-rule suppression comments
  (``# graftlint: disable=RULE -- reason``), a committed baseline for
  grandfathered findings, machine-readable JSON output, and the v2
  whole-program driver (``--why`` witness chains, ``--changed``
  incremental mode, ``--graph``).
- :mod:`rules` — the per-file rule catalog (see LINTING.md).
- :mod:`graph` — the project import/call graph: per-file fact
  extraction, cross-module symbol resolution, digest-cached facts.
- :mod:`interproc` — the whole-program passes: cross-file
  sql-interp/retry-bypass taint, ``lease-fence`` protocol dominance +
  LeaseSupersededError exception flow, ``lock-order`` cycle detection,
  ``fault-seat-drift`` matrix cross-check, and graftrace's static
  layer — ``snapshot-publish`` (immutable-after-publish classes are
  never mutated post-construction, chased across calls) and
  ``atomic-swap`` (``__publish_slots__`` references only rebound
  whole, never read-modify-written).
- :mod:`runtime` — the runtime half: ``jax.transfer_guard`` wiring and a
  jit compile counter, asserting the cluster hot loop performs zero
  implicit host->device transfers within a bounded compile budget.

Run it: ``python -m tse1m_tpu.lint`` (or ``python -m tse1m_tpu.cli
lint``).  Exit 0 means every finding is fixed, suppressed with a reason,
or baselined.
"""

from .engine import (BASELINE_DEFAULT, Baseline, Finding, LintError,
                     lint_paths, lint_project, load_source, main,
                     repo_root, run_repo_lint)
from .rules import RULES

__all__ = ["BASELINE_DEFAULT", "Baseline", "Finding", "LintError", "RULES",
           "lint_paths", "lint_project", "load_source", "main",
           "repo_root", "run_repo_lint"]
