"""The graftlint rule engine.

Mechanics, in one place so every rule stays a pure AST visitor:

- **Targets** — by default the ``tse1m_tpu/`` package plus the repo's
  top-level driver scripts (``bench.py``).  Tests are deliberately out of
  scope: chaos drivers legitimately SIGKILL processes, monkeypatch
  clocks, and write files non-atomically.
- **Suppressions** — ``# graftlint: disable=rule-a,rule-b -- reason`` on
  the finding's line suppresses those rules for that line;
  ``# graftlint: disable-file=rule-a -- reason`` anywhere in the file
  suppresses the rule file-wide.  The ``-- reason`` tail is required by
  convention (LINTING.md) and surfaced in ``--json`` output so a
  reasonless suppression is visible in review.
- **Baseline** — a committed JSON file of grandfathered findings.  A
  finding matches a baseline entry on (rule, path, normalized source
  line text) with multiplicity, so edits elsewhere in the file don't
  invalidate it, while touching the offending line itself does.
  ``--write-baseline`` regenerates the file (preserving reasons of
  entries that still match); new findings then fail the run until fixed,
  suppressed, or explicitly re-baselined.
- **Output** — human lines (``path:line:col: rule: message``) or
  ``--json`` for machines (CI, and the ``cli all`` run-manifest step).

Exit codes: 0 clean, 1 non-baselined findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import asdict, dataclass, field

BASELINE_DEFAULT = os.path.join(os.path.dirname(__file__), "baseline.json")

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-file)="
    r"(?P<rules>[\w,-]+)"
    r"(?:\s+--\s*(?P<reason>.*))?")


class LintError(RuntimeError):
    """Non-baselined findings (carries the machine summary for the
    run-manifest step)."""

    def __init__(self, message: str, step_result: dict | None = None):
        super().__init__(message)
        self.step_result = step_result


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative, posix form
    line: int          # 1-based
    col: int           # 0-based
    message: str
    text: str = ""     # stripped source line (baseline matching key)
    baselined: bool = False
    suppressed: bool = False

    def key(self) -> tuple:
        return (self.rule, self.path, self.text)

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"


@dataclass
class FileSource:
    """One parsed target file, shared by every rule."""

    path: str                    # repo-relative posix
    abspath: str
    text: str
    lines: list[str]
    tree: ast.AST
    # line -> set of rule names disabled on that line; "*" = all
    line_disables: dict[int, set] = field(default_factory=dict)
    file_disables: set = field(default_factory=set)
    # (scope, rules) -> reason strings, for the JSON report
    suppress_reasons: list = field(default_factory=list)


def load_source(abspath: str, relpath: str) -> FileSource:
    with open(abspath, encoding="utf-8") as f:
        text = f.read()
    lines = text.splitlines()
    tree = ast.parse(text, filename=relpath)
    src = FileSource(path=relpath, abspath=abspath, text=text, lines=lines,
                     tree=tree)
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        reason = (m.group("reason") or "").strip()
        if m.group(1) == "disable-file":
            src.file_disables |= rules
        else:
            # A trailing comment suppresses its own line; a standalone
            # comment line suppresses the NEXT line (long statements).
            target = i + 1 if line.strip().startswith("#") else i
            src.line_disables.setdefault(target, set()).update(rules)
        src.suppress_reasons.append(
            {"line": i, "scope": m.group(1), "rules": sorted(rules),
             "reason": reason})
    return src


class Baseline:
    """The committed set of grandfathered findings.

    Entries carry a multiplicity ``count`` (identical offending lines in
    one file collapse into one entry) and a human ``reason``."""

    def __init__(self, entries: list[dict] | None = None):
        self.entries = entries or []
        self._budget: dict[tuple, int] = {}
        for e in self.entries:
            k = (e["rule"], e["path"], e["text"])
            self._budget[k] = self._budget.get(k, 0) + int(e.get("count", 1))
        self._used: dict[tuple, int] = {}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls([])
        with open(path, encoding="utf-8") as f:
            return cls(json.load(f).get("findings", []))

    def absorb(self, finding: Finding) -> bool:
        """True (and consume one unit of budget) if the finding is
        grandfathered."""
        k = finding.key()
        used = self._used.get(k, 0)
        if used < self._budget.get(k, 0):
            self._used[k] = used + 1
            return True
        return False

    def stale_entries(self) -> list[dict]:
        """Entries whose budget was never (fully) consumed — the finding
        they grandfathered was fixed and they can be deleted."""
        out = []
        for e in self.entries:
            k = (e["rule"], e["path"], e["text"])
            if self._used.get(k, 0) < self._budget.get(k, 0):
                out.append(e)
                # report each key once even when count > 1
                self._used[k] = self._budget[k]
        return out

    @staticmethod
    def write(path: str, findings: list[Finding],
              old: "Baseline | None" = None,
              default_reason: str = "grandfathered pre-graftlint finding") \
            -> int:
        """Serialize ``findings`` (the non-suppressed ones) as the new
        baseline, keeping the reason of any entry that already existed."""
        reasons = {}
        if old is not None:
            for e in old.entries:
                reasons[(e["rule"], e["path"], e["text"])] = \
                    e.get("reason", default_reason)
        grouped: dict[tuple, dict] = {}
        for f in findings:
            if f.suppressed:
                continue
            k = f.key()
            if k in grouped:
                grouped[k]["count"] += 1
            else:
                grouped[k] = {"rule": f.rule, "path": f.path, "line": f.line,
                              "text": f.text, "count": 1,
                              "message": f.message,
                              "reason": reasons.get(k, default_reason)}
        payload = {"comment": "graftlint baseline — grandfathered findings. "
                              "Matching is (rule, path, line text) with "
                              "multiplicity; fix the line or re-run "
                              "--write-baseline to update.",
                   "findings": sorted(grouped.values(),
                                      key=lambda e: (e["path"], e["line"],
                                                     e["rule"]))}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
        os.replace(tmp, path)
        return len(grouped)


def repo_root() -> str:
    """The directory holding the ``tse1m_tpu`` package (== the repo root
    in every supported layout)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_targets(root: str | None = None) -> list[str]:
    root = root or repo_root()
    targets = []
    pkg = os.path.join(root, "tse1m_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                targets.append(os.path.join(dirpath, name))
    for script in ("bench.py",):
        p = os.path.join(root, script)
        if os.path.exists(p):
            targets.append(p)
    return targets


def lint_paths(paths: list[str], rules: dict | None = None,
               root: str | None = None,
               baseline: Baseline | None = None) -> list[Finding]:
    """Run ``rules`` over ``paths``; returns every finding with its
    ``suppressed``/``baselined`` flags resolved (callers filter)."""
    from .rules import RULES

    rules = rules if rules is not None else RULES
    root = root or repo_root()
    findings: list[Finding] = []
    for abspath in paths:
        rel = os.path.relpath(os.path.abspath(abspath), root)
        rel = rel.replace(os.sep, "/")
        try:
            src = load_source(abspath, rel)
        except (OSError, SyntaxError) as e:
            findings.append(Finding(rule="parse-error", path=rel, line=1,
                                    col=0, message=f"could not lint: {e}"))
            continue
        for name, rule_fn in rules.items():
            for f in rule_fn(src):
                f.rule = name
                if not f.text and 1 <= f.line <= len(src.lines):
                    f.text = src.lines[f.line - 1].strip()
                disabled = src.line_disables.get(f.line, set())
                if (name in src.file_disables or name in disabled
                        or "*" in disabled):
                    f.suppressed = True
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if baseline is not None:
        for f in findings:
            if not f.suppressed:
                f.baselined = baseline.absorb(f)
    return findings


def summarize(findings: list[Finding],
              stale: list[dict] | None = None) -> dict:
    new = [f for f in findings if not f.suppressed and not f.baselined]
    by_rule: dict[str, int] = {}
    for f in new:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "ok": not new,
        "new_findings": len(new),
        "baselined": sum(1 for f in findings if f.baselined),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "by_rule": dict(sorted(by_rule.items())),
        "stale_baseline_entries": len(stale or []),
    }


def run_repo_lint(baseline_path: str = BASELINE_DEFAULT,
                  root: str | None = None) -> dict:
    """Programmatic whole-repo lint (the ``cli all`` manifest step).

    Returns the JSON summary when clean; raises :class:`LintError`
    carrying the summary when there are non-baselined findings."""
    root = root or repo_root()
    baseline = Baseline.load(baseline_path)
    findings = lint_paths(default_targets(root), root=root,
                          baseline=baseline)
    summary = summarize(findings, baseline.stale_entries())
    if not summary["ok"]:
        new = [f for f in findings if not f.suppressed and not f.baselined]
        detail = "; ".join(f"{f.location()} {f.rule}" for f in new[:5])
        raise LintError(
            f"graftlint: {len(new)} non-baselined finding(s): {detail}",
            step_result=summary)
    return summary


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tse1m_tpu.lint",
        description="graftlint: enforce the repo's JAX, DB and resilience "
                    "invariants (rule catalog: LINTING.md)")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: tse1m_tpu/ + bench.py)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=BASELINE_DEFAULT,
                    help="baseline file (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "(keeps reasons of entries that still match)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    args = ap.parse_args(argv)

    from .rules import RULES

    rules = RULES
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - set(RULES)
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}; "
                  f"available: {', '.join(sorted(RULES))}", file=sys.stderr)
            return 2
        rules = {k: v for k, v in RULES.items() if k in wanted}

    root = repo_root()
    paths = ([os.path.abspath(p) for p in args.paths] if args.paths
             else default_targets(root))
    old = Baseline.load(args.baseline)
    baseline = None if (args.no_baseline or args.write_baseline) else old
    findings = lint_paths(paths, rules=rules, root=root, baseline=baseline)

    if args.write_baseline:
        n = Baseline.write(args.baseline, findings, old=old)
        print(f"graftlint: baseline rewritten with {n} entr"
              f"{'y' if n == 1 else 'ies'} -> {args.baseline}")
        return 0

    # Stale-entry detection only makes sense against the full target set:
    # an explicit-path run never visits most baselined files.
    stale = (baseline.stale_entries()
             if baseline is not None and not args.paths else [])
    summary = summarize(findings, stale)
    new = [f for f in findings if not f.suppressed and not f.baselined]
    if args.json:
        report = dict(summary)
        report["findings"] = [asdict(f) for f in new]
        report["stale_baseline"] = stale
        print(json.dumps(report, indent=2))
    else:
        for f in new:
            print(f"{f.location()}: {f.rule}: {f.message}")
        for e in stale:
            print(f"note: stale baseline entry ({e['rule']} at {e['path']}: "
                  f"{e['text'][:60]!r}) — finding fixed, entry can be "
                  "removed", file=sys.stderr)
        print(f"graftlint: {summary['new_findings']} new, "
              f"{summary['baselined']} baselined, "
              f"{summary['suppressed']} suppressed"
              + (f", {len(stale)} stale baseline entries" if stale else ""),
              file=sys.stderr)
    return 1 if new else 0
