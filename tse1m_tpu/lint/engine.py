"""The graftlint rule engine.

Mechanics, in one place so every rule stays a pure AST visitor:

- **Targets** — by default the ``tse1m_tpu/`` package plus the repo's
  top-level driver scripts (``bench.py``).  Tests are deliberately out of
  scope: chaos drivers legitimately SIGKILL processes, monkeypatch
  clocks, and write files non-atomically.
- **Suppressions** — ``# graftlint: disable=rule-a,rule-b -- reason`` on
  the finding's line suppresses those rules for that line;
  ``# graftlint: disable-file=rule-a -- reason`` anywhere in the file
  suppresses the rule file-wide.  The ``-- reason`` tail is required by
  convention (LINTING.md) and surfaced in ``--json`` output so a
  reasonless suppression is visible in review.
- **Baseline** — a committed JSON file of grandfathered findings.  A
  finding matches a baseline entry on (rule, path, normalized source
  line text) with multiplicity, so edits elsewhere in the file don't
  invalidate it, while touching the offending line itself does.
  ``--write-baseline`` regenerates the file (preserving reasons of
  entries that still match); new findings then fail the run until fixed,
  suppressed, or explicitly re-baselined.
- **Output** — human lines (``path:line:col: rule: message``) or
  ``--json`` for machines (CI, and the ``cli all`` run-manifest step).

Exit codes: 0 clean, 1 non-baselined findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
import time
from dataclasses import asdict, dataclass, field

BASELINE_DEFAULT = os.path.join(os.path.dirname(__file__), "baseline.json")

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-file)="
    r"(?P<rules>[\w,-]+)"
    r"(?:\s+--\s*(?P<reason>.*))?")


class LintError(RuntimeError):
    """Non-baselined findings (carries the machine summary for the
    run-manifest step)."""

    def __init__(self, message: str, step_result: dict | None = None):
        super().__init__(message)
        self.step_result = step_result


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative, posix form
    line: int          # 1-based
    col: int           # 0-based
    message: str
    text: str = ""     # stripped source line (baseline matching key)
    baselined: bool = False
    suppressed: bool = False
    # Interprocedural findings carry the call chain that proves them
    # (``--why RULE:path:line`` prints it); per-file findings leave it
    # empty.  Not part of the baseline key.
    witness: list = field(default_factory=list)

    def key(self) -> tuple:
        return (self.rule, self.path, self.text)

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"


@dataclass
class FileSource:
    """One parsed target file, shared by every rule."""

    path: str                    # repo-relative posix
    abspath: str
    text: str
    lines: list[str]
    tree: ast.AST
    # line -> set of rule names disabled on that line; "*" = all
    line_disables: dict[int, set] = field(default_factory=dict)
    file_disables: set = field(default_factory=set)
    # (scope, rules) -> reason strings, for the JSON report
    suppress_reasons: list = field(default_factory=list)


def load_source(abspath: str, relpath: str) -> FileSource:
    with open(abspath, encoding="utf-8") as f:
        text = f.read()
    lines = text.splitlines()
    tree = ast.parse(text, filename=relpath)
    src = FileSource(path=relpath, abspath=abspath, text=text, lines=lines,
                     tree=tree)
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        reason = (m.group("reason") or "").strip()
        if m.group(1) == "disable-file":
            src.file_disables |= rules
        else:
            # A trailing comment suppresses its own line; a standalone
            # comment line suppresses the NEXT line (long statements).
            target = i + 1 if line.strip().startswith("#") else i
            src.line_disables.setdefault(target, set()).update(rules)
        src.suppress_reasons.append(
            {"line": i, "scope": m.group(1), "rules": sorted(rules),
             "reason": reason})
    # A standalone suppression directly above a DECORATED def targets
    # the first decorator line; findings may anchor anywhere in the
    # decorator stack (multi-line decorators) or on the `def` line
    # itself, so the disable set spreads across the whole span.
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)) and node.decorator_list:
            first = min(d.lineno for d in node.decorator_list)
            covered = src.line_disables.get(first)
            if not covered:
                continue
            last = max([getattr(d, "end_lineno", d.lineno)
                        for d in node.decorator_list] + [node.lineno])
            for ln in range(first, last + 1):
                src.line_disables.setdefault(ln, set()).update(covered)
    return src


class Baseline:
    """The committed set of grandfathered findings.

    Entries carry a multiplicity ``count`` (identical offending lines in
    one file collapse into one entry) and a human ``reason``."""

    def __init__(self, entries: list[dict] | None = None):
        self.entries = entries or []
        self._budget: dict[tuple, int] = {}
        for e in self.entries:
            k = (e["rule"], e["path"], e["text"])
            self._budget[k] = self._budget.get(k, 0) + int(e.get("count", 1))
        self._used: dict[tuple, int] = {}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls([])
        with open(path, encoding="utf-8") as f:
            return cls(json.load(f).get("findings", []))

    def absorb(self, finding: Finding) -> bool:
        """True (and consume one unit of budget) if the finding is
        grandfathered."""
        k = finding.key()
        used = self._used.get(k, 0)
        if used < self._budget.get(k, 0):
            self._used[k] = used + 1
            return True
        return False

    def stale_entries(self) -> list[dict]:
        """Entries whose budget was never (fully) consumed — the finding
        they grandfathered was fixed and they can be deleted."""
        out = []
        for e in self.entries:
            k = (e["rule"], e["path"], e["text"])
            if self._used.get(k, 0) < self._budget.get(k, 0):
                out.append(e)
                # report each key once even when count > 1
                self._used[k] = self._budget[k]
        return out

    @staticmethod
    def write(path: str, findings: list[Finding],
              old: "Baseline | None" = None,
              default_reason: str = "grandfathered pre-graftlint finding") \
            -> int:
        """Serialize ``findings`` (the non-suppressed ones) as the new
        baseline, keeping the reason of any entry that already existed."""
        reasons = {}
        if old is not None:
            for e in old.entries:
                reasons[(e["rule"], e["path"], e["text"])] = \
                    e.get("reason", default_reason)
        grouped: dict[tuple, dict] = {}
        for f in findings:
            if f.suppressed:
                continue
            k = f.key()
            if k in grouped:
                grouped[k]["count"] += 1
            else:
                grouped[k] = {"rule": f.rule, "path": f.path, "line": f.line,
                              "text": f.text, "count": 1,
                              "message": f.message,
                              "reason": reasons.get(k, default_reason)}
        payload = {"comment": "graftlint baseline — grandfathered findings. "
                              "Matching is (rule, path, line text) with "
                              "multiplicity; fix the line or re-run "
                              "--write-baseline to update.",
                   "findings": sorted(grouped.values(),
                                      key=lambda e: (e["path"], e["line"],
                                                     e["rule"]))}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
        os.replace(tmp, path)
        return len(grouped)


def repo_root() -> str:
    """The directory holding the ``tse1m_tpu`` package (== the repo root
    in every supported layout)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_targets(root: str | None = None) -> list[str]:
    root = root or repo_root()
    targets = []
    pkg = os.path.join(root, "tse1m_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                targets.append(os.path.join(dirpath, name))
    for script in ("bench.py",):
        p = os.path.join(root, script)
        if os.path.exists(p):
            targets.append(p)
    return targets


def lint_paths(paths: list[str], rules: dict | None = None,
               root: str | None = None,
               baseline: Baseline | None = None) -> list[Finding]:
    """Run ``rules`` over ``paths``; returns every finding with its
    ``suppressed``/``baselined`` flags resolved (callers filter)."""
    from .rules import RULES

    rules = rules if rules is not None else RULES
    root = root or repo_root()
    findings: list[Finding] = []
    for abspath in paths:
        rel = os.path.relpath(os.path.abspath(abspath), root)
        rel = rel.replace(os.sep, "/")
        try:
            src = load_source(abspath, rel)
        except (OSError, SyntaxError) as e:
            findings.append(Finding(rule="parse-error", path=rel, line=1,
                                    col=0, message=f"could not lint: {e}"))
            continue
        for name, rule_fn in rules.items():
            for f in rule_fn(src):
                f.rule = name
                if not f.text and 1 <= f.line <= len(src.lines):
                    f.text = src.lines[f.line - 1].strip()
                disabled = src.line_disables.get(f.line, set())
                if (name in src.file_disables or name in disabled
                        or "*" in disabled):
                    f.suppressed = True
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if baseline is not None:
        for f in findings:
            if not f.suppressed:
                f.baselined = baseline.absorb(f)
    return findings


def _resolve_flags(findings: list[Finding], sources: dict,
                   root: str) -> None:
    """Fill text + suppression flags for findings whose file wasn't in
    the per-file loop (interprocedural findings can anchor anywhere,
    including tests/ci_fault_matrix.py)."""
    for f in findings:
        src = sources.get(f.path)
        if src is None:
            try:
                src = load_source(os.path.join(root, f.path), f.path)
            except (OSError, SyntaxError):
                continue
            sources[f.path] = src
        if not f.text and 1 <= f.line <= len(src.lines):
            f.text = src.lines[f.line - 1].strip()
        disabled = src.line_disables.get(f.line, set())
        if (f.rule in src.file_disables or f.rule in disabled
                or "*" in disabled):
            f.suppressed = True


def lint_project(report_paths: list[str], graph_paths: list[str],
                 rules: dict | None = None,
                 project_rules: set | None = None,
                 root: str | None = None,
                 baseline: "Baseline | None" = None,
                 use_cache: bool = True,
                 matrix_path: str | None = None):
    """The whole-program lint driver.

    Per-file rules run over ``report_paths``; the interprocedural
    passes (lint/interproc.py) run over the ProjectGraph built from
    ``graph_paths`` (a superset — unchanged files come from the digest
    cache).  Returns ``(findings, stats)`` where stats carries the
    graph/cache/wall numbers the run manifest records."""
    from .graph import build_graph
    from .interproc import run_project_passes

    t0 = time.perf_counter()
    root = root or repo_root()
    findings = lint_paths(report_paths, rules=rules, root=root,
                          baseline=None)
    sources: dict = {}
    prebuilt: dict = {}
    for abspath in report_paths:
        rel = os.path.relpath(os.path.abspath(abspath), root)
        rel = rel.replace(os.sep, "/")
        try:
            src = load_source(abspath, rel)
        except (OSError, SyntaxError):
            continue
        sources[rel] = src
        prebuilt[os.path.abspath(abspath)] = (rel, src.text, src.tree)
    graph = build_graph(graph_paths, root=root, sources=prebuilt,
                        use_cache=use_cache)
    if matrix_path is None:
        # Fixture runs carry their own seat inventory: a linted file
        # named ci_fault_matrix.py overrides tests/ci_fault_matrix.py.
        for abspath in report_paths:
            if os.path.basename(abspath) == "ci_fault_matrix.py":
                matrix_path = os.path.abspath(abspath)
                break
    project = run_project_passes(graph, wanted_rules=project_rules,
                                 matrix_path=matrix_path)
    _resolve_flags(project, sources, root)
    findings += project
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if baseline is not None:
        for f in findings:
            if not f.suppressed:
                f.baselined = baseline.absorb(f)
    stats = {
        "wall_s": round(time.perf_counter() - t0, 3),
        "graph_files": len(graph.facts),
        "graph_functions": len(graph.functions),
        "graph_call_edges": sum(len(v) for v in graph.calls.values()),
        "cache_files": graph.cache_files,
        "cache_hits": graph.cache_hits,
        "cache_hit_rate": round(
            graph.cache_hits / graph.cache_files, 4)
        if graph.cache_files else 0.0,
    }
    return findings, stats, graph


def _git_changed(root: str, ref: str) -> set:
    """Repo-relative paths of files that differ from ``ref`` (committed
    diff + working tree + untracked)."""
    import subprocess

    out: set = set()
    for cmd in (["git", "diff", "--name-only", ref, "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, cwd=root, capture_output=True,
                                  text=True, timeout=30, check=True)
        except (OSError, subprocess.SubprocessError) as e:
            raise RuntimeError(
                f"--changed {ref}: {' '.join(cmd)} failed: {e}") from e
        out |= {ln.strip() for ln in proc.stdout.splitlines()
                if ln.strip().endswith(".py")}
    return out


def changed_closure(root: str, ref: str, targets: list[str]):
    """(report_paths, info) for ``--changed REF``: files whose content
    digest differs from the cache/ref plus their reverse-dependency
    closure from the import graph."""
    from .graph import build_graph

    changed = _git_changed(root, ref)
    rel_targets = {os.path.relpath(os.path.abspath(p), root)
                   .replace(os.sep, "/"): p for p in targets}
    graph = build_graph(targets, root=root, use_cache=True)
    seed = {rel for rel in changed if rel in rel_targets}
    closure = graph.reverse_closure(seed) & set(rel_targets)
    report = [rel_targets[rel] for rel in sorted(closure)]
    info = {"ref": ref, "changed": sorted(seed),
            "closure": sorted(closure)}
    return report, info


def summarize(findings: list[Finding],
              stale: list[dict] | None = None) -> dict:
    new = [f for f in findings if not f.suppressed and not f.baselined]
    by_rule: dict[str, int] = {}
    for f in new:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    # Every finding per rule (suppressed/baselined included): the run
    # manifest's evidence that a rule RAN, not just that it was clean.
    by_rule_total: dict[str, int] = {}
    for f in findings:
        by_rule_total[f.rule] = by_rule_total.get(f.rule, 0) + 1
    return {
        "ok": not new,
        "new_findings": len(new),
        "baselined": sum(1 for f in findings if f.baselined),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "by_rule": dict(sorted(by_rule.items())),
        "by_rule_total": dict(sorted(by_rule_total.items())),
        "stale_baseline_entries": len(stale or []),
    }


def run_repo_lint(baseline_path: str = BASELINE_DEFAULT,
                  root: str | None = None) -> dict:
    """Programmatic whole-repo lint (the ``cli all`` manifest step):
    per-file rules plus the interprocedural passes, with the graph's
    wall time / cache hit rate / rule counts in the summary.

    Returns the JSON summary when clean; raises :class:`LintError`
    carrying the summary when there are non-baselined findings."""
    root = root or repo_root()
    baseline = Baseline.load(baseline_path)
    targets = default_targets(root)
    findings, stats, _ = lint_project(targets, targets,
                                      baseline=baseline, root=root)
    summary = summarize(findings, baseline.stale_entries())
    summary.update(stats)
    if not summary["ok"]:
        new = [f for f in findings if not f.suppressed and not f.baselined]
        detail = "; ".join(f"{f.location()} {f.rule}" for f in new[:5])
        raise LintError(
            f"graftlint: {len(new)} non-baselined finding(s): {detail}",
            step_result=summary)
    return summary


def _parse_why(spec: str):
    """'RULE:path:line' -> (rule, path, line) or None."""
    parts = spec.rsplit(":", 2)
    if len(parts) != 3 or not parts[2].isdigit():
        return None
    return parts[0], parts[1].replace(os.sep, "/"), int(parts[2])


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tse1m_tpu.lint",
        description="graftlint: enforce the repo's JAX, DB and resilience "
                    "invariants (rule catalog: LINTING.md)")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: tse1m_tpu/ + bench.py)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=BASELINE_DEFAULT,
                    help="baseline file (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "(keeps reasons of entries that still match)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--changed", metavar="REF", default=None,
                    help="incremental mode: lint only files whose content "
                         "differs from REF plus their reverse-dependency "
                         "closure (interprocedural passes still see the "
                         "whole graph, via the digest cache)")
    ap.add_argument("--why", metavar="RULE:PATH:LINE", default=None,
                    help="explain one finding: print the interprocedural "
                         "witness chain that proves it")
    ap.add_argument("--graph", action="store_true",
                    help="print the project import/call-graph summary "
                         "(with per-file edges for explicit paths)")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and do not write the digest cache")
    args = ap.parse_args(argv)

    from .interproc import PROJECT_RULES
    from .rules import RULES

    t0 = time.perf_counter()
    all_rules = set(RULES) | set(PROJECT_RULES)
    rules = RULES
    project_rules: set | None = None
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - all_rules
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}; "
                  f"available: {', '.join(sorted(all_rules))}",
                  file=sys.stderr)
            return 2
        rules = {k: v for k, v in RULES.items() if k in wanted}
        project_rules = wanted & set(PROJECT_RULES)

    root = repo_root()
    targets = default_targets(root)
    changed_info = None
    if args.changed and args.paths:
        print("--changed and explicit paths are mutually exclusive",
              file=sys.stderr)
        return 2
    if args.changed:
        try:
            report_paths, changed_info = changed_closure(
                root, args.changed, targets)
        except RuntimeError as e:
            print(str(e), file=sys.stderr)
            return 2
        graph_paths = targets
    elif args.paths:
        report_paths = [os.path.abspath(p) for p in args.paths]
        graph_paths = report_paths
    else:
        report_paths = graph_paths = targets
    old = Baseline.load(args.baseline)
    baseline = None if (args.no_baseline or args.write_baseline) else old
    findings, stats, graph = lint_project(
        report_paths, graph_paths, rules=rules,
        project_rules=project_rules, root=root, baseline=baseline,
        use_cache=not args.no_cache)

    if args.graph:
        report = {"files": stats["graph_files"],
                  "functions": stats["graph_functions"],
                  "call_edges": stats["graph_call_edges"],
                  "cache_hit_rate": stats["cache_hit_rate"]}
        if args.paths:
            rels = {os.path.relpath(os.path.abspath(p), root)
                    .replace(os.sep, "/") for p in args.paths}
            report["edges"] = [
                f"{q} -> {t}"
                for q, edges in sorted(graph.calls.items())
                if graph.fn_file.get(q) in rels
                for t, _ in edges]
        print(json.dumps(report, indent=2))
        return 0

    if args.why:
        spec = _parse_why(args.why)
        if spec is None:
            print("--why wants RULE:path:line", file=sys.stderr)
            return 2
        rule, path, line = spec
        hits = [f for f in findings
                if f.rule == rule and f.path == path and f.line == line]
        if not hits:
            print(f"no {rule} finding at {path}:{line} (run without "
                  "--why to list findings)", file=sys.stderr)
            return 2
        for f in hits:
            print(f"{f.location()}: {f.rule}: {f.message}")
            for step in (f.witness or ["(single-file finding — no "
                                       "interprocedural chain)"]):
                print(f"    {step}")
        return 0

    if args.write_baseline:
        n = Baseline.write(args.baseline, findings, old=old)
        print(f"graftlint: baseline rewritten with {n} entr"
              f"{'y' if n == 1 else 'ies'} -> {args.baseline}")
        return 0

    # Stale-entry detection only makes sense against the full target set:
    # an explicit-path or --changed run never visits most baselined files.
    full_run = not args.paths and not args.changed
    stale = (baseline.stale_entries()
             if baseline is not None and full_run else [])
    summary = summarize(findings, stale)
    summary.update(stats)
    summary["wall_s"] = round(time.perf_counter() - t0, 3)
    if changed_info is not None:
        summary["changed"] = changed_info
    new = [f for f in findings if not f.suppressed and not f.baselined]
    if args.json:
        report = dict(summary)
        report["findings"] = [asdict(f) for f in new]
        report["stale_baseline"] = stale
        print(json.dumps(report, indent=2))
    else:
        for f in new:
            print(f"{f.location()}: {f.rule}: {f.message}")
        for e in stale:
            print(f"note: stale baseline entry ({e['rule']} at {e['path']}: "
                  f"{e['text'][:60]!r}) — finding fixed, entry can be "
                  "removed", file=sys.stderr)
        scope = (f"{len(changed_info['closure'])} file(s) in the changed "
                 f"closure of {changed_info['ref']}, "
                 if changed_info is not None else "")
        print(f"graftlint: {summary['new_findings']} new, "
              f"{summary['baselined']} baselined, "
              f"{summary['suppressed']} suppressed"
              + (f", {len(stale)} stale baseline entries" if stale else "")
              + f" ({scope}wall {summary['wall_s']}s, cache hit rate "
              f"{summary['cache_hit_rate']})",
              file=sys.stderr)
    return 1 if new else 0
