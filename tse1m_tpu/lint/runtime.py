"""The runtime half of the sanitizer plane.

The static rules can prove a transfer SEAT sits in the wire layer; only
the runtime can prove the hot loop actually performs **zero implicit
host->device transfers** and stays within a **bounded compile budget**.
Two mechanisms, both degradation-gated (no hard dependency on any
particular jax version):

- :func:`no_implicit_transfers` — ``jax.transfer_guard_host_to_device
  ("disallow")``: any *implicit* staging (a numpy array or Python scalar
  silently uploaded as a jit argument) raises, while the wire layer's
  explicit ``device_put``/``jnp.asarray`` conversions stay legal.  This
  is exactly the regression class PR 2 fought: a stray np scalar in a
  jit call re-ships bytes every chunk.
- :class:`CompileCounter` — counts XLA backend compiles via
  ``jax.monitoring``'s ``/jax/core/compile/backend_compile_duration``
  event.  A warm steady-state run must compile NOTHING; a growing count
  between bench rounds is the silent-recompile signature (shape drift,
  weak-type flapping, cache-key churn).

:func:`sanitized` combines both for the bench / test harness:

    with sanitized(compile_budget=0) as san:
        cluster_sessions(items, params)        # warm run
    # raises SanitizerViolation if anything compiled

jax.monitoring has no listener-removal API, so ONE module listener is
installed lazily and counters snapshot its monotonic total.
"""

from __future__ import annotations

import contextlib
import threading

from ..utils.logging import get_logger

log = get_logger("lint.runtime")

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_state_lock = threading.Lock()
_compiles_total = 0
_listener_installed = False
_listener_ok: bool | None = None


class SanitizerViolation(AssertionError):
    """The hot loop broke a runtime invariant (implicit transfer or
    compile budget)."""


def _install_listener() -> bool:
    """Idempotently install the global compile-event listener; returns
    availability."""
    global _listener_installed, _listener_ok
    with _state_lock:
        if _listener_installed:
            return bool(_listener_ok)
        _listener_installed = True
        try:
            import jax.monitoring as monitoring

            def _on_event(event: str, duration: float = 0.0, **kw) -> None:
                global _compiles_total
                if event == _COMPILE_EVENT:
                    with _state_lock:
                        _compiles_total += 1

            monitoring.register_event_duration_secs_listener(_on_event)
            _listener_ok = True
        except Exception as e:  # graftlint: disable=broad-except -- jax absent/too old; sanitizer degrades to unavailable
            log.warning("compile counter unavailable (%s: %s)",
                        type(e).__name__, e)
            _listener_ok = False
    return bool(_listener_ok)


def compiles_so_far() -> int | None:
    """Process-lifetime backend-compile count (None when the monitoring
    hook is unavailable)."""
    if not _install_listener():
        return None
    with _state_lock:
        return _compiles_total


class CompileCounter:
    """Context manager: XLA backend compiles that happened inside the
    block.  ``count`` is None when jax.monitoring is unavailable."""

    def __init__(self) -> None:
        self.count: int | None = None
        self._start: int | None = None

    def __enter__(self) -> "CompileCounter":
        self._start = compiles_so_far()
        return self

    def __exit__(self, *exc) -> None:
        end = compiles_so_far()
        if self._start is not None and end is not None:
            self.count = end - self._start


@contextlib.contextmanager
def no_implicit_transfers():
    """Disallow implicit host->device staging inside the block (explicit
    device_put/jnp.asarray conversions — the wire layer — stay legal).
    Degrades to a no-op when this jax has no transfer guard."""
    try:
        import jax

        guard = jax.transfer_guard_host_to_device
    except (ImportError, AttributeError) as e:
        log.warning("transfer guard unavailable (%s: %s)",
                    type(e).__name__, e)
        yield False
        return
    with guard("disallow"):
        yield True


class SanitizerReport:
    """What the sanitized block observed — embeddable in bench JSON and
    the run manifest."""

    def __init__(self) -> None:
        self.transfer_guard_active = False
        self.compile_count: int | None = None
        self.compile_budget: int | None = None

    def as_dict(self) -> dict:
        return {
            "sanitizer_transfer_guard": self.transfer_guard_active,
            "sanitizer_compile_count": self.compile_count,
            "sanitizer_compile_budget": self.compile_budget,
        }


@contextlib.contextmanager
def sanitized(compile_budget: int | None = None):
    """Run the block under the full sanitizer: implicit H2D transfers
    raise immediately (via the transfer guard), and on exit the compile
    count is checked against ``compile_budget`` (None = record only).

    Yields a :class:`SanitizerReport`; raises
    :class:`SanitizerViolation` when the budget is exceeded."""
    report = SanitizerReport()
    report.compile_budget = compile_budget
    with no_implicit_transfers() as guard_on:
        report.transfer_guard_active = bool(guard_on)
        with CompileCounter() as counter:
            yield report
    report.compile_count = counter.count
    if (compile_budget is not None and counter.count is not None
            and counter.count > compile_budget):
        raise SanitizerViolation(
            f"compile budget exceeded: {counter.count} XLA compiles in a "
            f"sanitized block budgeted for {compile_budget} — a warm hot "
            "loop should not be compiling (shape drift / weak-type "
            "flapping / cache-key churn)")


def self_check() -> dict:
    """Cheap per-run proof that the sanitizer plane works on this
    process's jax: a tiny jitted op under the guard, warm call budget 0.
    Returns the report dict (the ``cli all`` manifest step embeds it)."""
    try:
        import jax
        import jax.numpy as jnp
    except ImportError:
        return {"sanitizer_available": False}
    f = jax.jit(lambda v: v * 2 + 1)
    x = jnp.arange(8, dtype=jnp.int32)
    f(x).block_until_ready()  # compile outside the sanitized window
    with sanitized(compile_budget=0) as report:
        f(x).block_until_ready()
    out = report.as_dict()
    out["sanitizer_available"] = True
    return out


__all__ = ["CompileCounter", "SanitizerReport", "SanitizerViolation",
           "compiles_so_far", "no_implicit_transfers", "sanitized",
           "self_check"]
