"""graftlint's whole-program layer: the project import/call graph.

The per-file rules (rules.py) are pure AST visitors — fast, but blind
past a file boundary, which is exactly where the pod plane's protocol
bugs live (a wrapper two calls away laundering an f-string into
``cursor.execute``, an append reached on a path nobody fenced).  This
module builds the shared substrate the interprocedural passes
(interproc.py) run on:

- **FileFacts** — one JSON-serializable summary per target file: module
  name, import table, per-function call sites (with receiver/arg facts,
  lock context, try/except context, statement order), class symbol
  tables (methods, lock attrs, ``self.x = ClassName(...)`` types,
  publication markers: ``frozen=True`` dataclasses,
  ``__immutable_after_publish__``, ``__publish_slots__``), attribute
  writes (store/item/aug, multi-target), name->attribute aliases,
  parameter annotations, direct raises, and ``fault_point(...)``
  seats.  Facts are everything the fixed-point passes need; the AST
  itself is never kept.
- **Symbol resolution** — dotted call strings resolve to fully
  qualified function names across modules: plain names through the
  import table (following one re-export hop), ``self.meth`` through the
  class and its bases, ``var.meth`` through constructor-assignment
  types, ``self.attr.meth`` through ``__init__``-assigned attribute
  types, and one level of ``self.helper(...).meth`` through the
  helper's return type (the ``range_store(r).append`` shape).
- **Digest cache** — facts are cached per file keyed by a blake2b
  content digest (the ``cluster/store.py`` content-addressing idiom):
  an incremental ``cli lint`` re-extracts only edited files, and
  ``--changed`` mode uses the import graph's reverse-dependency closure
  to pick which files need their per-file rules re-run.

The graph is deliberately approximate where Python is dynamic: calls
through bare callables (``fn()`` on a parameter) stay unresolved and
the passes treat them as opaque.  Soundness here means "no false
finding on the real tree"; coverage comes from the resolution cases the
codebase actually uses.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from dataclasses import dataclass, field

CACHE_BASENAME = ".graftlint_cache.json"
_CACHE_VERSION = 4  # bump when the FileFacts shape changes

_SQL_EXEC_ATTRS = ("execute", "executemany", "executescript")
_SQL_TOKENS = ("SELECT", "INSERT", "UPDATE", "DELETE", "CREATE", "DROP",
               "ALTER", "COPY", "PRAGMA", "SET")


def content_digest(data: bytes) -> str:
    """16-hex blake2b content digest (store.py's digest idiom)."""
    return hashlib.blake2b(data, digest_size=8).hexdigest()


def module_name(relpath: str) -> str:
    """'tse1m_tpu/cluster/store.py' -> 'tse1m_tpu.cluster.store';
    package ``__init__.py`` files name the package itself."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = [x for x in p.split("/") if x]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _literal_text(node: ast.AST) -> str:
    """Concatenated literal fragments of a string expression."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        return "".join(v.value for v in node.values
                       if isinstance(v, ast.Constant)
                       and isinstance(v.value, str))
    if isinstance(node, ast.BinOp):
        return _literal_text(node.left) + _literal_text(node.right)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        return _literal_text(node.func.value)  # "...".format(...)
    return ""


def _looks_sql(node: ast.AST) -> bool:
    text = _literal_text(node).upper()
    return any(f"{t} " in text or text.startswith(t) for t in _SQL_TOKENS)


def _is_interpolated(node: ast.AST) -> bool:
    """The expression composes a string from non-literal parts."""
    if isinstance(node, ast.JoinedStr):
        return any(isinstance(v, ast.FormattedValue) for v in node.values)
    if isinstance(node, ast.BinOp) and isinstance(node.op,
                                                  (ast.Add, ast.Mod)):
        return True
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "format"):
        return bool(node.args or node.keywords)
    return False


def _all_params(args: ast.arguments) -> list:
    return [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]


def _ann_dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a parameter annotation: plain names,
    quoted forward refs, and the useful half of ``X | None``."""
    if node is None:
        return ""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.strip()
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        left = _ann_dotted(node.left)
        return left or _ann_dotted(node.right)
    if isinstance(node, ast.Subscript):  # Optional[X] / list[X]: outer
        return _dotted(node.value)
    return _dotted(node)


def _attr_write_of(target: ast.AST):
    """Decompose an assignment target into (recv dotted, attr, kind):
    ``obj.attr = ...`` -> (obj, attr, 'store'); ``obj.attr[...] = ...``
    -> (obj, attr, 'item'); ``name[...] = ...`` -> (name, '', 'item')
    — the alias shape the atomic-swap pass resolves.  None otherwise."""
    node = target
    kind = "store"
    while isinstance(node, ast.Starred):
        node = node.value
    while isinstance(node, ast.Subscript):
        kind = "item"
        node = node.value
    if isinstance(node, ast.Attribute):
        recv = _dotted(node.value)
        if recv:
            return recv, node.attr, kind
    elif isinstance(node, ast.Name) and kind == "item":
        return node.id, "", kind
    return None


class _FactsVisitor:
    """Source-order DFS over one parsed file, extracting FileFacts.

    Tracks, per call site: the enclosing function, the lock tokens held
    (lexically enclosing ``with <lock>`` items), and the enclosing
    broad/explicit-LSE try handlers (for the exception-flow pass)."""

    def __init__(self, relpath: str, tree: ast.AST):
        self.path = relpath
        self.module = module_name(relpath)
        self.imports: dict[str, str] = {}
        self.constants: dict[str, object] = {}
        self.module_locks: list[str] = []
        self.classes: dict[str, dict] = {}
        self.functions: list[dict] = []
        self._fn_stack: list[dict] = []
        self._cls_stack: list[str] = []
        self._locks_held: list[str] = []
        self._try_stack: list[list] = []
        self._call_idx = 0
        self._module_fn = self._new_fn("<module>", None, 0, [], [], {})
        self.functions.append(self._module_fn)
        self._visit_body(tree.body)

    # -- helpers ------------------------------------------------------------

    def _new_fn(self, name: str, cls: str | None, lineno: int,
                params: list, decorators: list, env: dict) -> dict:
        qual = ".".join(x for x in (self.module, cls, name) if x)
        return {"qual": qual, "name": name, "cls": cls, "line": lineno,
                "params": params, "decorators": decorators, "calls": [],
                "raises": [], "broad_handlers": [], "lock_sites": [],
                "var_types": {}, "returns_call": None,
                "param_defaults": {}, "param_annotations": {},
                "attr_writes": [], "var_aliases": {}, "str_eqs": {},
                "_env": env}

    def _fn(self) -> dict:
        return self._fn_stack[-1] if self._fn_stack else self._module_fn

    def _lock_token(self, expr: ast.AST) -> str | None:
        """Canonical cross-instance lock identity for a with-item, or
        None when the context manager is not a known lock."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and self._cls_stack):
            cls = self._cls_stack[-1]
            if expr.attr in self.classes.get(cls, {}).get("locks", []):
                return f"{self.module}.{cls}.{expr.attr}"
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"{self.module}.{expr.id}"
        return None

    # -- traversal ----------------------------------------------------------

    def _visit_body(self, body: list) -> None:
        for node in body:
            self._visit(node)

    def _visit(self, node: ast.AST) -> None:
        meth = getattr(self, f"_v_{type(node).__name__}", None)
        if meth is not None:
            meth(node)
        else:
            self._generic(node)

    def _generic(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    # imports ---------------------------------------------------------------

    def _v_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self.imports[alias.asname or alias.name.split(".")[0]] = \
                alias.name

    def _v_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level:
            base_parts = self.module.split(".")
            base_parts = base_parts[:max(len(base_parts) - node.level, 0)]
            base = ".".join(base_parts)
            target = ".".join(x for x in (base, node.module or "") if x)
        else:
            target = node.module or ""
        for alias in node.names:
            if alias.name == "*":
                continue
            self.imports[alias.asname or alias.name] = \
                ".".join(x for x in (target, alias.name) if x)

    # defs ------------------------------------------------------------------

    def _v_ClassDef(self, node: ast.ClassDef) -> None:
        bases = [_dotted(b) for b in node.bases if _dotted(b)]
        entry = self.classes.setdefault(
            node.name, {"methods": [], "bases": bases, "locks": [],
                        "lock_kinds": {}, "attr_types": {},
                        "line": node.lineno})
        entry["bases"] = bases
        # Publication-discipline markers (graftrace's static layer):
        # @dataclass(frozen=True), __immutable_after_publish__, and the
        # __publish_slots__ tuple (lint/interproc.py snapshot-publish /
        # atomic-swap passes).
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and _dotted(dec.func).rsplit(
                    ".", 1)[-1] == "dataclass":
                for kw in dec.keywords:
                    if (kw.arg == "frozen"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is True):
                        entry["frozen"] = True
        for stmt in node.body:
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            tname = stmt.targets[0].id
            if tname == "__immutable_after_publish__" and isinstance(
                    stmt.value, ast.Constant):
                entry["immutable_after_publish"] = bool(stmt.value.value)
            elif tname == "__publish_slots__" and isinstance(
                    stmt.value, (ast.Tuple, ast.List)):
                entry["publish_slots"] = [
                    e.value for e in stmt.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
        # Pre-scan lock/type attrs so every method sees them regardless
        # of definition order relative to __init__.
        for inner in ast.walk(node):
            if not (isinstance(inner, ast.Assign)
                    and isinstance(inner.value, ast.Call)):
                continue
            callee = _dotted(inner.value.func)
            leaf = callee.rsplit(".", 1)[-1]
            for t in inner.targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                if leaf in ("Lock", "RLock"):
                    entry["locks"].append(t.attr)
                    entry["lock_kinds"][t.attr] = leaf
                elif callee and callee[:1].isalpha():
                    entry["attr_types"].setdefault(t.attr, callee)
        self._cls_stack.append(node.name)
        self._visit_body(node.body)
        self._cls_stack.pop()

    def _v_FunctionDef(self, node) -> None:
        cls = self._cls_stack[-1] if self._cls_stack else None
        env = {}
        for n in ast.walk(node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                env[n.targets[0].id] = n.value
        params = _all_params(node.args)
        decorators = [_dotted(d) or _dotted(getattr(d, "func", d))
                      for d in node.decorator_list]
        fn = self._new_fn(node.name, cls if not self._fn_stack else None,
                          node.lineno, params, decorators, env)
        if self._fn_stack:
            # Nested function: qualify under the parent so boundary
            # classification (the DB wrappers' inner op()) inherits.
            parent = self._fn_stack[-1]
            fn["qual"] = parent["qual"] + "." + node.name
            fn["parent"] = parent["qual"]
        elif cls is not None:
            self.classes.setdefault(
                cls, {"methods": [], "bases": [], "locks": [],
                      "attr_types": {}, "line": node.lineno})
            self.classes[cls]["methods"].append(node.name)
        args = node.args
        pos = args.posonlyargs + args.args
        for a, d in zip(pos[len(pos) - len(args.defaults):],
                        args.defaults):
            if isinstance(d, ast.Constant):
                fn["param_defaults"][a.arg] = d.value
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if isinstance(d, ast.Constant):
                fn["param_defaults"][a.arg] = d.value
        for a in pos + args.kwonlyargs:
            ann = _ann_dotted(a.annotation)
            if ann:
                fn["param_annotations"][a.arg] = ann
        self.functions.append(fn)
        for dec in node.decorator_list:
            self._visit(dec)
        self._fn_stack.append(fn)
        held, self._locks_held = self._locks_held, []
        trys, self._try_stack = self._try_stack, []
        self._visit_body(node.body)
        self._locks_held, self._try_stack = held, trys
        self._fn_stack.pop()

    _v_AsyncFunctionDef = _v_FunctionDef

    # statements ------------------------------------------------------------

    def _record_attr_writes(self, node, targets: list,
                            kind_override: str | None = None) -> None:
        fn = self._fn()
        multi = len(targets) > 1
        flat = []
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                multi = True
                flat.extend(t.elts)
            else:
                flat.append(t)
        for t in flat:
            rec = _attr_write_of(t)
            if rec is None:
                continue
            recv, attr, kind = rec
            fn["attr_writes"].append(
                {"recv": recv, "attr": attr,
                 "kind": kind_override or kind, "multi": multi,
                 "line": node.lineno, "col": node.col_offset})

    def _v_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_attr_writes(node, [node.target], kind_override="aug")
        self._generic(node)

    def _v_Assign(self, node: ast.Assign) -> None:
        fn = self._fn()
        self._record_attr_writes(node, node.targets)
        if len(node.targets) == 1 and isinstance(node.targets[0],
                                                 ast.Name) \
                and isinstance(node.value, ast.Attribute):
            src = _dotted(node.value)
            if src:
                # Alias fact: `idx = self._index` — the snapshot-publish
                # and atomic-swap passes chase mutations through it.
                fn["var_aliases"][node.targets[0].id] = src
        if isinstance(node.value, ast.Call):
            callee = _dotted(node.value.func)
            for t in node.targets:
                if isinstance(t, ast.Name) and callee:
                    fn["var_types"][t.id] = callee
            if not self._fn_stack and callee.rsplit(".", 1)[-1] in (
                    "Lock", "RLock"):
                self.module_locks += [t.id for t in node.targets
                                      if isinstance(t, ast.Name)]
        elif len(node.targets) == 1 and isinstance(node.targets[0],
                                                   ast.Name):
            t = node.targets[0]
            if not self._fn_stack:
                if isinstance(node.value, ast.Constant):
                    self.constants[t.id] = node.value.value
                elif isinstance(node.value, (ast.Tuple, ast.List)) and all(
                        isinstance(e, ast.Constant)
                        for e in node.value.elts):
                    self.constants[t.id] = [e.value
                                            for e in node.value.elts]
            if isinstance(node.value, ast.Name):
                src = fn["var_types"].get(node.value.id)
                if src:
                    fn["var_types"][t.id] = src
        self._generic(node)

    def _v_Compare(self, node: ast.Compare) -> None:
        # `name == "literal"` (either side): the dispatch-table fact
        # the verb-dispatch-drift pass reads off `_dispatch_op`-style
        # functions.  Chained comparisons stay opaque on purpose.
        if len(node.ops) == 1 and isinstance(node.ops[0], ast.Eq):
            left, right = node.left, node.comparators[0]
            name = value = None
            if isinstance(left, ast.Name) \
                    and isinstance(right, ast.Constant) \
                    and isinstance(right.value, str):
                name, value = left.id, right.value
            elif isinstance(right, ast.Name) \
                    and isinstance(left, ast.Constant) \
                    and isinstance(left.value, str):
                name, value = right.id, left.value
            if name is not None:
                eqs = self._fn()["str_eqs"].setdefault(name, [])
                if value not in eqs:
                    eqs.append(value)
        self._generic(node)

    def _v_Return(self, node: ast.Return) -> None:
        fn = self._fn()
        if node.value is not None:
            if isinstance(node.value, ast.Call):
                callee = _dotted(node.value.func)
                if callee:
                    fn["returns_call"] = callee
            elif isinstance(node.value, ast.Name):
                src = fn["var_types"].get(node.value.id)
                if src:
                    fn["returns_call"] = src
        self._generic(node)

    def _v_Raise(self, node: ast.Raise) -> None:
        fn = self._fn()
        name = ""
        if node.exc is not None:
            name = _dotted(node.exc) or _dotted(
                getattr(node.exc, "func", node.exc))
        fn["raises"].append({"name": name.rsplit(".", 1)[-1],
                             "line": node.lineno,
                             "bare": node.exc is None,
                             "handlers": [h for t in self._try_stack
                                          for h in t]})
        self._generic(node)

    def _v_With(self, node: ast.With) -> None:
        fn = self._fn()
        tokens = []
        for item in node.items:
            self._visit(item.context_expr)
            tok = self._lock_token(item.context_expr)
            if tok is not None:
                tokens.append(tok)
                fn["lock_sites"].append(
                    {"token": tok, "line": node.lineno,
                     "held": list(self._locks_held)})
        self._locks_held.extend(tokens)
        self._visit_body(node.body)
        if tokens:
            del self._locks_held[-len(tokens):]

    _v_AsyncWith = _v_With

    def _v_Try(self, node: ast.Try) -> None:
        fn = self._fn()
        ids = []
        for h in node.handlers:
            if self._is_broad(h.type):
                hid = len(fn["broad_handlers"])
                fn["broad_handlers"].append({
                    "id": hid, "line": h.lineno,
                    "reraises": self._handler_reraises(h),
                    "lse_escapes": self._handler_lse_escapes(h)})
                ids.append(hid)
            elif self._catches_lse(h.type) \
                    and not self._handler_reraises(h):
                # Explicit LeaseSupersededError handler that does NOT
                # re-raise: deliberate handling — it also stops upward
                # may-raise propagation for the calls in this try body.
                hid = len(fn["broad_handlers"])
                fn["broad_handlers"].append(
                    {"id": hid, "line": h.lineno, "reraises": False,
                     "lse_escapes": False, "explicit_lse": True})
                ids.append(hid)
        self._try_stack.append(ids)
        self._visit_body(node.body)
        self._try_stack.pop()
        for h in node.handlers:
            self._visit_body(h.body)
        self._visit_body(node.orelse)
        self._visit_body(node.finalbody)

    _v_TryStar = _v_Try

    @staticmethod
    def _is_broad(type_node) -> bool:
        if type_node is None:
            return True
        if isinstance(type_node, ast.Tuple):
            return any(_FactsVisitor._is_broad(e) for e in type_node.elts)
        return _dotted(type_node).rsplit(".", 1)[-1] in ("Exception",
                                                         "BaseException")

    @staticmethod
    def _catches_lse(type_node) -> bool:
        if type_node is None:
            return True
        if isinstance(type_node, ast.Tuple):
            return any(_FactsVisitor._catches_lse(e)
                       for e in type_node.elts)
        return _dotted(type_node).rsplit(".", 1)[-1] == \
            "LeaseSupersededError"

    @staticmethod
    def _handler_reraises(handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise) and (n.exc is None
                                             or n.cause is not None):
                return True
        return False

    @staticmethod
    def _handler_lse_escapes(handler: ast.ExceptHandler) -> bool:
        """Does LeaseSupersededError itself provably escape this broad
        handler?  A bare ``raise`` / ``raise e`` (the caught name)
        re-raises the original; ``raise X(...) from e`` does NOT — it
        converts the fence signal into another type, which is exactly
        the masking the lease protocol forbids."""
        caught = handler.name
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise):
                if n.exc is None:
                    return True
                if (caught and isinstance(n.exc, ast.Name)
                        and n.exc.id == caught and n.cause is None):
                    return True
        return False

    # calls -----------------------------------------------------------------

    def _v_Call(self, node: ast.Call) -> None:
        fn = self._fn()
        callee = _dotted(node.func)
        recv_call = None
        if isinstance(node.func, ast.Attribute) and isinstance(
                node.func.value, ast.Call):
            # one-level receiver-call: self.range_store(r).append(...)
            recv_call = _dotted(node.func.value.func)
            if recv_call:
                callee = f"<call:{recv_call}>.{node.func.attr}"
        call: dict = {"callee": callee, "line": node.lineno,
                      "col": node.col_offset, "idx": self._call_idx,
                      "locks": list(self._locks_held),
                      "handlers": [h for t in self._try_stack for h in t]}
        self._call_idx += 1
        call["args"] = [self._arg_fact(a, fn) for a in node.args]
        call["kwargs"] = {kw.arg: self._arg_fact(kw.value, fn)
                          for kw in node.keywords if kw.arg}
        tail = callee.rsplit(".", 1)[-1]
        if tail == "fault_point" and node.args:
            site = node.args[0]
            if isinstance(site, ast.Constant) and isinstance(
                    site.value, str):
                call["fault_site"] = site.value
            elif isinstance(site, ast.Name):
                call["fault_site_param"] = site.id
            else:
                call["fault_site_param"] = "<expr>"
        if tail in _SQL_EXEC_ATTRS and isinstance(node.func,
                                                  ast.Attribute):
            call["exec_recv"] = _dotted(node.func.value) or (
                f"<call:{recv_call}>" if recv_call else "<expr>")
        if tail == "open":
            mode = node.args[1] if len(node.args) >= 2 else None
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            call["open_write"] = bool(
                isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and any(c in mode.value for c in "wa+x"))
        if tail in ("open", "atomic_write") and node.args:
            toks = self._path_tokens(node.args[0], fn)
            if toks:
                call["path_tokens"] = sorted(toks)
        fn["calls"].append(call)
        self._generic(node)

    def _arg_fact(self, a: ast.AST, fn: dict) -> dict:
        fact: dict = {"kind": "other"}
        if isinstance(a, ast.Constant):
            fact = {"kind": "const"}
            if isinstance(a.value, str):
                fact["value"] = a.value
        elif isinstance(a, ast.Name):
            if a.id in fn["params"]:
                fact = {"kind": "param", "name": a.id}
            else:
                fact = {"kind": "var", "name": a.id}
                vt = fn["var_types"].get(a.id)
                if vt:
                    fact["type"] = vt
        elif isinstance(a, ast.Attribute):
            expr = _dotted(a)
            if expr:
                fact = {"kind": "attr", "expr": expr}
        elif isinstance(a, ast.Call):
            fact = {"kind": "call", "callee": _dotted(a.func)}
        if self._sql_tainted(a, fn):
            fact["kind"] = "tainted-sql"
        return fact

    def _sql_tainted(self, a: ast.AST, fn: dict) -> bool:
        """A string expression interpolating non-blessed parts into SQL
        text (reuses the per-file rule's blessing logic over this
        function's local name->binding env)."""
        from .rules import _blessed_expr

        env = fn.get("_env", {})
        node = env.get(a.id) if isinstance(a, ast.Name) else a
        if node is None or not _looks_sql(node) \
                or not _is_interpolated(node):
            return False
        if isinstance(node, ast.JoinedStr):
            return any(not _blessed_expr(v.value, env)
                       for v in node.values
                       if isinstance(v, ast.FormattedValue))
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            return not all(_blessed_expr(x, env) for x in node.args)
        if isinstance(node, ast.BinOp):
            if isinstance(node.op, ast.Mod):
                right = (node.right.elts
                         if isinstance(node.right, ast.Tuple)
                         else [node.right])
                return not all(_blessed_expr(r, env) for r in right)
            return not (_blessed_expr(node.left, env)
                        and _blessed_expr(node.right, env))
        return False

    def _path_tokens(self, a: ast.AST, fn: dict) -> set:
        """Protocol-file tokens mentioned by a path expression (one
        level of name/constant resolution): membership.json / lease_* /
        hb_* or the coordinator path helpers."""
        toks: set = set()
        env = fn.get("_env", {})

        def scan(node, depth=0):
            if node is None or depth > 4:
                return
            if isinstance(node, ast.Constant) and isinstance(node.value,
                                                             str):
                self._token_match(node.value, toks)
            elif isinstance(node, ast.JoinedStr):
                for v in node.values:
                    scan(v.value if isinstance(v, ast.FormattedValue)
                         else v, depth + 1)
            elif isinstance(node, ast.Name):
                const = self.constants.get(node.id)
                if isinstance(const, str):
                    self._token_match(const, toks)
                else:
                    scan(env.get(node.id), depth + 1)
            elif isinstance(node, ast.Call):
                tail = _dotted(node.func).rsplit(".", 1)[-1]
                if tail in ("lease_path", "heartbeat_path"):
                    toks.add(tail)
                for x in node.args:
                    scan(x, depth + 1)
            elif isinstance(node, ast.BinOp):
                scan(node.left, depth + 1)
                scan(node.right, depth + 1)
            elif isinstance(node, ast.Attribute):
                # self.path-style: typed through the class attr table
                if (isinstance(node.value, ast.Name)
                        and node.value.id == "self" and self._cls_stack):
                    crec = self.classes.get(self._cls_stack[-1], {})
                    src = crec.get("attr_paths", {}).get(node.attr)
                    if src:
                        self._token_match(src, toks)

        scan(a)
        return toks

    @staticmethod
    def _token_match(text: str, toks: set) -> None:
        low = text.lower()
        if "membership.json" in low:
            toks.add("membership.json")
        if "lease_" in low:
            toks.add("lease_")
        if "hb_" in low:
            toks.add("hb_")


def extract_facts(relpath: str, text: str,
                  tree: ast.AST | None = None) -> dict:
    """FileFacts for one file (parses ``text`` unless ``tree`` given)."""
    if tree is None:
        tree = ast.parse(text, filename=relpath)
    v = _FactsVisitor(relpath, tree)
    for fn in v.functions:
        fn.pop("_env", None)
    return {"path": relpath, "module": v.module, "imports": v.imports,
            "constants": v.constants, "module_locks": v.module_locks,
            "classes": v.classes, "functions": v.functions}


# -- the project graph -------------------------------------------------------


@dataclass
class ProjectGraph:
    """Resolved whole-program view over a set of FileFacts."""

    root: str
    facts: dict[str, dict] = field(default_factory=dict)   # path -> facts
    functions: dict[str, dict] = field(default_factory=dict)  # qual -> fn
    fn_file: dict[str, str] = field(default_factory=dict)   # qual -> path
    modules: dict[str, str] = field(default_factory=dict)   # module -> path
    classes: dict[str, dict] = field(default_factory=dict)  # mod.Cls -> rec
    calls: dict[str, list] = field(default_factory=dict)    # qual -> edges
    rev_calls: dict[str, list] = field(default_factory=dict)
    cache_files: int = 0
    cache_hits: int = 0
    extracted: list[str] = field(default_factory=list)  # paths re-parsed

    # ---- construction ----

    def add_file(self, facts: dict) -> None:
        path = facts["path"]
        self.facts[path] = facts
        self.modules[facts["module"]] = path
        for cname, crec in facts["classes"].items():
            self.classes[f"{facts['module']}.{cname}"] = crec
        for fn in facts["functions"]:
            self.functions[fn["qual"]] = fn
            self.fn_file[fn["qual"]] = path

    def finalize(self) -> None:
        """Resolve every call site to a qualified callee (where
        possible) and build forward/reverse call-edge tables."""
        for qual, fn in self.functions.items():
            edges = []
            for call in fn["calls"]:
                target = self.resolve_call(qual, call)
                if target is not None:
                    call["resolved"] = target
                    edges.append((target, call))
            self.calls[qual] = edges
            for target, call in edges:
                self.rev_calls.setdefault(target, []).append((qual, call))

    def module_of(self, qual: str) -> str:
        path = self.fn_file.get(qual)
        return self.facts[path]["module"] if path else ""

    # ---- symbol resolution ----

    def _module_symbol(self, module: str, name: str,
                       depth: int = 0) -> str | None:
        """``module.name`` resolved to a function/class qual, following
        up to three import hops (re-exports)."""
        if depth > 3:
            return None
        path = self.modules.get(module)
        if path is None:
            return None
        qual = f"{module}.{name}"
        if qual in self.functions or qual in self.classes:
            return qual
        target = self.facts[path]["imports"].get(name)
        if target and target != qual:
            mod, _, leaf = target.rpartition(".")
            if mod:
                return self._module_symbol(mod, leaf, depth + 1)
        return None

    def _class_method(self, cls_qual: str, meth: str,
                      depth: int = 0) -> str | None:
        if depth > 4:
            return None
        crec = self.classes.get(cls_qual)
        if crec is None:
            return None
        if meth in crec["methods"]:
            return f"{cls_qual}.{meth}"
        mod = cls_qual.rsplit(".", 1)[0]
        for base in crec.get("bases", []):
            base_qual = self._resolve_dotted(mod, base)
            if base_qual:
                found = self._class_method(base_qual, meth, depth + 1)
                if found:
                    return found
        return None

    def _resolve_dotted(self, module: str, dotted: str) -> str | None:
        """Resolve a dotted expression *as written in ``module``* to a
        function or class qual."""
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        path = self.modules.get(module)
        imports = self.facts.get(path or "", {}).get("imports", {})
        target = imports.get(head)
        if target is None:
            local = self._module_symbol(module, head)
            if local is None:
                return None
            if not rest:
                return local
            if local in self.classes:
                return self._class_method(local, rest.split(".")[0])
            return None
        if rest:
            # imported module (import x.y as z; z.fn) or imported class
            full_mod = target
            parts = rest.split(".")
            while len(parts) > 1 and f"{full_mod}.{parts[0]}" \
                    in self.modules:
                full_mod = f"{full_mod}.{parts[0]}"
                parts = parts[1:]
            if full_mod in self.modules:
                sym = self._module_symbol(full_mod, parts[0])
                if sym is not None and len(parts) > 1 \
                        and sym in self.classes:
                    return self._class_method(sym, parts[1])
                return sym
            tmod, _, tleaf = target.rpartition(".")
            sym = self._module_symbol(tmod, tleaf) if tmod else None
            if sym and sym in self.classes:
                return self._class_method(sym, parts[0])
            return None
        # plain imported symbol
        mod, _, leaf = target.rpartition(".")
        if mod:
            return self._module_symbol(mod, leaf)
        return None

    def resolve_call(self, caller_qual: str, call: dict) -> str | None:
        callee = call["callee"]
        fn = self.functions.get(caller_qual)
        if fn is None or not callee:
            return None
        path = self.fn_file[caller_qual]
        module = self.facts[path]["module"]
        if callee.startswith("<call:"):
            inner, _, meth = callee[6:].partition(">.")
            inner_qual = self.resolve_call(caller_qual,
                                           {"callee": inner})
            if inner_qual is None:
                return None
            if inner_qual in self.classes:  # Ctor().meth(...)
                return self._class_method(inner_qual, meth)
            ret = self.functions.get(inner_qual, {}).get("returns_call")
            if not ret:
                return None
            ret_module = self.module_of(inner_qual)
            cls_qual = self._resolve_dotted(ret_module, ret)
            if cls_qual and cls_qual in self.classes:
                return self._class_method(cls_qual, meth)
            return None
        head, _, rest = callee.partition(".")
        if head == "self":
            cls = fn.get("cls")
            if cls is None and fn.get("parent"):
                cls = self.functions.get(fn["parent"], {}).get("cls")
            if cls is None or not rest:
                return None
            cls_qual = f"{module}.{cls}"
            meth, _, trail = rest.partition(".")
            if trail:
                crec = self.classes.get(cls_qual, {})
                attr_t = crec.get("attr_types", {}).get(meth)
                if attr_t:
                    tq = self._resolve_dotted(module, attr_t)
                    if tq and tq in self.classes:
                        return self._class_method(tq, trail.split(".")[0])
                return None
            return self._class_method(cls_qual, meth)
        if rest:
            vt = fn["var_types"].get(head)
            if vt:
                tq = self._resolve_dotted(module, vt)
                if tq and tq in self.classes:
                    return self._class_method(tq, rest.split(".")[0])
                if tq and tq in self.functions:
                    ret = self.functions[tq].get("returns_call")
                    if ret:
                        rq = self._resolve_dotted(self.module_of(tq), ret)
                        if rq and rq in self.classes:
                            return self._class_method(rq,
                                                      rest.split(".")[0])
                return None
        return self._resolve_dotted(module, callee)

    # ---- import graph ----

    def import_edges(self) -> dict[str, set]:
        """path -> set of project paths it imports."""
        out: dict[str, set] = {p: set() for p in self.facts}
        for path, facts in self.facts.items():
            for target in facts["imports"].values():
                mod = target
                while mod:
                    if mod in self.modules:
                        if self.modules[mod] != path:
                            out[path].add(self.modules[mod])
                        break
                    mod = mod.rpartition(".")[0]
        return out

    def reverse_closure(self, paths: set) -> set:
        """``paths`` plus every file that (transitively) imports one."""
        rev: dict[str, set] = {}
        for src, dsts in self.import_edges().items():
            for d in dsts:
                rev.setdefault(d, set()).add(src)
        out = set(paths)
        work = list(paths)
        while work:
            p = work.pop()
            for dep in rev.get(p, ()):
                if dep not in out:
                    out.add(dep)
                    work.append(dep)
        return out

    # ---- witness chains ----

    def call_chain(self, start: str, goal: str) -> list | None:
        """Shortest resolved-call path start -> ... -> goal as a list of
        (caller_qual, call, callee_qual) edges, or None."""
        if start == goal:
            return []
        prev: dict[str, tuple] = {}
        seen = {start}
        frontier = [start]
        while frontier:
            nxt = []
            for q in frontier:
                for target, call in self.calls.get(q, ()):
                    if target in seen:
                        continue
                    seen.add(target)
                    prev[target] = (q, call)
                    if target == goal:
                        chain = []
                        node = goal
                        while node != start:
                            q2, c2 = prev[node]
                            chain.append((q2, c2, node))
                            node = q2
                        return list(reversed(chain))
                    nxt.append(target)
            frontier = nxt
        return None

    def site(self, qual: str, call: dict | None = None) -> str:
        path = self.fn_file.get(qual, "?")
        line = (call or {}).get("line") or \
            self.functions.get(qual, {}).get("line", 0)
        return f"{path}:{line}"


# -- build + cache -----------------------------------------------------------


def cache_path(root: str) -> str:
    return os.path.join(root, CACHE_BASENAME)


def load_cache(root: str) -> dict:
    try:
        with open(cache_path(root), encoding="utf-8") as f:
            data = json.load(f)
        if data.get("version") != _CACHE_VERSION:
            return {}
        return data.get("files", {})
    except (OSError, ValueError):
        return {}


def save_cache(root: str, files: dict) -> None:
    payload = {"version": _CACHE_VERSION, "files": files}
    tmp = cache_path(root) + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, cache_path(root))
    except OSError:
        pass  # a read-only checkout just runs cold every time


def build_graph(paths: list[str], root: str,
                sources: dict | None = None,
                use_cache: bool = True) -> ProjectGraph:
    """Build the ProjectGraph over ``paths``.

    ``sources`` optionally maps abspath -> (relpath, text, tree) for
    files the engine already parsed (one parse per run).  The digest
    cache short-circuits fact extraction for unchanged files."""
    graph = ProjectGraph(root=root)
    cached = load_cache(root) if use_cache else {}
    new_cache: dict = {}
    for abspath in paths:
        abspath = os.path.abspath(abspath)
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        pre = (sources or {}).get(abspath)
        if pre is not None:
            text = pre[1]
        else:
            try:
                with open(abspath, encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
        digest = content_digest(text.encode("utf-8"))
        graph.cache_files += 1
        entry = cached.get(rel)
        if entry is not None and entry.get("digest") == digest:
            graph.cache_hits += 1
            facts = entry["facts"]
        else:
            try:
                facts = extract_facts(rel, text,
                                      tree=pre[2] if pre else None)
            except (SyntaxError, RecursionError, ValueError):
                continue
            graph.extracted.append(rel)
        new_cache[rel] = {"digest": digest, "facts": facts}
        graph.add_file(facts)
    if use_cache:
        # Merge over the existing cache: an explicit-path or fixture run
        # must not evict the full-target entries.
        save_cache(root, {**cached, **new_cache})
    graph.finalize()
    return graph


__all__ = ["CACHE_BASENAME", "ProjectGraph", "build_graph", "cache_path",
           "content_digest", "extract_facts", "load_cache", "module_name",
           "save_cache"]
