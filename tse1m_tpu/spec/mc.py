"""graftspec's explicit-state model checker (the TLC tradition, sized
for bounded protocol scopes).

Exploration is exhaustive over the spec's reachable states under a
bounded scope: BFS by default (shortest counterexamples), DFS on
request.  States are canonicalized (:func:`~.dsl.state_key`) and, when
the spec declares a process-id symmetry, quotiented by the minimal
encoding over all id permutations — the classic symmetry reduction:
sound for safety because permuted states have isomorphic futures, and
the representative kept per class makes guards/effects well-defined.

Properties:

- **Invariants** are checked at every state as it is discovered; a
  violation reports the shortest (BFS) action path from the initial
  state.
- **Liveness** (``[]<>goal`` under weak fairness) is checked on the
  complete reachability graph: a violation is either a terminal state
  where the goal fails, or a *fair lasso* — a reachable cycle on which
  the goal never holds and no weakly-fair action is starved (every
  fair action is disabled somewhere on the cycle or taken by it).
  SCCs come from an iterative Tarjan pass over the goal-false
  subgraph.

Counterexamples are emitted as replayable graftrace schedule strings
(``v1:fix:action,action,...`` via trace/sched.py's export hook);
:func:`replay` re-executes one deterministically through the same
canonical machinery, so a reported trace is checkable by construction
(tests replay every mutant counterexample back to its violating
state).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from itertools import permutations

from ..resilience.watchdog import deadline_clock
from ..trace.sched import fixed_schedule_string
from .dsl import Spec, SpecError, state_key

_DEFAULT_MAX_STATES = 200_000


@dataclass(frozen=True)
class Violation:
    """One property violation with its replayable counterexample."""

    kind: str            # "invariant" | "liveness"
    prop: str            # property name
    trace: tuple         # action names, initial state -> witness state
    state: dict          # witness state (terminal / cycle entry)
    cycle: tuple = ()    # liveness only: the starved cycle's actions

    @property
    def schedule_str(self) -> str:
        return fixed_schedule_string(self.trace + self.cycle)

    def describe(self) -> str:
        lines = [f"{self.kind} violation: {self.prop}",
                 f"  trace ({len(self.trace)} steps): "
                 + (" -> ".join(self.trace) or "<initial state>")]
        if self.cycle:
            lines.append(f"  starved cycle: {' -> '.join(self.cycle)}"
                         " -> (repeat)")
        lines.append("  state: " + ", ".join(
            f"{k}={v!r}" for k, v in sorted(self.state.items())))
        lines.append(f"  replay: {self.schedule_str}")
        return "\n".join(lines)


@dataclass
class CheckResult:
    spec: str
    ok: bool
    states: int
    transitions: int
    depth: int
    complete: bool
    mode: str
    wall_s: float
    violation: Violation | None = None
    scope: dict = field(default_factory=dict)

    def summary(self) -> dict:
        out = {"spec": self.spec, "ok": bool(self.ok),
               "states": self.states, "transitions": self.transitions,
               "depth": self.depth, "complete": self.complete,
               "mode": self.mode, "wall_s": round(self.wall_s, 3)}
        if self.violation is not None:
            out["violation"] = {
                "kind": self.violation.kind,
                "prop": self.violation.prop,
                "schedule": self.violation.schedule_str}
        return out


def _identity(n: int) -> tuple:
    return tuple(range(n))


def _canon(spec: Spec, state: dict) -> tuple:
    """(canonical key, representative state) for one concrete state."""
    if spec.symmetry is None or spec.n_symmetric <= 1:
        return state_key(state), state
    best_key, best_state = None, None
    for perm in permutations(range(spec.n_symmetric)):
        s2 = state if perm == _identity(spec.n_symmetric) \
            else spec.symmetry(state, perm)
        k2 = state_key(s2)
        if best_key is None or k2 < best_key:
            best_key, best_state = k2, s2
    return best_key, best_state


def _trace_to(nodes: dict, key) -> tuple:
    names: list = []
    while True:
        parent, action, _state, _depth = nodes[key]
        if parent is None:
            break
        names.append(action)
        key = parent
    return tuple(reversed(names))


def _explore(spec: Spec, mode: str, max_states: int):
    """Reachability: nodes, edges, and an invariant violation if one
    exists (None otherwise).  nodes: key -> (parent_key, action_name,
    representative_state, depth); edges: key -> [(action, child_key)]."""
    ikey, istate = _canon(spec, spec.init)
    nodes = {ikey: (None, None, istate, 0)}
    edges: dict = {ikey: []}
    frontier = deque([ikey])
    transitions = 0
    max_depth = 0

    def _check_invariants(key, state):
        for inv in spec.invariants:
            if not inv.pred(state):
                return Violation(kind="invariant", prop=inv.name,
                                 trace=_trace_to(nodes, key),
                                 state=state)
        return None

    bad = _check_invariants(ikey, istate)
    if bad is not None:
        return nodes, edges, transitions, 0, True, bad

    while frontier:
        key = frontier.popleft() if mode == "bfs" else frontier.pop()
        _p, _a, state, depth = nodes[key]
        for action in spec.actions:
            if not action.guard(state):
                continue
            nxt = action.effect(state)
            ckey, cstate = _canon(spec, nxt)
            transitions += 1
            edges[key].append((action.name, ckey))
            if ckey in nodes:
                continue
            nodes[ckey] = (key, action.name, cstate, depth + 1)
            edges[ckey] = []
            max_depth = max(max_depth, depth + 1)
            bad = _check_invariants(ckey, cstate)
            if bad is not None:
                return nodes, edges, transitions, max_depth, True, bad
            if len(nodes) >= max_states:
                return nodes, edges, transitions, max_depth, False, None
            frontier.append(ckey)
    return nodes, edges, transitions, max_depth, True, None


def _sccs(keys: set, edges: dict) -> list:
    """Tarjan's SCCs (iterative) over the subgraph induced by ``keys``."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    out: list = []
    counter = [0]

    for root in keys:
        if root in index:
            continue
        work = [(root, iter([c for _a, c in edges[root] if c in keys]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append(
                        (w, iter([c for _a, c in edges[w]
                                  if c in keys])))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)
    return out


def _path(src, dst, members: set, edges: dict) -> tuple:
    """(action names, nodes touched) for a BFS path src -> dst inside
    ``members`` (empty path when src == dst).  Both are in one SCC, so
    the path exists."""
    if src == dst:
        return [], {src}
    parent: dict = {}
    seen = {src}
    queue = [src]
    while queue:
        v = queue.pop(0)
        for action, w in edges[v]:
            if w not in members or w in seen:
                continue
            seen.add(w)
            parent[w] = (v, action)
            if w == dst:
                names: list = []
                touched = {dst}
                while w != src:
                    pv, pa = parent[w]
                    names.append(pa)
                    touched.add(pv)
                    w = pv
                return list(reversed(names)), touched
            queue.append(w)
    raise SpecError("SCC path not found (checker bug)")


def _fair_tour(entry, members: set, edges: dict,
               need_edges: list) -> tuple:
    """A cycle entry -> ... -> entry that traverses every SCC state
    (so every somewhere-disabled fair action is disabled on it) and
    every edge in ``need_edges`` (so every everywhere-enabled fair
    action is taken on it) — a genuine weak-fairness witness, not just
    any cycle."""
    names: list = []
    visited = {entry}
    cur = entry
    for (v, action, w) in need_edges:
        seg, touched = _path(cur, v, members, edges)
        names += seg + [action]
        visited |= touched | {w}
        cur = w
    for m in sorted(members):
        if m in visited:
            continue
        seg, touched = _path(cur, m, members, edges)
        names += seg
        visited |= touched
        cur = m
    seg, _touched = _path(cur, entry, members, edges)
    names += seg
    if not names:  # single-state SCC: the self-loop IS the cycle
        action = next(a for a, w in edges[entry] if w == entry)
        names = [action]
    return tuple(names)


def _liveness_violation(spec: Spec, nodes: dict, edges: dict
                        ) -> Violation | None:
    fair = [a for a in spec.actions if a.fair]
    for prop in spec.liveness:
        # Terminal states: a quiescent protocol must have reached the
        # goal — nothing will ever re-establish it.
        for key, (_p, _a, state, _d) in nodes.items():
            if not edges[key] and not prop.goal(state):
                return Violation(kind="liveness", prop=prop.name,
                                 trace=_trace_to(nodes, key),
                                 state=state)
        # Fair lassos through the goal-false subgraph.
        bad_keys = {k for k, (_p, _a, s, _d) in nodes.items()
                    if not prop.goal(s)}
        for comp in _sccs(bad_keys, edges):
            members = set(comp)
            internal = [(v, a, w) for v in comp
                        for a, w in edges[v] if w in members]
            if not internal:
                continue  # trivial SCC, no cycle
            need_edges: list = []
            unfair = False
            for fa in fair:
                if not all(fa.guard(nodes[v][2]) for v in comp):
                    continue  # disabled somewhere: the tour covers it
                edge = next(((v, a, w) for v, a, w in internal
                             if a == fa.name), None)
                if edge is None:
                    unfair = True  # continuously enabled, never taken
                    break
                need_edges.append(edge)
            if unfair:
                continue
            entry = min(comp, key=lambda k: nodes[k][3])
            cycle = _fair_tour(entry, members, edges, need_edges)
            return Violation(kind="liveness", prop=prop.name,
                             trace=_trace_to(nodes, entry),
                             state=nodes[entry][2], cycle=cycle)
    return None


def check(spec: Spec, mode: str = "bfs",
          max_states: int = _DEFAULT_MAX_STATES) -> CheckResult:
    """Model-check one spec in its bounded scope."""
    if mode not in ("bfs", "dfs"):
        raise SpecError(f"unknown exploration mode {mode!r}")
    t0 = deadline_clock()
    nodes, edges, transitions, depth, complete, bad = _explore(
        spec, mode, max_states)
    if bad is None and complete:
        bad = _liveness_violation(spec, nodes, edges)
    return CheckResult(spec=spec.name, ok=bad is None and complete,
                       states=len(nodes), transitions=transitions,
                       depth=depth, complete=complete, mode=mode,
                       wall_s=deadline_clock() - t0, violation=bad,
                       scope=dict(spec.scope))


def replay(spec: Spec, schedule) -> list:
    """Re-execute a counterexample (a ``v1:fix:...`` string or an
    action-name sequence) through the canonical state machinery;
    returns the visited representative states.  Raises SpecError if a
    scheduled action is disabled — i.e. the trace is not a real run."""
    if isinstance(schedule, str):
        from ..trace.sched import Schedule
        names = Schedule.from_string(schedule).choices
    else:
        names = tuple(schedule)
    _key, state = _canon(spec, spec.init)
    states = [state]
    for name in names:
        action = spec.action(name)
        if not action.guard(state):
            raise SpecError(
                f"replay diverged: action {name!r} disabled after "
                f"{len(states) - 1} steps")
        _key, state = _canon(spec, action.effect(state))
        states.append(state)
    return states


__all__ = ["CheckResult", "Violation", "check", "replay"]
