"""Protocol spec: epoch leases, membership re-deal, zombie
self-fencing (resilience/coordinator.py + the serve daemon's
fence-before-append seat).

The model: ``n_procs`` writer processes compete for one range lease.
The on-disk lease is ``(epoch, owner)``; a claim is advance-then-
acquire (``RangeLeaseGuard.claim``): the epoch is bumped whether or
not the previous holder is dead — which is exactly why a paused
("wedged") old holder can wake as a **zombie** believing a stale
epoch.  Every batch commit verifies the lease atomically with the
append (``verify_lease`` inside the ingest commit), so the zombie's
next write observes the advanced epoch and latches **fenced** instead
of appending.

Bounded scope (defaults): 2 writers x 3 epochs, append log capped at
2, 2 wedge/wake excursions.  ~300 states; exhaustive in milliseconds.

Safety: every recorded append carries ``believed == actual`` epoch
(the fence happened BEFORE the append, never after), and at most one
process holds a current view of the lease.  Liveness (weak fairness on
the commit action): a live zombie cannot persist — its continuously
enabled commit eventually runs and fences it.

The committed mutation ``fence-after-append`` reorders the commit
effect (append first, then fence on mismatch): the checker finds the
classic zombie double-write with a minimal replayable schedule.
"""

from __future__ import annotations

from .dsl import Action, Invariant, Liveness, Spec, tupset, upd

SPEC_NAME = "lease"

MUTANTS = ("fence-after-append",)


def _claim(p: int):
    def guard(s):
        return s["pstate"][p] == "idle" and s["epoch"] < s["max_epoch"]

    def effect(s):
        e = s["epoch"] + 1
        return upd(s, epoch=e, owner=p,
                   pstate=tupset(s["pstate"], p, "holder"),
                   pepoch=tupset(s["pepoch"], p, e))
    return guard, effect


def _wedge(p: int):
    def guard(s):
        return s["pstate"][p] == "holder" and s["wedges"] < s["max_wedges"]

    def effect(s):
        return upd(s, pstate=tupset(s["pstate"], p, "wedged"),
                   wedges=s["wedges"] + 1)
    return guard, effect


def _wake(p: int):
    def guard(s):
        return s["pstate"][p] == "wedged"

    def effect(s):
        return upd(s, pstate=tupset(s["pstate"], p, "holder"))
    return guard, effect


def _commit(p: int, mutant: str | None):
    def guard(s):
        return s["pstate"][p] == "holder"

    def effect(s):
        current = s["pepoch"][p] == s["epoch"] and s["owner"] == p
        if mutant == "fence-after-append":
            # BUG under test: the append lands before the fence check.
            out = s
            if len(s["log"]) < s["log_cap"]:
                out = upd(s, log=s["log"] + ((s["pepoch"][p],
                                              s["epoch"]),))
            if not current:
                out = upd(out, pstate=tupset(out["pstate"], p, "fenced"))
            return out
        if not current:
            return upd(s, pstate=tupset(s["pstate"], p, "fenced"))
        if len(s["log"]) < s["log_cap"]:
            return upd(s, log=s["log"] + ((s["pepoch"][p],
                                           s["epoch"]),))
        return dict(s)  # log saturated: the commit is a no-op
    return guard, effect


def build(n_procs: int = 2, max_epoch: int = 3, log_cap: int = 2,
          max_wedges: int = 2, mutant: str | None = None) -> Spec:
    if mutant is not None and mutant not in MUTANTS:
        raise ValueError(f"unknown lease mutant {mutant!r}")
    init = {"epoch": 1, "owner": 0,
            "pstate": ("holder",) + ("idle",) * (n_procs - 1),
            "pepoch": (1,) + (0,) * (n_procs - 1),
            "log": (), "wedges": 0,
            "max_epoch": max_epoch, "log_cap": log_cap,
            "max_wedges": max_wedges}
    actions = []
    for p in range(n_procs):
        g, e = _claim(p)
        actions.append(Action(f"claim_p{p}", g, e,
                              seat="call:acquire_lease"))
        g, e = _wedge(p)
        actions.append(Action(f"wedge_p{p}", g, e, seat="model:pause"))
        g, e = _wake(p)
        actions.append(Action(f"wake_p{p}", g, e, seat="model:pause"))
        g, e = _commit(p, mutant)
        actions.append(Action(f"commit_p{p}", g, e,
                              seat="call:verify_lease", fair=True))

    def _no_stale_append(s):
        return all(believed == actual for believed, actual in s["log"])

    def _single_current_holder(s):
        current = [p for p in range(n_procs)
                   if s["pstate"][p] == "holder"
                   and s["pepoch"][p] == s["epoch"]
                   and s["owner"] == p]
        return len(current) <= 1

    def _no_future_view(s):
        return all(pe <= s["epoch"] for pe in s["pepoch"])

    def _no_live_zombie(s):
        return not any(s["pstate"][p] == "holder"
                       and (s["pepoch"][p] != s["epoch"]
                            or s["owner"] != p)
                       for p in range(n_procs))

    return Spec(
        name="lease" if mutant is None else f"lease[{mutant}]",
        init=init,
        actions=tuple(actions),
        invariants=(
            Invariant("fence-before-append", _no_stale_append),
            Invariant("single-current-holder", _single_current_holder),
            Invariant("no-future-view", _no_future_view),
        ),
        liveness=(Liveness("zombie-eventually-fences", _no_live_zombie),),
        scope={"n_procs": n_procs, "max_epoch": max_epoch,
               "log_cap": log_cap, "max_wedges": max_wedges},
    )


__all__ = ["MUTANTS", "SPEC_NAME", "build"]
