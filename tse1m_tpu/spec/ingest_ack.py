"""Protocol spec: durable-once ingest acks through the router
(serve/router.py fan-out + serve/daemon.py journal replay).

The model: ``n_rids`` client requests, each fanned out by the router
to ``n_shards`` shard daemons under the per-shard request id.  A shard
commit journals the rid at the durability seat
(``fault_point("serve.ingest.commit")``) and answers; the answer can
die in the lost-ack window (``fault_point("serve.router.forward")``),
a shard can crash with requests in flight (journal survives, channel
does not), and the router retries the SAME rid — a retry of a
journaled rid is answered by **replay** (ack from the journal, no
second absorb).

Bounded scope (defaults): 2 shards x 1 in-flight rid, 1 dropped ack,
1 crash, 3 sends per channel (sends >= drops + crashes + 1, so the
adversary cannot exhaust retries).  Shards are symmetric: the checker
quotients over shard-id permutations.

Safety: a row batch is absorbed at most ONCE per shard however many
retries race the journal (durable-once), and every ack — shard-level
or client-level — is backed by a journal entry.  Liveness: every
client request is eventually acked.

The committed mutation ``ack-before-journal`` answers the client
before the journal write survives: the dropped ack's retry finds no
journal entry and absorbs AGAIN — the checker produces the minimal
double-absorb schedule.
"""

from __future__ import annotations

from .dsl import Action, Invariant, Liveness, Spec, tupset, upd

SPEC_NAME = "ingest_ack"

MUTANTS = ("ack-before-journal",)

_ABSORB_SAT = 2  # saturating absorb counter: 2 already violates


def _ch(s: dict, var: str, r: int, sh: int):
    return s[var][r][sh]


def _chset(s: dict, var: str, r: int, sh: int, value) -> dict:
    return upd(s, **{var: tupset(s[var], r,
                                 tupset(s[var][r], sh, value))})


def _send(r: int, sh: int):
    def guard(s):
        return (s["client"][r] == "waiting"
                and not _ch(s, "done", r, sh)
                and not _ch(s, "msg", r, sh)
                and not _ch(s, "ack", r, sh)
                and _ch(s, "sends", r, sh) < s["max_sends"])

    def effect(s):
        s = _chset(s, "msg", r, sh, True)
        return _chset(s, "sends", r, sh, _ch(s, "sends", r, sh) + 1)
    return guard, effect


def _commit(r: int, sh: int, mutant: str | None):
    def guard(s):
        return _ch(s, "msg", r, sh) and not _ch(s, "journal", r, sh)

    def effect(s):
        s = _chset(s, "absorbed", r, sh,
                   min(_ch(s, "absorbed", r, sh) + 1, _ABSORB_SAT))
        if mutant != "ack-before-journal":
            s = _chset(s, "journal", r, sh, True)
        # BUG under test (mutant): the ack leaves without the journal
        # entry, so a retried rid cannot be recognized as a replay.
        s = _chset(s, "msg", r, sh, False)
        return _chset(s, "ack", r, sh, True)
    return guard, effect


def _replay(r: int, sh: int):
    def guard(s):
        return _ch(s, "msg", r, sh) and _ch(s, "journal", r, sh)

    def effect(s):
        s = _chset(s, "msg", r, sh, False)
        return _chset(s, "ack", r, sh, True)
    return guard, effect


def _drop(r: int, sh: int):
    def guard(s):
        return _ch(s, "ack", r, sh) and s["drops"] < s["max_drops"]

    def effect(s):
        s = _chset(s, "ack", r, sh, False)
        return upd(s, drops=s["drops"] + 1)
    return guard, effect


def _collect(r: int, sh: int):
    def guard(s):
        return _ch(s, "ack", r, sh)

    def effect(s):
        s = _chset(s, "ack", r, sh, False)
        return _chset(s, "done", r, sh, True)
    return guard, effect


def _crash(sh: int, n_rids: int):
    def guard(s):
        return (s["crashes"] < s["max_crashes"]
                and any(_ch(s, "msg", r, sh) for r in range(n_rids)))

    def effect(s):
        for r in range(n_rids):
            s = _chset(s, "msg", r, sh, False)
        return upd(s, crashes=s["crashes"] + 1)
    return guard, effect


def _client_ack(r: int, n_shards: int):
    def guard(s):
        return (s["client"][r] == "waiting"
                and all(_ch(s, "done", r, sh)
                        for sh in range(n_shards)))

    def effect(s):
        return upd(s, client=tupset(s["client"], r, "acked"))
    return guard, effect


def build(n_shards: int = 2, n_rids: int = 1, max_drops: int = 1,
          max_crashes: int = 1, max_sends: int = 3,
          mutant: str | None = None) -> Spec:
    if mutant is not None and mutant not in MUTANTS:
        raise ValueError(f"unknown ingest_ack mutant {mutant!r}")
    zeros = tuple((0,) * n_shards for _ in range(n_rids))
    falses = tuple((False,) * n_shards for _ in range(n_rids))
    init = {"client": ("waiting",) * n_rids,
            "msg": falses, "ack": falses, "done": falses,
            "journal": falses, "absorbed": zeros, "sends": zeros,
            "drops": 0, "crashes": 0,
            "max_drops": max_drops, "max_crashes": max_crashes,
            "max_sends": max_sends}
    actions = []
    for r in range(n_rids):
        for sh in range(n_shards):
            g, e = _send(r, sh)
            actions.append(Action(f"send_r{r}s{sh}", g, e,
                                  seat="verb:ingest", fair=True))
            g, e = _commit(r, sh, mutant)
            actions.append(Action(
                f"commit_r{r}s{sh}", g, e,
                seat="fault:serve.ingest.commit", fair=True))
            g, e = _replay(r, sh)
            actions.append(Action(f"replay_r{r}s{sh}", g, e,
                                  seat="verb:ingest", fair=True))
            g, e = _drop(r, sh)
            actions.append(Action(
                f"drop_r{r}s{sh}", g, e,
                seat="fault:serve.router.forward"))
            g, e = _collect(r, sh)
            actions.append(Action(f"collect_r{r}s{sh}", g, e,
                                  seat="call:_forward", fair=True))
        g, e = _client_ack(r, n_shards)
        actions.append(Action(f"client_ack_r{r}", g, e,
                              seat="verb:ingest", fair=True))
    for sh in range(n_shards):
        g, e = _crash(sh, n_rids)
        actions.append(Action(f"crash_s{sh}", g, e, seat="model:crash"))

    def _durable_once(s):
        return all(_ch(s, "absorbed", r, sh) <= 1
                   for r in range(n_rids) for sh in range(n_shards))

    def _ack_implies_journal(s):
        return all((not _ch(s, "ack", r, sh)
                    and not _ch(s, "done", r, sh))
                   or _ch(s, "journal", r, sh)
                   for r in range(n_rids) for sh in range(n_shards))

    def _acked_implies_durable(s):
        return all(s["client"][r] != "acked"
                   or all(_ch(s, "journal", r, sh)
                          for sh in range(n_shards))
                   for r in range(n_rids))

    def _all_acked(s):
        return all(c == "acked" for c in s["client"])

    def _symmetry(s, perm):
        out = dict(s)
        for var in ("msg", "ack", "done", "journal", "absorbed",
                    "sends"):
            out[var] = tuple(tuple(row[perm[i]]
                                   for i in range(n_shards))
                             for row in s[var])
        return out

    invariants = (Invariant("durable-once", _durable_once),)
    if mutant != "ack-before-journal":
        # The mutant acks before journaling BY DESIGN, so these two
        # would fire trivially at the first commit; dropping them makes
        # the checker exhibit the consequential bug — the retried rid
        # double-absorbs (durable-once) — as the counterexample.
        invariants += (
            Invariant("ack-implies-journal", _ack_implies_journal),
            Invariant("acked-implies-durable", _acked_implies_durable),
        )

    return Spec(
        name="ingest_ack" if mutant is None
        else f"ingest_ack[{mutant}]",
        init=init,
        actions=tuple(actions),
        invariants=invariants,
        liveness=(Liveness("every-request-acked", _all_acked),),
        symmetry=_symmetry,
        n_symmetric=n_shards,
        scope={"n_shards": n_shards, "n_rids": n_rids,
               "max_drops": max_drops, "max_crashes": max_crashes,
               "max_sends": max_sends},
    )


__all__ = ["MUTANTS", "SPEC_NAME", "build"]
