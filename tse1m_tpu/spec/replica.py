"""Protocol spec: read-replica streaming with manifest-last commit and
generation adoption (serve/replicate.py).

The model: a writer advances its committed generation; a puller
streams the writer's tree to the replica in the code's fixed order —
CRC-framed shard files, then the cluster state, then the manifest
LAST (the atomicity point, ``fault_point("serve.replica.stream")``
sits just before it).  A crash mid-stream leaves whatever files were
copied (a torn mix of generations) but never a manifest pointing past
them; the replica adopts a view only when the manifest's generation
advances (``refresh``).

Bounded scope (defaults): 2 writer generations, 1 mid-stream crash.
A few dozen states.

Safety: the manifest never references a generation the copied files
do not fully have, and the replica never adopts past the manifest —
so a reader can never observe a torn view.  Liveness (weak fairness
on the pull/adopt steps): the replica converges to the writer's final
generation.

The committed mutation ``manifest-first`` streams the manifest before
the file copies: the checker immediately exhibits the torn window
(manifest ahead of the state file) a crash would freeze forever.
"""

from __future__ import annotations

from .dsl import Action, Invariant, Liveness, Spec, upd

SPEC_NAME = "replica"

MUTANTS = ("manifest-first",)


def build(max_gen: int = 2, max_crashes: int = 1,
          mutant: str | None = None) -> Spec:
    if mutant is not None and mutant not in MUTANTS:
        raise ValueError(f"unknown replica mutant {mutant!r}")
    init = {"writer_gen": 0, "shards_gen": 0, "state_gen": 0,
            "manifest_gen": 0, "adopted": 0, "pull": "idle",
            "crashes": 0, "max_gen": max_gen,
            "max_crashes": max_crashes}

    def writer_commit(s):
        return upd(s, writer_gen=s["writer_gen"] + 1)

    def pull_shards(s):
        out = upd(s, shards_gen=s["writer_gen"], pull="shards")
        if mutant == "manifest-first":
            # BUG under test: the manifest is streamed before the
            # files it references are copied.
            out = upd(out, manifest_gen=s["writer_gen"])
        return out

    def pull_state(s):
        return upd(s, state_gen=s["shards_gen"], pull="state")

    def pull_manifest(s):
        out = upd(s, pull="idle")
        if mutant != "manifest-first":
            out = upd(out, manifest_gen=s["shards_gen"])
        return out

    def crash_pull(s):
        return upd(s, pull="idle", crashes=s["crashes"] + 1)

    def adopt(s):
        return upd(s, adopted=s["manifest_gen"])

    actions = (
        Action("writer_commit",
               lambda s: s["writer_gen"] < s["max_gen"],
               writer_commit, seat="verb:ingest"),
        Action("pull_shards",
               lambda s: s["pull"] == "idle"
               and s["manifest_gen"] < s["writer_gen"],
               pull_shards, seat="call:stream_shards", fair=True),
        Action("pull_state",
               lambda s: s["pull"] == "shards",
               pull_state, seat="call:stream_shards", fair=True),
        Action("pull_manifest",
               lambda s: s["pull"] == "state",
               pull_manifest, seat="fault:serve.replica.stream",
               fair=True),
        Action("crash_pull",
               lambda s: s["pull"] != "idle"
               and s["crashes"] < s["max_crashes"],
               crash_pull, seat="model:crash"),
        Action("adopt",
               lambda s: s["manifest_gen"] > s["adopted"],
               adopt, seat="call:refresh", fair=True),
    )

    def _manifest_within_files(s):
        return (s["manifest_gen"] <= s["shards_gen"]
                and s["manifest_gen"] <= s["state_gen"])

    def _adopted_within_manifest(s):
        return s["adopted"] <= s["manifest_gen"]

    def _converged(s):
        return s["adopted"] == s["writer_gen"]

    return Spec(
        name="replica" if mutant is None else f"replica[{mutant}]",
        init=init,
        actions=actions,
        invariants=(
            Invariant("manifest-within-files", _manifest_within_files),
            Invariant("adopted-within-manifest",
                      _adopted_within_manifest),
        ),
        liveness=(Liveness("replica-converges", _converged),),
        scope={"max_gen": max_gen, "max_crashes": max_crashes},
    )


__all__ = ["MUTANTS", "SPEC_NAME", "build"]
