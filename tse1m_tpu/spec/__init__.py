"""graftspec: executable protocol specs + explicit-state model checking.

The serve plane's distributed obligations — epoch-lease fencing,
durable-once router acks, generation-ordered replica adoption — are
modeled as bounded-state machines (spec/dsl.py) and exhaustively
checked (spec/mc.py) against invariants and weak-fairness liveness;
counterexamples come out as replayable ``v1:fix:...`` graftrace
schedule strings.  The specs are load-bearing, not documentation: the
lint conformance passes (``spec-conformance`` / ``verb-dispatch-drift``
in lint/interproc.py) hold every spec action to a declared code seat
and every serve-plane fault seat / dispatch verb to a spec, and the
committed mutants (spec/mutants.py) prove the checker catches the
exact bug classes the chaos tests guard dynamically.

Entry points: ``python -m tse1m_tpu.cli spec {check,trace,mutants}``;
``cli all`` records a ``graftspec`` step in the run manifest.
"""

from __future__ import annotations

from . import ingest_ack, lease, replica
from .dsl import Action, Invariant, Liveness, Spec, SpecError
from .mc import CheckResult, Violation, check, replay
from .mutants import MUTANT_BUILDERS

SPEC_BUILDERS = {
    "lease": lease.build,
    "ingest_ack": ingest_ack.build,
    "replica": replica.build,
}


def build_spec(name: str) -> Spec:
    """The named protocol spec (or committed mutant) in its default
    bounded scope."""
    if name in SPEC_BUILDERS:
        return SPEC_BUILDERS[name]()
    if name in MUTANT_BUILDERS:
        return MUTANT_BUILDERS[name]()
    known = sorted(SPEC_BUILDERS) + sorted(MUTANT_BUILDERS)
    raise SpecError(f"unknown spec {name!r} (known: {', '.join(known)})")


def check_all(names=None, mode: str = "bfs",
              max_states: int | None = None) -> list:
    """CheckResults for the named real specs (all three by default)."""
    kwargs = {} if max_states is None else {"max_states": max_states}
    out = []
    for name in (names or sorted(SPEC_BUILDERS)):
        if name not in SPEC_BUILDERS:
            raise SpecError(f"unknown spec {name!r} (known: "
                            f"{', '.join(sorted(SPEC_BUILDERS))})")
        out.append(check(SPEC_BUILDERS[name](), mode=mode, **kwargs))
    return out


def mutant_selftest(mode: str = "bfs") -> dict:
    """Run every committed mutant; each MUST produce a violation whose
    counterexample replays back to the machine (the checker's own
    acceptance bar).  Returns per-mutant records; raises SpecError if
    any mutant slips through."""
    records = {}
    missed = []
    for name, builder in sorted(MUTANT_BUILDERS.items()):
        spec = builder()
        result = check(spec, mode=mode)
        rec = {"spec": spec.name, "caught": result.violation is not None,
               "states": result.states}
        if result.violation is None:
            missed.append(name)
        else:
            v = result.violation
            replay(builder(), v.trace + v.cycle)  # must not diverge
            rec.update(kind=v.kind, prop=v.prop,
                       schedule=v.schedule_str, replayed=True)
        records[name] = rec
    if missed:
        raise SpecError(
            f"mutant self-test FAILED: {', '.join(missed)} produced no "
            "violation — the checker does not catch the bug class it "
            "claims to")
    return records


__all__ = ["Action", "CheckResult", "Invariant", "Liveness",
           "MUTANT_BUILDERS", "SPEC_BUILDERS", "Spec", "SpecError",
           "Violation", "build_spec", "check", "check_all",
           "mutant_selftest", "replay"]
