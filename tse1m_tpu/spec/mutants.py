"""The committed spec mutations — the checker's own test corpus.

Each mutant is a deliberate single-decision reordering of one protocol
spec, reproducing a bug class the chaos tests guard dynamically; the
``cli spec mutants`` self-test (and CI's spec-check job) requires the
model checker to produce a violation WITH a replayable counterexample
for every one of them, proving the specs + checker actually encode the
design decisions they claim to:

- ``ack-before-journal``  (ingest_ack): the shard answers before the
  journal entry is durable — a dropped ack's retry double-absorbs.
- ``fence-after-append``  (lease): the commit appends before checking
  the lease epoch — a zombie writes with a stale view.
- ``manifest-first``      (replica): the manifest streams before the
  files it references — a crash freezes a torn view.
"""

from __future__ import annotations

from . import ingest_ack, lease, replica

MUTANT_BUILDERS = {
    "ack-before-journal":
        lambda: ingest_ack.build(mutant="ack-before-journal"),
    "fence-after-append":
        lambda: lease.build(mutant="fence-after-append"),
    "manifest-first":
        lambda: replica.build(mutant="manifest-first"),
}

__all__ = ["MUTANT_BUILDERS"]
