"""The serve plane's verb alphabets, as the specs know them.

These tuples are the spec-side source of truth the
``verb-dispatch-drift`` lint pass holds the code to: the server
dispatch table (``ServeServer._dispatch_op``), the router dispatch
table (``RouterServer._dispatch_op``), the client method set
(``ServeClient``'s ``self.request(<verb>, ...)`` calls) and the
router's shard-forwarding set (``LocalTransport.__call__``) must each
agree EXACTLY with their alphabet here — a verb added to any one
surface without the others (and without the spec) fails lint.

Sorted tuples, string literals only: the lint graph reads them as
module constants, so no computed values.
"""

from __future__ import annotations

# Every verb the single-daemon front end answers (and the client can
# issue — the two surfaces are intentionally identical).
SERVER_VERBS = ("ingest", "metrics", "ping", "profile", "query",
                "quiesce", "shutdown", "slowlog", "status", "topk",
                "trace")

CLIENT_VERBS = ("ingest", "metrics", "ping", "profile", "query",
                "quiesce", "shutdown", "slowlog", "status", "topk",
                "trace")

# The router front end: no slowlog/profile (those are per-daemon
# diagnostics; the router aggregates metrics/trace instead).
ROUTER_VERBS = ("ingest", "metrics", "ping", "query", "quiesce",
                "shutdown", "status", "topk", "trace")

# What the router forwards to shard daemons in-process.
FORWARD_VERBS = ("ingest", "ping", "query", "quiesce", "status",
                 "topk")

__all__ = ["CLIENT_VERBS", "FORWARD_VERBS", "ROUTER_VERBS",
           "SERVER_VERBS"]
