"""graftspec's executable-spec DSL: typed state machines as data.

A spec is a plain Python value: an initial state (a flat dict of
hashable variables), a set of guarded atomic :class:`Action`\\ s, a set
of :class:`Invariant`\\ s checked at every reachable state, and a set
of :class:`Liveness` goals checked against fair infinite behaviors and
terminal states.  The model checker (spec/mc.py) owns the semantics;
this module only owns the vocabulary, so a spec file reads like the
protocol's design note.

Every action carries a **seat** — the code location class it models —
in one of four forms, enforced against the real tree by the lint
conformance pass (``spec-conformance`` in lint/interproc.py):

- ``fault:<site>``  — a production ``fault_point("<site>")`` seat
- ``verb:<op>``     — a serve-plane dispatch verb handler
- ``call:<leaf>``   — a named protocol function/method (lease calls,
  stream/refresh entry points)
- ``model:<tag>``   — a pure environment action (crash, drop, wake)
  with deliberately no code seat

Effects are pure: an action's ``effect`` receives the current state
dict and returns a NEW dict (use :func:`upd`); mutating the input is a
spec bug.  Guards are pure predicates.  Determinism matters — the
checker canonicalizes and hashes states, so every state value must be
hashable after :func:`freeze` (scalars, strings, tuples).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable


class SpecError(Exception):
    """A malformed spec (non-hashable state, unknown action, effect
    mutated its input) — distinct from a property violation, which the
    checker reports as a :class:`~tse1m_tpu.spec.mc.Violation`."""


def freeze(value):
    """Recursively convert a state value to a hashable canonical form
    (lists/tuples -> tuples, dicts -> sorted item tuples)."""
    if isinstance(value, (list, tuple)):
        return tuple(freeze(v) for v in value)
    if isinstance(value, dict):
        return tuple(sorted((k, freeze(v)) for k, v in value.items()))
    if isinstance(value, set):
        return tuple(sorted(freeze(v) for v in value))
    return value


def state_key(state: dict) -> tuple:
    """The canonical hashable encoding of one state dict."""
    try:
        out = tuple(sorted((k, freeze(v)) for k, v in state.items()))
        hash(out)  # fail HERE, not deep inside the checker's node map
        return out
    except TypeError as e:  # unhashable leaf
        raise SpecError(f"state has a non-freezable value: {e}") from e


def upd(state: dict, **changes) -> dict:
    """A new state with ``changes`` applied — the only sanctioned way
    for an effect to 'write'."""
    out = dict(state)
    out.update(changes)
    return out


def tupset(t: tuple, i: int, value) -> tuple:
    """``t`` with element ``i`` replaced (tuples model per-process
    variable arrays)."""
    return t[:i] + (value,) + t[i + 1:]


@dataclass(frozen=True)
class Action:
    """One guarded atomic step.  ``fair`` marks weak fairness: an
    action continuously enabled along an infinite behavior must
    eventually be taken (the checker rejects lassos that starve it)."""

    name: str
    guard: Callable[[dict], bool]
    effect: Callable[[dict], dict]
    seat: str = "model:env"
    fair: bool = False

    def __post_init__(self):
        if any(ch in self.name for ch in ",:\n "):
            raise SpecError(
                f"action name {self.name!r} is not schedule-safe "
                "(no ',', ':' or whitespace — names become "
                "v1:fix: schedule tokens)")
        kind = self.seat.split(":", 1)[0]
        if kind not in ("fault", "verb", "call", "model"):
            raise SpecError(f"action {self.name!r} has unknown seat "
                            f"kind {self.seat!r}")


@dataclass(frozen=True)
class Invariant:
    """A safety property: ``pred(state)`` must hold at EVERY reachable
    state."""

    name: str
    pred: Callable[[dict], bool]


@dataclass(frozen=True)
class Liveness:
    """A progress property in the ``[]<>goal`` shape: along every fair
    infinite behavior the goal holds infinitely often, and every
    terminal (deadlocked/quiescent) state satisfies it.  This covers
    both 'eventually acked' (goal stays true once reached) and
    response-style goals like 'no live zombie' (re-established after
    every excursion)."""

    name: str
    goal: Callable[[dict], bool]


@dataclass(frozen=True)
class Spec:
    """A bounded protocol model.

    ``symmetry``: optional ``(state, perm) -> state`` renaming states
    under a permutation of ``range(n_symmetric)`` process ids; the
    checker quotients the reachable graph by it (action names in
    counterexamples are then valid modulo that renaming — replay goes
    through :func:`~tse1m_tpu.spec.mc.replay`, which canonicalizes the
    same way)."""

    name: str
    init: dict
    actions: tuple = ()
    invariants: tuple = ()
    liveness: tuple = ()
    symmetry: Callable[[dict, tuple], dict] | None = None
    n_symmetric: int = 0
    scope: dict = field(default_factory=dict)  # bound knobs, for display

    def __post_init__(self):
        names = [a.name for a in self.actions]
        if len(set(names)) != len(names):
            dup = sorted({n for n in names if names.count(n) > 1})
            raise SpecError(f"spec {self.name!r} has duplicate action "
                            f"names {dup}")

    def action(self, name: str) -> Action:
        for a in self.actions:
            if a.name == name:
                return a
        raise SpecError(f"spec {self.name!r} has no action {name!r}")

    def enabled(self, state: dict) -> list:
        return [a for a in self.actions if a.guard(state)]


__all__ = ["Action", "Invariant", "Liveness", "Spec", "SpecError",
           "freeze", "state_key", "tupset", "upd"]
