"""Device-side segment primitives.

These replace the reference's per-project Python loops with single fused
device ops (SURVEY.md §2.3, §3.1):

- :func:`segment_searchsorted` — session/iteration indexing: "iteration of an
  event = number of builds strictly before its timestamp"
  (rq1_detection_rate.py:226-227, rq4a_bug.py:344-346) as one vectorised
  binary search over a CSR array.  O(Q log N) gathers, XLA-friendly fixed
  trip count, no [P x maxB] padding materialised.
- :func:`counts_to_survival` — per-iteration project population
  (rq1_detection_rate.py:195-200): #projects with >= k builds, via bincount
  + reversed cumsum.
- :func:`unique_pairs_count_per_iteration` — "unique detected projects per
  iteration" (rq1_detection_rate.py:249) as a boolean scatter + column sum.
- :func:`masked_percentile` — percentiles over padded ragged rows (the
  rebuild form of the per-session np.percentile over ragged lists,
  rq2_coverage_count.py:149-152).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np


def segment_searchsorted(values, offsets, queries, query_segments, side: str = "left",
                         values_lo=None, queries_lo=None):
    """Vectorised per-segment searchsorted.

    Args:
      values: [N] array, sorted ascending *within* each segment.
      offsets: [P+1] int array of segment boundaries (CSR).
      queries: [Q] query values.
      query_segments: [Q] int array mapping each query to its segment.
      side: 'left' -> count of elements strictly < query (the reference's
        ``issue_ts > build_ts`` rule); 'right' -> count of elements <= query.
      values_lo/queries_lo: optional low-order components for lexicographic
        comparison — lets int64-ns timestamps ride as two int32 lanes
        (seconds, ns remainder) without enabling x64 on device, keeping
        exact sub-second ordering semantics.

    Returns:
      [Q] int32 insertion positions relative to each query's segment start.
    """
    values = jnp.asarray(values)
    offsets = jnp.asarray(offsets, dtype=jnp.int32)
    queries = jnp.asarray(queries)
    query_segments = jnp.asarray(query_segments, dtype=jnp.int32)
    two_lane = values_lo is not None
    if two_lane:
        values_lo = jnp.asarray(values_lo)
        queries_lo = jnp.asarray(queries_lo)
    n = values.shape[0]
    if n == 0:
        return jnp.zeros(queries.shape, dtype=jnp.int32)

    lo = offsets[query_segments]
    hi = offsets[query_segments + 1]
    start = lo
    is_left = side == "left"
    n_iters = max(1, int(math.ceil(math.log2(max(n, 2)))) + 1)

    def body(carry, _):
        lo, hi = carry
        active = lo < hi
        mid = jnp.clip((lo + hi) // 2, 0, n - 1)
        v = values[mid]
        if two_lane:
            vl = values_lo[mid]
            lt = (v < queries) | ((v == queries) & (vl < queries_lo))
            le = (v < queries) | ((v == queries) & (vl <= queries_lo))
            go_right = lt if is_left else le
        else:
            go_right = (v < queries) if is_left else (v <= queries)
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
        return (lo, hi), None

    (lo, hi), _ = jax.lax.scan(body, (lo, hi), None, length=n_iters)
    return (lo - start).astype(jnp.int32)


def counts_to_survival(counts, max_k: int):
    """#segments with count >= k, for k = 1..max_k.

    counts: [P] int array of per-segment element counts.
    Returns [max_k] int32 where out[k-1] = sum(counts >= k).
    """
    counts = jnp.asarray(counts)
    hist = jnp.bincount(jnp.clip(counts, 0, max_k), length=max_k + 1)
    # survival[k] = #projects with count >= k  (k in 1..max_k)
    total = counts.shape[0]
    below = jnp.cumsum(hist)  # below[k] = #projects with count <= k
    return (total - below[:-1]).astype(jnp.int32)


def unique_pairs_count_per_iteration(segments, iterations, n_segments: int, max_k: int):
    """Count *unique* segments hitting each iteration.

    segments: [Q] int segment id per event; iterations: [Q] 1-based iteration
    per event (0 or > max_k are ignored).  Returns [max_k] int32 where
    out[k-1] = #unique segments with at least one event at iteration k.
    """
    segments = jnp.asarray(segments, dtype=jnp.int32)
    iterations = jnp.asarray(iterations, dtype=jnp.int32)
    valid = (iterations >= 1) & (iterations <= max_k)
    # Route invalid events to a scratch column (index 0 of a max_k+1 grid).
    col = jnp.where(valid, iterations, 0)
    grid = jnp.zeros((n_segments, max_k + 1), dtype=jnp.bool_)
    grid = grid.at[segments, col].set(True, mode="drop")
    return grid[:, 1:].sum(axis=0, dtype=jnp.int32)


def masked_mean(x, mask):
    """Mean per row of a padded matrix over valid entries; NaN if none."""
    x = jnp.asarray(x, dtype=jnp.float32)
    mask = jnp.asarray(mask)
    n = mask.sum(axis=-1)
    s = jnp.where(mask, x, 0.0).sum(axis=-1)
    return jnp.where(n > 0, s / n, jnp.nan)


def masked_spearman(x, mask):
    """Spearman correlation of each padded row against its session index.

    The device form of the reference's per-project
    ``spearmanr(range(n), coverage_trend)`` loop
    (rq2_coverage_count.py:316-320): average-rank ties (scipy's default),
    Pearson on the ranks.  x: [R, C]; mask: [R, C] bool.  Rows with < 2
    valid entries or zero variance return NaN.
    """
    x = jnp.asarray(x, dtype=jnp.float32)
    mask = jnp.asarray(mask)
    C = x.shape[-1]
    if C == 0:
        return jnp.full(x.shape[:-1], jnp.nan, dtype=jnp.float32)

    def one_row(xr, mr):
        big = jnp.float32(np.finfo(np.float32).max)
        filled = jnp.where(mr, xr, big)
        order = jnp.argsort(filled)          # valid entries first, by value
        sorted_vals = filled[order]
        pos = jnp.arange(C, dtype=jnp.float32)
        new_grp = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), sorted_vals[1:] != sorted_vals[:-1]])
        gid = jnp.cumsum(new_grp.astype(jnp.int32)) - 1
        gsum = jax.ops.segment_sum(pos, gid, num_segments=C)
        gcnt = jax.ops.segment_sum(jnp.ones(C, jnp.float32), gid,
                                   num_segments=C)
        avg_pos = gsum / jnp.maximum(gcnt, 1.0)
        ranks_sorted = avg_pos[gid] + 1.0     # 1-based average ranks
        ranks = jnp.zeros(C, jnp.float32).at[order].set(ranks_sorted)
        # index ranks: 1..n over valid entries in original order (no ties)
        idx_rank = jnp.cumsum(mr.astype(jnp.float32)) * mr
        n = mr.sum().astype(jnp.float32)
        rx = jnp.where(mr, ranks, 0.0)
        ry = idx_rank
        sx, sy = rx.sum(), ry.sum()
        sxx = (rx * rx).sum()
        syy = (ry * ry).sum()
        sxy = (rx * ry).sum()
        cov = sxy - sx * sy / jnp.maximum(n, 1.0)
        vx = sxx - sx * sx / jnp.maximum(n, 1.0)
        vy = syy - sy * sy / jnp.maximum(n, 1.0)
        denom = jnp.sqrt(vx * vy)
        return jnp.where((n >= 2) & (denom > 0), cov / denom, jnp.nan)

    return jax.vmap(one_row)(x, mask)


def masked_percentile(x, mask, q):
    """Percentile per row of a padded matrix, ignoring masked-out entries.

    x: [R, C] values; mask: [R, C] bool (True = valid); q: scalar or [K]
    percentiles in [0, 100].  Linear interpolation, matching np.percentile.
    Rows with no valid entries return NaN.
    """
    scalar_q = np.ndim(q) == 0
    x = jnp.asarray(x, dtype=jnp.float32)
    mask = jnp.asarray(mask)
    if x.shape[-1] == 0:
        shape = x.shape[:-1] if scalar_q else (np.shape(q)[0],) + x.shape[:-1]
        return jnp.full(shape, jnp.nan, dtype=jnp.float32)
    big = jnp.float32(np.finfo(np.float32).max)
    filled = jnp.where(mask, x, big)
    s = jnp.sort(filled, axis=-1)  # valid entries first, pads at the end
    n_valid = mask.sum(axis=-1)  # [R]
    q = jnp.atleast_1d(jnp.asarray(q, dtype=jnp.float32))

    def one_q(qi):
        pos = (n_valid.astype(jnp.float32) - 1.0) * qi / 100.0
        lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, s.shape[-1] - 1)
        hi = jnp.clip(lo + 1, 0, s.shape[-1] - 1)
        frac = pos - lo.astype(jnp.float32)
        vlo = jnp.take_along_axis(s, lo[:, None], axis=-1)[:, 0]
        vhi = jnp.take_along_axis(s, hi[:, None], axis=-1)[:, 0]
        hi_valid = (lo + 1) <= (n_valid - 1)
        out = vlo + jnp.where(hi_valid, frac * (vhi - vlo), 0.0)
        return jnp.where(n_valid > 0, out, jnp.nan)

    out = jax.vmap(one_q)(q)  # [K, R]
    return out[0] if scalar_q else out
