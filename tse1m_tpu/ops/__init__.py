from .segment import (
    segment_searchsorted,
    counts_to_survival,
    unique_pairs_count_per_iteration,
    masked_percentile,
)

__all__ = [
    "segment_searchsorted",
    "counts_to_survival",
    "unique_pairs_count_per_iteration",
    "masked_percentile",
]
