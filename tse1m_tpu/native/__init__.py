"""Native host-decode layer (SURVEY §2.4's C++ seat).

``fetch_table()`` — when available — streams a sqlite query into typed
numpy columns in one C++ pass (see ``decode.cc``).  The extension is
compiled on first use with the system ``g++`` and cached next to the
source; every failure mode (no compiler, no libsqlite3, unparseable data)
degrades to ``None`` so callers fall back to the pandas path.  The rebuild
therefore never *requires* native code — it is a throughput lever for the
1.19M-build extraction stage, not a correctness dependency.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig
import tempfile

from ..utils.logging import get_logger

log = get_logger("native")

_SRC = os.path.join(os.path.dirname(__file__), "decode.cc")
_SO = os.path.join(os.path.dirname(__file__), "_tse1m_decode.so")

_module = None
_tried = False


def _compile() -> bool:
    import numpy as np

    def cmd(std: str) -> list:
        return [
            "g++", "-O2", std, "-shared", "-fPIC",
            "-I" + sysconfig.get_paths()["include"],
            "-I" + np.get_include(),
            _SRC,
            "-l:libsqlite3.so.0",
        ]

    # Atomic replace so concurrent first-callers never import a half-written
    # object; the temp file must live on the same filesystem for rename.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(_SO))
    os.close(fd)
    try:
        # C++20 first (heterogeneous string_view map lookup in the hot
        # per-cell scan — decode.cc SvMap); toolchains without it (g++ <11)
        # retry C++17, where decode.cc compiles its std::string-temporary
        # lookup form — slower per cell but the native path stays alive.
        errors = []
        for std in ("-std=c++20", "-std=c++17"):
            proc = subprocess.run(cmd(std) + ["-o", tmp],
                                  capture_output=True, text=True,
                                  timeout=300)
            if proc.returncode == 0:
                break
            tail = (proc.stderr.strip().splitlines()[-1]
                    if proc.stderr.strip() else proc.returncode)
            errors.append(f"{std}: {tail}")
        else:
            # Every attempt's diagnostic is kept — the first one usually
            # names the real problem, the retry's would mask it.
            log.info("native decode build failed (falling back to pandas "
                     "path): %s", " | ".join(map(str, errors)))
            return False
        os.replace(tmp, _SO)
        return True
    except Exception as e:  # no g++, sandboxed exec, ...
        log.info("native decode unavailable (%s); using pandas path", e)
        return False
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load():
    global _module, _tried
    if _tried:
        return _module
    _tried = True
    try:
        stale = (not os.path.exists(_SO)
                 or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if stale and not _compile():
            return None
        spec = importlib.util.spec_from_file_location("_tse1m_decode", _SO)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _module = mod
        log.info("native sqlite decoder loaded (%s)", _SO)
    except Exception as e:
        log.info("native decode import failed (%s); using pandas path", e)
        _module = None
    return _module


def fetch_table(db_path: str, sql: str, params, spec: str, key_values):
    """Run ``sql`` against ``db_path`` and decode per ``spec`` (see
    decode.cc).  Returns a tuple of numpy arrays, or None when the native
    path is unavailable — callers must treat None as "use the fallback".
    Raises RuntimeError for data the strict native parsers reject (e.g.
    timezone-suffixed timestamps); callers catch and fall back.
    """
    mod = _load()
    if mod is None:
        return None
    return mod.fetch_table(db_path, sql, tuple(params), spec,
                           list(key_values))
