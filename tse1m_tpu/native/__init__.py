"""Native host layer (SURVEY §2.4's C++ seat).

Two compile-on-first-use CPython extensions, each cached next to its
source and rebuilt when the source is newer:

- ``fetch_table()`` / ``decode.cc`` — streams a sqlite query into typed
  numpy columns in one C++ pass (the 1.19M-build extraction stage).
- ``group_delta_native()`` / ``encode.cc`` — the base-delta grouping pass
  feeding the cluster pipeline's H2D encoding (cluster/encode.py).

Every failure mode (no compiler, no libsqlite3, unparseable data)
degrades to ``None`` so callers fall back to the pure-Python path.  The
rebuild therefore never *requires* native code — it is a throughput
lever, not a correctness dependency.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig
import tempfile

from ..utils.logging import get_logger

log = get_logger("native")

_DIR = os.path.dirname(__file__)


def _build_and_load(name: str, src: str, so: str, stds: tuple,
                    link_flags: tuple, fallback_note: str,
                    deps: tuple = ()):
    """Compile ``src`` -> ``so`` (if stale) and import it.  Returns the
    module or None; never raises — the caller's pure-Python path is the
    recovery strategy for every failure mode.  ``deps`` are additional
    source files (headers) whose changes must trigger a rebuild."""
    import numpy as np

    try:
        newest = max(os.path.getmtime(p) for p in (src, *deps))
        stale = (not os.path.exists(so)
                 or os.path.getmtime(so) < newest)
        if stale:
            # Atomic replace so concurrent first-callers never import a
            # half-written object; the temp file must live on the same
            # filesystem for rename.
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
            os.close(fd)
            try:
                errors = []
                for std in stds:
                    proc = subprocess.run(
                        ["g++", "-O2", std, "-shared", "-fPIC",
                         "-I" + sysconfig.get_paths()["include"],
                         "-I" + np.get_include(), src, *link_flags,
                         "-o", tmp],
                        capture_output=True, text=True, timeout=300)
                    if proc.returncode == 0:
                        break
                    tail = (proc.stderr.strip().splitlines()[-1]
                            if proc.stderr.strip() else proc.returncode)
                    errors.append(f"{std}: {tail}")
                else:
                    # Every attempt's diagnostic is kept — the first one
                    # usually names the real problem, the retry's would
                    # mask it.
                    log.info("native %s build failed (%s): %s", name,
                             fallback_note, " | ".join(map(str, errors)))
                    return None
                os.replace(tmp, so)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        spec = importlib.util.spec_from_file_location(name, so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        log.info("native %s loaded (%s)", name, so)
        return mod
    except Exception as e:  # no g++, sandboxed exec, import failure, ...
        from ..resilience import reraise_if_fault

        reraise_if_fault(e)  # the pandas fallback is the designed path
        log.info("native %s unavailable (%s); %s", name, e, fallback_note)
        return None


_module = None
_tried = False


def _load():
    global _module, _tried
    if _tried:
        return _module
    _tried = True
    # C++20 first (heterogeneous string_view map lookup in the hot
    # per-cell scan — decode.cc SvMap); toolchains without it (g++ <11)
    # retry C++17, where decode.cc compiles its std::string-temporary
    # lookup form — slower per cell but the native path stays alive.
    _module = _build_and_load(
        "_tse1m_decode", os.path.join(_DIR, "decode.cc"),
        os.path.join(_DIR, "_tse1m_decode.so"),
        stds=("-std=c++20", "-std=c++17"),
        link_flags=("-l:libsqlite3.so.0",),
        fallback_note="using pandas path",
        deps=(os.path.join(_DIR, "columns.h"),))
    return _module


_enc_module = None
_enc_tried = False


def _load_encode():
    """Separate object from the decoder: encode.cc has no sqlite
    dependency, so a missing libsqlite3 cannot take the encoder down
    with it."""
    global _enc_module, _enc_tried
    if _enc_tried:
        return _enc_module
    _enc_tried = True
    _enc_module = _build_and_load(
        "_tse1m_encode", os.path.join(_DIR, "encode.cc"),
        os.path.join(_DIR, "_tse1m_encode.so"),
        stds=("-std=c++17",), link_flags=(),
        fallback_note="using numpy encoder")
    return _enc_module


_pg_module = None
_pg_tried = False


def _load_pg():
    """Postgres COPY-binary decoder (pg_decode.cc); links against
    libpq.so.5 directly (inline prototypes — this image ships the library
    without headers)."""
    global _pg_module, _pg_tried
    if _pg_tried:
        return _pg_module
    _pg_tried = True
    _pg_module = _build_and_load(
        "_tse1m_pgdecode", os.path.join(_DIR, "pg_decode.cc"),
        os.path.join(_DIR, "_tse1m_pgdecode.so"),
        stds=("-std=c++20", "-std=c++17"),
        link_flags=("-l:libpq.so.5",),
        fallback_note="using driver-row path",
        deps=(os.path.join(_DIR, "columns.h"),))
    return _pg_module


def parse_copy_binary(data: bytes, spec: str, key_values):
    """Decode a Postgres COPY-binary stream per ``spec`` (decode.cc's spec
    language), or None when the native path is unavailable."""
    mod = _load_pg()
    if mod is None:
        return None
    return mod.parse_copy_binary(data, spec, list(key_values))


def fetch_table_pg(conninfo: str, copy_sql: str, spec: str, key_values):
    """Run ``copy_sql`` (a ``COPY ... TO STDOUT (FORMAT binary)``
    statement) against ``conninfo`` and decode per ``spec``.  Returns a
    tuple of numpy arrays, or None when the native path is unavailable;
    raises RuntimeError for streams the strict parsers reject — callers
    catch and fall back, same ladder as the sqlite decoder."""
    mod = _load_pg()
    if mod is None:
        return None
    return mod.fetch_table_pg(conninfo, copy_sql, spec, list(key_values))


def group_delta_native(items, max_diffs: int, n_probes: int):
    """C++ grouping pass for cluster/encode.py, or None when the native
    path is unavailable — the caller falls back to the numpy encoder."""
    mod = _load_encode()
    if mod is None:
        return None
    return mod.group_delta(items, int(max_diffs), int(n_probes))


def fetch_table(db_path: str, sql: str, params, spec: str, key_values):
    """Run ``sql`` against ``db_path`` and decode per ``spec`` (see
    decode.cc).  Returns a tuple of numpy arrays, or None when the native
    path is unavailable — callers must treat None as "use the fallback".
    Raises RuntimeError for data the strict native parsers reject (e.g.
    timezone-suffixed timestamps); callers catch and fall back.
    """
    mod = _load()
    if mod is None:
        return None
    return mod.fetch_table(db_path, sql, tuple(params), spec,
                           list(key_values))
