// Shared column accumulators + numpy materialisation for the native
// decoders (decode.cc: sqlite scan; pg_decode.cc: Postgres COPY-binary
// scan).  The two scans read very different wire formats, but build the
// SAME per-spec-char columns and materialise them identically — one
// implementation keeps the Python-side consumers (data/columnar.py's
// CodedColumn/BytesColumn contracts) honest across both engines.
//
// Include contract: this header is textually included INSIDE each .cc's
// anonymous namespace, AFTER <Python.h>, <numpy/arrayobject.h> and the
// std headers it relies on (<cstdint>, <cstring>, <string>,
// <string_view>, <unordered_map>, <vector>) — it performs no #includes
// of its own so it can live at internal linkage in each translation unit.

// 'o' cell tags.
enum : uint8_t { O_NULL = 0, O_INT = 1, O_FLOAT = 2, O_TEXT = 3 };

struct TextRef {
  size_t off;
  int32_t len;  // -1 = NULL
};

// Heterogeneous (string_view) lookup for the hot per-cell maps: a plain
// std::unordered_map<std::string, …>::find forces a std::string temporary
// per CELL — ~4M heap allocations per 1M-build study across the key and
// intern maps.  Transparent hash/eq let the scan probe with a string_view
// and allocate only on first insertion of a distinct value.  Generic
// unordered lookup needs C++20/libstdc++ >= 11; older toolchains compile
// the std::string-temporary form instead (the Python builder retries with
// -std=c++17) — slower per cell, but the native path stays alive.
#if defined(__cpp_lib_generic_unordered_lookup) && \
    __cpp_lib_generic_unordered_lookup >= 201811L
struct SvHash {
  using is_transparent = void;
  size_t operator()(std::string_view s) const noexcept {
    return std::hash<std::string_view>{}(s);
  }
  size_t operator()(const std::string &s) const noexcept {
    return std::hash<std::string_view>{}(std::string_view(s));
  }
};
using SvMap =
    std::unordered_map<std::string, int32_t, SvHash, std::equal_to<>>;
template <typename M>
inline auto sv_find(M &m, std::string_view k) {
  return m.find(k);
}
#else
using SvMap = std::unordered_map<std::string, int32_t>;
template <typename M>
inline auto sv_find(M &m, std::string_view k) {
  return m.find(std::string(k));
}
#endif

struct Col {
  char spec;                          // p/t/f/s/u/o (+ c/b)
  std::vector<int32_t> i32;           // 'p', and 's'/'c' intern ids
  std::vector<int64_t> i64;           // 't', and 'o' ints
  std::vector<double> f64;            // 'f', and 'o' floats
  std::vector<uint8_t> tag;           // 'o'
  std::vector<TextRef> text;          // 'u'/'b'/'o' arena refs
  std::string arena;                  // 'u'/'b'/'o' raw text bytes
  std::vector<std::string> distinct;  // 's'/'c' intern table
  SvMap intern;                       // 's'/'c'
};

inline PyObject *err(const std::string &msg) {
  PyErr_Format(PyExc_RuntimeError, "native decode: %s", msg.c_str());
  return nullptr;
}

template <typename T>
PyObject *numeric_array(const std::vector<T> &v, int npy_type) {
  npy_intp n = static_cast<npy_intp>(v.size());
  PyObject *arr = PyArray_SimpleNew(1, &n, npy_type);
  if (arr)
    memcpy(PyArray_DATA(reinterpret_cast<PyArrayObject *>(arr)), v.data(),
           v.size() * sizeof(T));
  return arr;
}

// key_values list -> value -> index map (the 'p' column codes).
inline bool build_keymap(PyObject *keys_o, SvMap &keymap) {
  PyObject *fast = PySequence_Fast(keys_o, "key_values");
  if (!fast) return false;
  const Py_ssize_t nk = PySequence_Fast_GET_SIZE(fast);
  for (Py_ssize_t i = 0; i < nk; i++) {
    Py_ssize_t sl;
    const char *sp =
        PyUnicode_AsUTF8AndSize(PySequence_Fast_GET_ITEM(fast, i), &sl);
    if (!sp) {
      Py_DECREF(fast);
      return false;
    }
    keymap.emplace(std::string(sp, sl), static_cast<int32_t>(i));
  }
  Py_DECREF(fast);
  return true;
}

// One column -> numpy array (GIL held), or NULL with an exception set.
inline PyObject *materialize(Col &c) {
  switch (c.spec) {
    case 'p':
      return numeric_array(c.i32, NPY_INT32);
    case 't':
      return numeric_array(c.i64, NPY_INT64);
    case 'f':
      return numeric_array(c.f64, NPY_FLOAT64);
    default:
      break;
  }
  if (c.spec == 'b') {
    // Lazy bytes column: (uint8 arena, int64 starts, int32 lens) — zero
    // per-row Python objects; the Python BytesColumn wrapper decodes
    // single cells on demand (consumers touch only tiny subsets of these
    // near-unique columns).  len -1 = NULL.
    std::vector<int64_t> starts(c.text.size());
    std::vector<int32_t> lens(c.text.size());
    for (size_t i = 0; i < c.text.size(); i++) {
      starts[i] = static_cast<int64_t>(c.text[i].off);
      lens[i] = c.text[i].len;
    }
    npy_intp asize = static_cast<npy_intp>(c.arena.size());
    PyObject *arena = PyArray_SimpleNew(1, &asize, NPY_UINT8);
    if (!arena) return nullptr;
    memcpy(PyArray_DATA(reinterpret_cast<PyArrayObject *>(arena)),
           c.arena.data(), c.arena.size());
    PyObject *st = numeric_array(starts, NPY_INT64);
    PyObject *ln = numeric_array(lens, NPY_INT32);
    if (!st || !ln) {
      Py_DECREF(arena);
      Py_XDECREF(st);
      Py_XDECREF(ln);
      return nullptr;
    }
    PyObject *triple = PyTuple_Pack(3, arena, st, ln);
    Py_DECREF(arena);
    Py_DECREF(st);
    Py_DECREF(ln);
    return triple;
  }
  if (c.spec == 'c') {
    // Coded column: (int32 codes, vocab list) — ZERO per-row Python
    // objects.  -1 = NULL; vocab order is first appearance (matches
    // pd.factorize in the fallback, so codes are byte-identical).
    PyObject *codes = numeric_array(c.i32, NPY_INT32);
    if (!codes) return nullptr;
    PyObject *vocab = PyList_New(static_cast<Py_ssize_t>(c.distinct.size()));
    if (!vocab) {
      Py_DECREF(codes);
      return nullptr;
    }
    for (size_t i = 0; i < c.distinct.size(); i++) {
      PyObject *o = PyUnicode_DecodeUTF8(
          c.distinct[i].data(),
          static_cast<Py_ssize_t>(c.distinct[i].size()), nullptr);
      if (!o) {
        Py_DECREF(codes);
        Py_DECREF(vocab);
        return nullptr;
      }
      PyList_SET_ITEM(vocab, static_cast<Py_ssize_t>(i), o);
    }
    PyObject *pair = PyTuple_Pack(2, codes, vocab);
    Py_DECREF(codes);
    Py_DECREF(vocab);
    return pair;
  }
  const size_t n_rows = c.spec == 's' ? c.i32.size() : c.text.size();
  npy_intp n = static_cast<npy_intp>(n_rows);
  PyObject *arr = PyArray_SimpleNew(1, &n, NPY_OBJECT);
  if (!arr) return nullptr;
  PyObject **data = reinterpret_cast<PyObject **>(
      PyArray_DATA(reinterpret_cast<PyArrayObject *>(arr)));
  if (c.spec == 's') {
    std::vector<PyObject *> uniq(c.distinct.size());
    for (size_t i = 0; i < c.distinct.size(); i++) {
      uniq[i] = PyUnicode_DecodeUTF8(c.distinct[i].data(),
                                     static_cast<Py_ssize_t>(
                                         c.distinct[i].size()), nullptr);
      if (!uniq[i]) {
        for (size_t j = 0; j < i; j++) Py_DECREF(uniq[j]);
        Py_DECREF(arr);
        return nullptr;
      }
    }
    for (size_t r = 0; r < n_rows; r++) {
      PyObject *o = c.i32[r] < 0 ? Py_None : uniq[c.i32[r]];
      Py_INCREF(o);
      data[r] = o;
    }
    for (auto *o : uniq) Py_DECREF(o);  // array rows now hold the refs
    return arr;
  }
  for (size_t r = 0; r < n_rows; r++) {
    const TextRef &t = c.text[r];
    PyObject *o;
    if (c.spec == 'o' && c.tag[r] == O_INT)
      o = PyLong_FromLongLong(c.i64[r]);
    else if (c.spec == 'o' && c.tag[r] == O_FLOAT)
      o = PyFloat_FromDouble(c.f64[r]);
    else if (t.len < 0) {
      o = Py_None;
      Py_INCREF(o);
    } else {
      o = PyUnicode_DecodeUTF8(c.arena.data() + t.off, t.len, nullptr);
    }
    if (!o) {
      Py_DECREF(arr);  // frees the rows materialized so far
      return nullptr;
    }
    data[r] = o;
  }
  return arr;
}
