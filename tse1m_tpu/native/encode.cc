// Native grouping pass for the cluster pipeline's base-delta H2D encoding
// (cluster/encode.py — see that module's docstring for the scheme).
//
// The numpy encoder spends ~2.3 s/1M rows in its sketch + group + verify
// passes on this image's single host core — a large bite out of the ~3 s
// the encoding saves on a ~25 MB/s tunneled PJRT link.  This C++ pass does
// the same work in one thread in ~0.2-0.4 s: per probe, hash each pooled
// row (multiply-add), key it by (min, max) of the hashed row, and attach
// verified near-duplicates (exact diff count <= max_diffs) to the first
// row seen with their key.  Python keeps the cheap vectorised extraction.
//
// Contract mirror of cluster/encode.py::_group_rows: returns rep_of[N]
// int64 (-1 = full lane) with the no-chain invariant — a row with
// children is pinned and can never itself become a delta row.  The two
// encoders need not produce identical groupings (both are verified and
// decode bit-exactly); tests assert the invariants on each.

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

#include <cstdint>
#include <vector>

namespace {

constexpr uint32_t kProbes[][2] = {
    {0x9E3779B1u, 0x85EBCA77u},
    {0xC2B2AE3Du, 0x27D4EB2Fu},
    {0x165667B1u, 0x9E3779B9u},
    {0x85EBCA6Bu, 0xC2B2AE35u},
};
constexpr int kMaxProbes = 4;

uint64_t sketch_key(const uint32_t *row, npy_intp s, uint32_t a, uint32_t b) {
  uint32_t mn = 0xFFFFFFFFu, mx = 0;
  for (npy_intp j = 0; j < s; j++) {
    const uint32_t h = row[j] * a + b;  // wraps, same as numpy uint32
    if (h < mn) mn = h;
    if (h > mx) mx = h;
  }
  return (static_cast<uint64_t>(mn) << 32) | mx;
}

// Open-addressing key -> first-row table.  The raw (min << 32 | max)
// keys concentrate their high bits (both order statistics live in narrow
// bands), so slots come from a splitmix64 finalizer; linear probing at
// <= 50% load.  ~3x faster than unordered_map on the 1M-row pass.
struct FirstSeen {
  std::vector<uint64_t> keys;
  std::vector<int64_t> rows;
  uint64_t mask = 0;

  void reset(size_t n_entries) {
    size_t cap = 16;
    while (cap < n_entries * 2) cap <<= 1;
    keys.assign(cap, 0);
    rows.assign(cap, -1);
    mask = cap - 1;
  }

  static uint64_t mix(uint64_t x) {
    x += 0x9E3779B97F4A7C15ull;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
    return x ^ (x >> 31);
  }

  // Returns the first row seen with `key`, inserting `row` if new.
  int64_t insert_or_get(uint64_t key, int64_t row) {
    if (key == 0) key = 1;  // 0 marks an empty slot
    uint64_t i = mix(key) & mask;
    for (;; i = (i + 1) & mask) {
      if (keys[i] == key) return rows[i];
      if (keys[i] == 0) {
        keys[i] = key;
        rows[i] = row;
        return row;
      }
    }
  }
};

void group_rows(const uint32_t *items, npy_intp n, npy_intp s, int max_diffs,
                int n_probes, int64_t *rep_of) {
  std::vector<uint8_t> pinned(static_cast<size_t>(n), 0);
  std::vector<int64_t> pool(static_cast<size_t>(n));
  for (npy_intp i = 0; i < n; i++) {
    rep_of[i] = -1;
    pool[static_cast<size_t>(i)] = i;
  }
  std::vector<uint64_t> keys;
  FirstSeen first;
  for (int p = 0; p < n_probes && p < kMaxProbes; p++) {
    if (pool.size() < 2) break;
    keys.resize(pool.size());
    for (size_t k = 0; k < pool.size(); k++)
      keys[k] = sketch_key(items + pool[k] * s, s, kProbes[p][0],
                           kProbes[p][1]);
    first.reset(pool.size());
    // Pinned rows claim their key first (ascending order), so stragglers
    // attach to existing bases instead of spawning a duplicate base —
    // same priority rule as the numpy encoder's (key, pinned-first) sort.
    for (size_t k = 0; k < pool.size(); k++)
      if (pinned[static_cast<size_t>(pool[k])])
        first.insert_or_get(keys[k], pool[k]);
    for (size_t k = 0; k < pool.size(); k++) {
      const int64_t row = pool[k];
      if (pinned[static_cast<size_t>(row)]) continue;
      const int64_t rep = first.insert_or_get(keys[k], row);
      if (rep == row) continue;
      const uint32_t *ra = items + row * s, *rb = items + rep * s;
      int nd = 0;
      for (npy_intp j = 0; j < s && nd <= max_diffs; j++) nd += ra[j] != rb[j];
      if (nd <= max_diffs) {
        rep_of[row] = rep;
        pinned[static_cast<size_t>(rep)] = 1;
      }
    }
    size_t w = 0;
    for (size_t k = 0; k < pool.size(); k++)
      if (rep_of[pool[k]] < 0) pool[w++] = pool[k];
    pool.resize(w);
  }
}

PyObject *group_delta(PyObject *, PyObject *args) {
  PyObject *items_o;
  int max_diffs, n_probes;
  if (!PyArg_ParseTuple(args, "Oii", &items_o, &max_diffs, &n_probes))
    return nullptr;
  PyArrayObject *items = reinterpret_cast<PyArrayObject *>(
      PyArray_FROM_OTF(items_o, NPY_UINT32, NPY_ARRAY_C_CONTIGUOUS));
  if (!items) return nullptr;
  if (PyArray_NDIM(items) != 2) {
    Py_DECREF(items);
    PyErr_SetString(PyExc_ValueError, "items must be 2-D");
    return nullptr;
  }
  const npy_intp n = PyArray_DIM(items, 0), s = PyArray_DIM(items, 1);
  npy_intp dims[1] = {n};
  PyArrayObject *rep = reinterpret_cast<PyArrayObject *>(
      PyArray_SimpleNew(1, dims, NPY_INT64));
  if (!rep) {
    Py_DECREF(items);
    return nullptr;
  }
  const uint32_t *ip = static_cast<const uint32_t *>(PyArray_DATA(items));
  int64_t *rp = static_cast<int64_t *>(PyArray_DATA(rep));
  Py_BEGIN_ALLOW_THREADS;
  group_rows(ip, n, s, max_diffs, n_probes, rp);
  Py_END_ALLOW_THREADS;
  Py_DECREF(items);
  return reinterpret_cast<PyObject *>(rep);
}

PyMethodDef methods[] = {
    {"group_delta", group_delta, METH_VARARGS,
     "group_delta(items[N,S] uint32, max_diffs, n_probes) -> rep_of[N] "
     "int64 (-1 = full lane)"},
    {nullptr, nullptr, 0, nullptr}};

struct PyModuleDef moddef = {PyModuleDef_HEAD_INIT, "_tse1m_encode",
                             "base-delta grouping pass", -1, methods,
                             nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__tse1m_encode(void) {
  import_array();
  return PyModule_Create(&moddef);
}
