// Native Postgres decoder: COPY ... TO STDOUT (FORMAT binary) -> typed
// numpy columns in one C++ pass.
//
// The reference's deployment is Postgres (dbFile.py:26-38,
// docker-compose.yml:10-20), but until round 5 only sqlite had a native
// extraction path (decode.cc) — Postgres rode the pandas fallback at ~2x
// the wall.  This decoder closes that asymmetry: the columnar layer wraps
// each bulk query in `COPY (SELECT ...) TO STDOUT (FORMAT binary)`; libpq
// streams the rows; the binary frames decode straight into the SAME
// column accumulators decode.cc fills (columns.h), so the Python-side
// contract (CodedColumn/BytesColumn/int64-ns lanes) is identical.
//
// Binary COPY format (postgresql.org/docs/current/sql-copy.html):
//   header: "PGCOPY\n\377\r\n\0" + int32 flags + int32 extension length
//   tuple:  int16 field count, then per field int32 byte length (-1 =
//           NULL) + payload; trailer: int16 -1
// Per-type payloads used here (all big-endian):
//   timestamptz  int64 microseconds since 2000-01-01 UTC
//   date         int32 days since 2000-01-01
//   float8       IEEE double
//   text         raw bytes (array columns are cast ::text by the wrapper
//                SQL, so their Postgres literal form arrives as text —
//                exactly what data/columnar.py's parse_array consumes)
//
// Parity contract (same as decode.cc): anything the strict decoders
// cannot prove they handle — unexpected payload widths, infinity
// timestamps, unknown 'p' keys — raises, and the caller falls back to
// the pandas path.  The parser is exposed separately
// (parse_copy_binary) so tests cover it without a live server.
//
// The libpq prototypes are declared inline because this image ships
// libpq.so.5 without its headers; these are the documented, ABI-stable
// public API (postgresql.org/docs/current/libpq.html).

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

extern "C" {
typedef struct pg_conn PGconn;
typedef struct pg_result PGresult;
PGconn *PQconnectdb(const char *);
int PQstatus(const PGconn *);
char *PQerrorMessage(const PGconn *);
void PQfinish(PGconn *);
PGresult *PQexec(PGconn *, const char *);
PGresult *PQgetResult(PGconn *);
int PQresultStatus(const PGresult *);
char *PQresultErrorMessage(const PGresult *);
void PQclear(PGresult *);
int PQgetCopyData(PGconn *, char **, int);
void PQfreemem(void *);
}

#define CONNECTION_OK 0
#define PGRES_COMMAND_OK 1
#define PGRES_TUPLES_OK 2
#define PGRES_COPY_OUT 3

namespace {

#include "columns.h"

// ---- COPY binary stream parsing --------------------------------------------

constexpr int64_t kPgEpochNs = 946684800LL * 1000000000LL;  // 2000-01-01 UTC
const char kSignature[11] = {'P', 'G', 'C', 'O', 'P', 'Y',
                             '\n', '\377', '\r', '\n', '\0'};

inline int16_t be16(const uint8_t *p) {
  return static_cast<int16_t>((p[0] << 8) | p[1]);
}
inline int32_t be32(const uint8_t *p) {
  return static_cast<int32_t>((static_cast<uint32_t>(p[0]) << 24) |
                              (static_cast<uint32_t>(p[1]) << 16) |
                              (static_cast<uint32_t>(p[2]) << 8) | p[3]);
}
inline int64_t be64(const uint8_t *p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return static_cast<int64_t>(v);
}

// Decode the whole stream into cols.  Empty string on success.
std::string parse_stream(const uint8_t *data, size_t size,
                         const SvMap &keymap, std::vector<Col> &cols) {
  const size_t ncol = cols.size();
  size_t pos = 0;
  auto need = [&](size_t n) { return pos + n <= size; };
  if (!need(19) || memcmp(data, kSignature, 11) != 0)
    return "bad COPY binary signature";
  pos = 11;
  const int32_t flags = be32(data + pos);
  pos += 4;
  if (flags & 0xFFFF0000) return "incompatible COPY flags";
  const int32_t extlen = be32(data + pos);
  pos += 4;
  if (extlen < 0 || !need(static_cast<size_t>(extlen)))
    return "bad COPY header extension";
  pos += static_cast<size_t>(extlen);

  for (;;) {
    if (!need(2)) return "truncated stream (no trailer)";
    const int16_t nfields = be16(data + pos);
    pos += 2;
    if (nfields == -1) break;  // trailer
    if (static_cast<size_t>(nfields) != ncol)
      return "field count != spec length";
    for (size_t ci = 0; ci < ncol; ci++) {
      Col &c = cols[ci];
      if (!need(4)) return "truncated field length";
      const int32_t len = be32(data + pos);
      pos += 4;
      const bool null = len < 0;
      if (!null && !need(static_cast<size_t>(len)))
        return "truncated field payload";
      const uint8_t *p = data + pos;
      if (!null) pos += static_cast<size_t>(len);
      switch (c.spec) {
        case 'p': {
          if (null) return "NULL key column";
          auto it = sv_find(keymap, std::string_view(
              reinterpret_cast<const char *>(p),
              static_cast<size_t>(len)));
          if (it == keymap.end()) return "key value not in key_values";
          c.i32.push_back(it->second);
          break;
        }
        case 't': {
          if (null) return "NULL timestamp (caller should fall back)";
          if (len == 8) {  // timestamp(tz): us since 2000-01-01
            const int64_t us = be64(p);
            if (us == INT64_MAX || us == INT64_MIN)
              return "infinity timestamp (caller should fall back)";
            c.i64.push_back(us * 1000 + kPgEpochNs);
          } else if (len == 4) {  // date: days since 2000-01-01
            const int64_t d = be32(p);
            c.i64.push_back(d * 86400LL * 1000000000LL + kPgEpochNs);
          } else {
            return "unexpected timestamp width";
          }
          break;
        }
        case 'f': {
          if (null) {
            c.f64.push_back(Py_NAN);
          } else if (len == 8) {
            const int64_t bits = be64(p);
            double d;
            memcpy(&d, &bits, 8);
            c.f64.push_back(d);
          } else {
            return "unexpected float width (caller should fall back)";
          }
          break;
        }
        case 's':
        case 'c': {
          if (null) {
            c.i32.push_back(-1);
            break;
          }
          const std::string_view key(reinterpret_cast<const char *>(p),
                                     static_cast<size_t>(len));
          auto it = sv_find(c.intern, key);
          if (it == c.intern.end()) {
            it = c.intern
                     .emplace(std::string(key),
                              static_cast<int32_t>(c.distinct.size()))
                     .first;
            c.distinct.push_back(it->first);
          }
          c.i32.push_back(it->second);
          break;
        }
        case 'u':
        case 'b': {
          if (null) {
            c.text.push_back({0, -1});
            break;
          }
          c.text.push_back({c.arena.size(), len});
          c.arena.append(reinterpret_cast<const char *>(p),
                         static_cast<size_t>(len));
          break;
        }
        case 'o': {  // text passthrough (COPY binary carries no type tag)
          if (null) {
            c.tag.push_back(O_NULL);
            c.i64.push_back(0);
            c.f64.push_back(0.0);
            c.text.push_back({0, -1});
          } else {
            c.tag.push_back(O_TEXT);
            c.i64.push_back(0);
            c.f64.push_back(0.0);
            c.text.push_back({c.arena.size(), len});
            c.arena.append(reinterpret_cast<const char *>(p),
                           static_cast<size_t>(len));
          }
          break;
        }
      }
    }
  }
  return "";
}

// libpq COPY transport: run `sql` (a COPY ... TO STDOUT statement) and
// collect the whole binary stream.  Empty string on success.
std::string fetch_stream(const std::string &conninfo, const std::string &sql,
                         std::string &out) {
  PGconn *conn = PQconnectdb(conninfo.c_str());
  auto fail = [&](const std::string &msg) {
    std::string full = msg;
    if (conn) {
      full += ": ";
      full += PQerrorMessage(conn);
      PQfinish(conn);
    }
    return full;
  };
  if (!conn || PQstatus(conn) != CONNECTION_OK) return fail("connect failed");
  PGresult *res = PQexec(conn, sql.c_str());
  if (PQresultStatus(res) != PGRES_COPY_OUT) {
    std::string msg = PQresultErrorMessage(res);
    PQclear(res);
    return fail("COPY did not start: " + msg);
  }
  PQclear(res);
  char *buf = nullptr;
  int n;
  while ((n = PQgetCopyData(conn, &buf, 0)) > 0) {
    out.append(buf, static_cast<size_t>(n));
    PQfreemem(buf);
    buf = nullptr;
  }
  if (n == -2) return fail("COPY stream error");
  // Drain the command-completion result(s).
  bool ok = true;
  while ((res = PQgetResult(conn)) != nullptr) {
    const int st = PQresultStatus(res);
    if (st != PGRES_COMMAND_OK && st != PGRES_TUPLES_OK) ok = false;
    PQclear(res);
  }
  if (!ok) return fail("COPY did not complete cleanly");
  PQfinish(conn);
  return "";
}

// ---- Python entry points ---------------------------------------------------

PyObject *decode_cols(const std::string &spec, std::vector<Col> &cols) {
  PyObject *out = PyTuple_New(static_cast<Py_ssize_t>(cols.size()));
  if (!out) return nullptr;
  for (size_t i = 0; i < cols.size(); i++) {
    PyObject *arr = materialize(cols[i]);
    if (!arr) {
      Py_DECREF(out);
      return nullptr;
    }
    PyTuple_SET_ITEM(out, static_cast<Py_ssize_t>(i), arr);
  }
  return out;
}

bool init_cols(const char *spec_c, std::vector<Col> &cols) {
  const std::string spec(spec_c);
  cols.resize(spec.size());
  for (size_t i = 0; i < spec.size(); i++) {
    cols[i].spec = spec[i];
    if (!strchr("ptfscubo", spec[i])) {
      err("unknown spec char");
      return false;
    }
  }
  return true;
}

// parse_copy_binary(data: bytes, spec, key_values) -> tuple of arrays.
// The server-independent half — unit-tested on crafted streams.
PyObject *parse_copy_binary(PyObject *, PyObject *args) {
  const char *spec_c;
  PyObject *data_o, *keys_o;
  if (!PyArg_ParseTuple(args, "SsO", &data_o, &spec_c, &keys_o))
    return nullptr;
  std::vector<Col> cols;
  if (!init_cols(spec_c, cols)) return nullptr;
  SvMap keymap;
  if (!build_keymap(keys_o, keymap)) return nullptr;
  const uint8_t *data = reinterpret_cast<const uint8_t *>(
      PyBytes_AS_STRING(data_o));
  const size_t size = static_cast<size_t>(PyBytes_GET_SIZE(data_o));
  std::string e;
  Py_BEGIN_ALLOW_THREADS;
  e = parse_stream(data, size, keymap, cols);
  Py_END_ALLOW_THREADS;
  if (!e.empty()) return err(e);
  return decode_cols(spec_c, cols);
}

// fetch_table_pg(conninfo, copy_sql, spec, key_values) -> tuple of arrays.
PyObject *fetch_table_pg(PyObject *, PyObject *args) {
  const char *conninfo_c, *sql_c, *spec_c;
  PyObject *keys_o;
  if (!PyArg_ParseTuple(args, "sssO", &conninfo_c, &sql_c, &spec_c, &keys_o))
    return nullptr;
  std::vector<Col> cols;
  if (!init_cols(spec_c, cols)) return nullptr;
  SvMap keymap;
  if (!build_keymap(keys_o, keymap)) return nullptr;
  std::string stream, e;
  Py_BEGIN_ALLOW_THREADS;
  e = fetch_stream(conninfo_c, sql_c, stream);
  if (e.empty())
    e = parse_stream(reinterpret_cast<const uint8_t *>(stream.data()),
                     stream.size(), keymap, cols);
  Py_END_ALLOW_THREADS;
  if (!e.empty()) return err(e);
  return decode_cols(spec_c, cols);
}

PyMethodDef methods[] = {
    {"parse_copy_binary", parse_copy_binary, METH_VARARGS,
     "parse_copy_binary(data, spec, key_values) -> tuple of numpy arrays"},
    {"fetch_table_pg", fetch_table_pg, METH_VARARGS,
     "fetch_table_pg(conninfo, copy_sql, spec, key_values) -> tuple of "
     "numpy arrays"},
    {nullptr, nullptr, 0, nullptr}};

struct PyModuleDef moddef = {PyModuleDef_HEAD_INIT, "_tse1m_pgdecode",
                             "Postgres COPY-binary -> numpy bulk decoder",
                             -1, methods, nullptr, nullptr, nullptr,
                             nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__tse1m_pgdecode(void) {
  import_array();
  return PyModule_Create(&moddef);
}
