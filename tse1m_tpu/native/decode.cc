// Native bulk decoder: sqlite rows -> typed numpy columns in one C++ pass.
//
// This is the TPU-rebuild's answer to the reference's hot host boundary
// (SURVEY §2.4): the reference pays the per-cell Python-object cost once
// per row x column over ~1.19M builds (rq1_detection_rate.py:192-203 via
// psycopg2 fetchall; our sqlite twin showed the same profile — ~60% of
// extraction wall time inside Cursor.fetchall).  Here the sqlite3 C API
// streams straight into preallocated C++ vectors:
//   - ISO8601 timestamps parse to int64 epoch-nanoseconds in C (bit-parity
//     with pandas.to_datetime(format="ISO8601") asserted in
//     tests/test_native_decode.py; anything the strict parser cannot prove
//     it parses identically — timezones, junk — raises, and the caller
//     falls back to the pandas path),
//   - repeated TEXT cells (result enums, modules/revisions arrays) intern
//     through a hash map so each distinct value allocates ONE PyUnicode,
//   - numerics land in numpy buffers with no intermediate tuples.
//
// The sqlite3 prototypes are declared inline because this image ships
// libsqlite3.so.0 without its header; the declarations below are the
// documented, ABI-stable public C API (sqlite.org/c3ref).

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {
typedef struct sqlite3 sqlite3;
typedef struct sqlite3_stmt sqlite3_stmt;
int sqlite3_open_v2(const char *, sqlite3 **, int, const char *);
int sqlite3_prepare_v2(sqlite3 *, const char *, int, sqlite3_stmt **,
                       const char **);
int sqlite3_bind_text(sqlite3_stmt *, int, const char *, int, void (*)(void *));
int sqlite3_bind_int64(sqlite3_stmt *, int, long long);
int sqlite3_bind_double(sqlite3_stmt *, int, double);
int sqlite3_step(sqlite3_stmt *);
int sqlite3_column_count(sqlite3_stmt *);
int sqlite3_column_type(sqlite3_stmt *, int);
const unsigned char *sqlite3_column_text(sqlite3_stmt *, int);
int sqlite3_column_bytes(sqlite3_stmt *, int);
long long sqlite3_column_int64(sqlite3_stmt *, int);
double sqlite3_column_double(sqlite3_stmt *, int);
int sqlite3_finalize(sqlite3_stmt *);
int sqlite3_close(sqlite3 *);
const char *sqlite3_errmsg(sqlite3 *);
}

#define SQLITE_OK 0
#define SQLITE_ROW 100
#define SQLITE_DONE 101
#define SQLITE_OPEN_READONLY 0x01
#define SQLITE_INTEGER 1
#define SQLITE_FLOAT 2
#define SQLITE_TEXT 3
#define SQLITE_NULL 5
#define SQLITE_TRANSIENT ((void (*)(void *))(intptr_t)-1)

namespace {

// ---- ISO8601 -> epoch ns ---------------------------------------------------

inline bool all_digits(const char *s, int n) {
  for (int i = 0; i < n; i++)
    if (s[i] < '0' || s[i] > '9') return false;
  return true;
}

inline long long to_int(const char *s, int n) {
  long long v = 0;
  for (int i = 0; i < n; i++) v = v * 10 + (s[i] - '0');
  return v;
}

// Howard Hinnant's days_from_civil (public-domain algorithm).
inline int64_t days_from_civil(int y, unsigned m, unsigned d) {
  y -= m <= 2;
  const int era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = static_cast<unsigned>(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return static_cast<int64_t>(era) * 146097 + static_cast<int64_t>(doe) -
         719468;
}

// Strict parse of "YYYY-MM-DD", "YYYY-MM-DD[ T]HH:MM[:SS[.frac]]".
// Returns false on anything else (timezone suffixes included) — the caller
// then falls back to the pandas parser rather than guessing.
bool parse_iso_ns(const char *s, int len, int64_t *out) {
  if (len < 10) return false;
  if (!all_digits(s, 4) || s[4] != '-' || !all_digits(s + 5, 2) ||
      s[7] != '-' || !all_digits(s + 8, 2))
    return false;
  const int y = static_cast<int>(to_int(s, 4));
  const unsigned mo = static_cast<unsigned>(to_int(s + 5, 2));
  const unsigned d = static_cast<unsigned>(to_int(s + 8, 2));
  if (mo < 1 || mo > 12 || d < 1) return false;
  // Real month lengths (leap-aware): days_from_civil would silently
  // normalize e.g. Feb 30 -> Mar 1, where pandas raises — and a raise is
  // what routes the fetch to the fallback.
  static const unsigned mdays[] = {31, 28, 31, 30, 31, 30,
                                   31, 31, 30, 31, 30, 31};
  const bool leap = (y % 4 == 0 && y % 100 != 0) || y % 400 == 0;
  if (d > (mo == 2 && leap ? 29u : mdays[mo - 1])) return false;
  int64_t secs = days_from_civil(y, mo, d) * 86400;
  int64_t frac_ns = 0;
  if (len > 10) {
    if ((s[10] != ' ' && s[10] != 'T') || len < 16) return false;
    if (!all_digits(s + 11, 2) || s[13] != ':' || !all_digits(s + 14, 2))
      return false;
    const long long hh = to_int(s + 11, 2), mi = to_int(s + 14, 2);
    if (hh > 23 || mi > 59) return false;
    secs += hh * 3600 + mi * 60;
    int pos = 16;
    if (len > 16) {
      if (s[16] != ':' || len < 19 || !all_digits(s + 17, 2)) return false;
      const long long ss = to_int(s + 17, 2);
      if (ss > 59) return false;
      secs += ss;
      pos = 19;
      if (len > 19) {
        if (s[19] != '.') return false;
        int nd = len - 20;
        if (nd < 1 || nd > 9 || !all_digits(s + 20, nd)) return false;
        long long f = to_int(s + 20, nd);
        for (int i = nd; i < 9; i++) f *= 10;
        frac_ns = f;
        pos = len;
      }
    }
    if (pos != len) return false;
  }
  *out = secs * 1000000000LL + frac_ns;
  return true;
}

// ---- column accumulators ---------------------------------------------------

struct Col {
  char spec;                       // p/t/f/s/u/o
  std::vector<int32_t> i32;        // 'p'
  std::vector<int64_t> i64;        // 't'
  std::vector<double> f64;         // 'f'
  std::vector<PyObject *> obj;     // 's'/'u'/'o' (owned refs)
  std::unordered_map<std::string, PyObject *> intern;  // 's' (borrowed into obj)
};

struct Closer {
  sqlite3 *db = nullptr;
  sqlite3_stmt *stmt = nullptr;
  std::vector<Col> *cols = nullptr;
  ~Closer() {
    if (stmt) sqlite3_finalize(stmt);
    if (db) sqlite3_close(db);
    if (cols)
      for (auto &c : *cols) {
        for (auto *o : c.obj) Py_XDECREF(o);
        // Error-path cleanup: each interned value still holds the map's
        // extra ref (the success path clears intern before building the
        // output arrays, making this a no-op there).
        for (auto &kv : c.intern) Py_DECREF(kv.second);
      }
  }
};

PyObject *err(const char *msg, sqlite3 *db = nullptr) {
  PyErr_Format(PyExc_RuntimeError, "native decode: %s%s%s", msg,
               db ? ": " : "", db ? sqlite3_errmsg(db) : "");
  return nullptr;
}

// fetch_table(db_path, sql, params, spec, key_values) -> tuple of arrays
//
// spec: one char per selected column —
//   p  TEXT key -> int32 code via the key_values list (error if unseen)
//   t  TEXT ISO8601 -> int64 epoch-ns
//   f  numeric -> float64 (NULL -> NaN)
//   s  TEXT -> object array, values interned per column
//   u  TEXT -> object array, no interning (high-cardinality, e.g. names)
//   o  object array preserving sqlite's native type (int/float/text/None)
PyObject *fetch_table(PyObject *, PyObject *args) {
  const char *db_path, *sql, *spec;
  PyObject *params, *keys;
  if (!PyArg_ParseTuple(args, "ssOsO", &db_path, &sql, &params, &spec, &keys))
    return nullptr;
  if (!PySequence_Check(params) || !PySequence_Check(keys))
    return err("params and key_values must be sequences");

  const Py_ssize_t ncol = static_cast<Py_ssize_t>(strlen(spec));
  std::vector<Col> cols(ncol);
  for (Py_ssize_t i = 0; i < ncol; i++) {
    cols[i].spec = spec[i];
    if (!strchr("ptfsuo", spec[i])) return err("unknown spec char");
  }

  std::unordered_map<std::string, int32_t> keymap;
  {
    PyObject *fast = PySequence_Fast(keys, "key_values");
    if (!fast) return nullptr;
    const Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    for (Py_ssize_t i = 0; i < n; i++) {
      Py_ssize_t sl;
      const char *sp =
          PyUnicode_AsUTF8AndSize(PySequence_Fast_GET_ITEM(fast, i), &sl);
      if (!sp) {
        Py_DECREF(fast);
        return nullptr;
      }
      keymap.emplace(std::string(sp, sl), static_cast<int32_t>(i));
    }
    Py_DECREF(fast);
  }

  Closer guard;
  guard.cols = &cols;
  if (sqlite3_open_v2(db_path, &guard.db, SQLITE_OPEN_READONLY, nullptr) !=
      SQLITE_OK)
    return err("cannot open database", guard.db);
  if (sqlite3_prepare_v2(guard.db, sql, -1, &guard.stmt, nullptr) != SQLITE_OK)
    return err("prepare failed", guard.db);

  {
    PyObject *fast = PySequence_Fast(params, "params");
    if (!fast) return nullptr;
    const Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject *p = PySequence_Fast_GET_ITEM(fast, i);
      int rc;
      if (PyUnicode_Check(p)) {
        Py_ssize_t sl;
        const char *sp = PyUnicode_AsUTF8AndSize(p, &sl);
        if (!sp) {
          Py_DECREF(fast);
          return nullptr;
        }
        rc = sqlite3_bind_text(guard.stmt, static_cast<int>(i + 1), sp,
                               static_cast<int>(sl), SQLITE_TRANSIENT);
      } else if (PyLong_Check(p)) {
        rc = sqlite3_bind_int64(guard.stmt, static_cast<int>(i + 1),
                                PyLong_AsLongLong(p));
      } else if (PyFloat_Check(p)) {
        rc = sqlite3_bind_double(guard.stmt, static_cast<int>(i + 1),
                                 PyFloat_AsDouble(p));
      } else {
        Py_DECREF(fast);
        return err("unsupported parameter type");
      }
      if (rc != SQLITE_OK) {
        Py_DECREF(fast);
        return err("bind failed", guard.db);
      }
    }
    Py_DECREF(fast);
  }

  if (sqlite3_column_count(guard.stmt) != static_cast<int>(ncol))
    return err("spec length != selected column count");

  int rc;
  while ((rc = sqlite3_step(guard.stmt)) == SQLITE_ROW) {
    for (Py_ssize_t i = 0; i < ncol; i++) {
      Col &c = cols[i];
      const int ci = static_cast<int>(i);
      const int ty = sqlite3_column_type(guard.stmt, ci);
      switch (c.spec) {
        case 'p': {
          if (ty != SQLITE_TEXT) return err("key column must be TEXT");
          const char *sp = reinterpret_cast<const char *>(
              sqlite3_column_text(guard.stmt, ci));
          auto it = keymap.find(
              std::string(sp, sqlite3_column_bytes(guard.stmt, ci)));
          if (it == keymap.end()) return err("key value not in key_values");
          c.i32.push_back(it->second);
          break;
        }
        case 't': {
          if (ty != SQLITE_TEXT) return err("timestamp column must be TEXT");
          int64_t ns;
          if (!parse_iso_ns(reinterpret_cast<const char *>(
                                sqlite3_column_text(guard.stmt, ci)),
                            sqlite3_column_bytes(guard.stmt, ci), &ns))
            return err("unparseable timestamp (caller should fall back)");
          c.i64.push_back(ns);
          break;
        }
        case 'f': {
          // TEXT is rejected rather than coerced: sqlite3_column_double
          // turns junk text into 0.0 silently, while the pandas fallback
          // raises on malformed numerics — falling back keeps that
          // fail-loudly contract.
          if (ty == SQLITE_NULL)
            c.f64.push_back(Py_NAN);
          else if (ty == SQLITE_INTEGER || ty == SQLITE_FLOAT)
            c.f64.push_back(sqlite3_column_double(guard.stmt, ci));
          else
            return err("non-numeric cell in float column "
                       "(caller should fall back)");
          break;
        }
        case 's':
        case 'u': {
          if (ty == SQLITE_NULL) {
            Py_INCREF(Py_None);
            c.obj.push_back(Py_None);
            break;
          }
          const char *sp = reinterpret_cast<const char *>(
              sqlite3_column_text(guard.stmt, ci));
          const int sl = sqlite3_column_bytes(guard.stmt, ci);
          if (c.spec == 's') {
            std::string key(sp, sl);
            auto it = c.intern.find(key);
            if (it != c.intern.end()) {
              Py_INCREF(it->second);
              c.obj.push_back(it->second);
            } else {
              PyObject *o = PyUnicode_DecodeUTF8(sp, sl, nullptr);
              if (!o) return nullptr;
              c.intern.emplace(std::move(key), o);
              Py_INCREF(o);  // one ref held via obj, one via intern map
              c.obj.push_back(o);
            }
          } else {
            PyObject *o = PyUnicode_DecodeUTF8(sp, sl, nullptr);
            if (!o) return nullptr;
            c.obj.push_back(o);
          }
          break;
        }
        case 'o': {
          PyObject *o;
          if (ty == SQLITE_NULL) {
            o = Py_None;
            Py_INCREF(o);
          } else if (ty == SQLITE_INTEGER) {
            o = PyLong_FromLongLong(sqlite3_column_int64(guard.stmt, ci));
          } else if (ty == SQLITE_FLOAT) {
            o = PyFloat_FromDouble(sqlite3_column_double(guard.stmt, ci));
          } else {
            o = PyUnicode_DecodeUTF8(reinterpret_cast<const char *>(
                                         sqlite3_column_text(guard.stmt, ci)),
                                     sqlite3_column_bytes(guard.stmt, ci),
                                     nullptr);
          }
          if (!o) return nullptr;
          c.obj.push_back(o);
          break;
        }
      }
    }
  }
  if (rc != SQLITE_DONE) return err("step failed", guard.db);
  // Intern maps hold one extra ref per distinct value; release those now.
  for (auto &c : cols)
    for (auto &kv : c.intern) Py_DECREF(kv.second);
  for (auto &c : cols) c.intern.clear();

  PyObject *out = PyTuple_New(ncol);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < ncol; i++) {
    Col &c = cols[i];
    npy_intp n;
    PyObject *arr = nullptr;
    switch (c.spec) {
      case 'p':
        n = static_cast<npy_intp>(c.i32.size());
        arr = PyArray_SimpleNew(1, &n, NPY_INT32);
        if (arr)
          memcpy(PyArray_DATA(reinterpret_cast<PyArrayObject *>(arr)),
                 c.i32.data(), c.i32.size() * sizeof(int32_t));
        break;
      case 't':
        n = static_cast<npy_intp>(c.i64.size());
        arr = PyArray_SimpleNew(1, &n, NPY_INT64);
        if (arr)
          memcpy(PyArray_DATA(reinterpret_cast<PyArrayObject *>(arr)),
                 c.i64.data(), c.i64.size() * sizeof(int64_t));
        break;
      case 'f':
        n = static_cast<npy_intp>(c.f64.size());
        arr = PyArray_SimpleNew(1, &n, NPY_FLOAT64);
        if (arr)
          memcpy(PyArray_DATA(reinterpret_cast<PyArrayObject *>(arr)),
                 c.f64.data(), c.f64.size() * sizeof(double));
        break;
      default: {
        n = static_cast<npy_intp>(c.obj.size());
        arr = PyArray_SimpleNew(1, &n, NPY_OBJECT);
        if (arr) {
          PyObject **data = reinterpret_cast<PyObject **>(
              PyArray_DATA(reinterpret_cast<PyArrayObject *>(arr)));
          // Transfer ownership of each ref into the (NULL-initialised)
          // object array.
          memcpy(data, c.obj.data(), c.obj.size() * sizeof(PyObject *));
          c.obj.clear();  // refs now owned by the array
        }
        break;
      }
    }
    if (!arr) {
      Py_DECREF(out);
      return nullptr;
    }
    PyTuple_SET_ITEM(out, i, arr);
  }
  return out;
}

PyMethodDef methods[] = {
    {"fetch_table", fetch_table, METH_VARARGS,
     "fetch_table(db_path, sql, params, spec, key_values) -> tuple of numpy "
     "arrays"},
    {nullptr, nullptr, 0, nullptr}};

struct PyModuleDef moddef = {PyModuleDef_HEAD_INIT, "_tse1m_decode",
                             "sqlite -> numpy bulk decoder", -1, methods,
                             nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__tse1m_decode(void) {
  import_array();
  return PyModule_Create(&moddef);
}
