// Native bulk decoder: sqlite rows -> typed numpy columns in one C++ pass.
//
// This is the TPU-rebuild's answer to the reference's hot host boundary
// (SURVEY §2.4): the reference pays the per-cell Python-object cost once
// per row x column over ~1.19M builds (rq1_detection_rate.py:192-203 via
// psycopg2 fetchall; our sqlite twin showed the same profile — ~60% of
// extraction wall time inside Cursor.fetchall).
//
// Two phases:
//   1. GIL-RELEASED scan: sqlite3_step loop entirely in C++ — project-key
//      lookups, strict ISO8601 -> epoch-ns parsing, numerics into typed
//      vectors, text into an arena (interned text into a per-column
//      distinct-string table).  Because the GIL is dropped, the four study
//      tables can be fetched concurrently from Python threads and the
//      decoder never stalls other Python work.
//   2. GIL-HELD materialisation: numpy buffers via memcpy; ONE PyUnicode
//      per distinct interned value; arena text -> PyUnicode for
//      high-cardinality columns.
//
// Parity contract: anything the strict parsers cannot prove they decode
// identically to the pandas path (timezone suffixes, junk text in numeric
// columns, unknown keys) raises, and the caller falls back to pandas —
// asserted in tests/test_native_decode.py.
//
// The sqlite3 prototypes are declared inline because this image ships
// libsqlite3.so.0 without its header; the declarations below are the
// documented, ABI-stable public C API (sqlite.org/c3ref).

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <variant>
#include <vector>

extern "C" {
typedef struct sqlite3 sqlite3;
typedef struct sqlite3_stmt sqlite3_stmt;
int sqlite3_open_v2(const char *, sqlite3 **, int, const char *);
int sqlite3_prepare_v2(sqlite3 *, const char *, int, sqlite3_stmt **,
                       const char **);
int sqlite3_bind_text(sqlite3_stmt *, int, const char *, int, void (*)(void *));
int sqlite3_bind_int64(sqlite3_stmt *, int, long long);
int sqlite3_bind_double(sqlite3_stmt *, int, double);
int sqlite3_step(sqlite3_stmt *);
int sqlite3_column_count(sqlite3_stmt *);
int sqlite3_column_type(sqlite3_stmt *, int);
const unsigned char *sqlite3_column_text(sqlite3_stmt *, int);
int sqlite3_column_bytes(sqlite3_stmt *, int);
long long sqlite3_column_int64(sqlite3_stmt *, int);
double sqlite3_column_double(sqlite3_stmt *, int);
int sqlite3_finalize(sqlite3_stmt *);
int sqlite3_close(sqlite3 *);
const char *sqlite3_errmsg(sqlite3 *);
}

#define SQLITE_OK 0
#define SQLITE_ROW 100
#define SQLITE_DONE 101
#define SQLITE_OPEN_READONLY 0x01
#define SQLITE_INTEGER 1
#define SQLITE_FLOAT 2
#define SQLITE_TEXT 3
#define SQLITE_NULL 5
#define SQLITE_TRANSIENT ((void (*)(void *))(intptr_t)-1)

namespace {

// ---- ISO8601 -> epoch ns ---------------------------------------------------

inline bool all_digits(const char *s, int n) {
  for (int i = 0; i < n; i++)
    if (s[i] < '0' || s[i] > '9') return false;
  return true;
}

inline long long to_int(const char *s, int n) {
  long long v = 0;
  for (int i = 0; i < n; i++) v = v * 10 + (s[i] - '0');
  return v;
}

// Howard Hinnant's days_from_civil (public-domain algorithm).
inline int64_t days_from_civil(int y, unsigned m, unsigned d) {
  y -= m <= 2;
  const int era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = static_cast<unsigned>(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return static_cast<int64_t>(era) * 146097 + static_cast<int64_t>(doe) -
         719468;
}

// Strict parse of "YYYY-MM-DD", "YYYY-MM-DD[ T]HH:MM[:SS[.frac]]".
// Returns false on anything else (timezone suffixes included) — the caller
// then falls back to the pandas parser rather than guessing.
bool parse_iso_ns(const char *s, int len, int64_t *out) {
  if (len < 10) return false;
  if (!all_digits(s, 4) || s[4] != '-' || !all_digits(s + 5, 2) ||
      s[7] != '-' || !all_digits(s + 8, 2))
    return false;
  const int y = static_cast<int>(to_int(s, 4));
  const unsigned mo = static_cast<unsigned>(to_int(s + 5, 2));
  const unsigned d = static_cast<unsigned>(to_int(s + 8, 2));
  if (mo < 1 || mo > 12 || d < 1) return false;
  // Real month lengths (leap-aware): days_from_civil would silently
  // normalize e.g. Feb 30 -> Mar 1, where pandas raises — and a raise is
  // what routes the fetch to the fallback.
  static const unsigned mdays[] = {31, 28, 31, 30, 31, 30,
                                   31, 31, 30, 31, 30, 31};
  const bool leap = (y % 4 == 0 && y % 100 != 0) || y % 400 == 0;
  if (d > (mo == 2 && leap ? 29u : mdays[mo - 1])) return false;
  int64_t secs = days_from_civil(y, mo, d) * 86400;
  int64_t frac_ns = 0;
  if (len > 10) {
    if ((s[10] != ' ' && s[10] != 'T') || len < 16) return false;
    if (!all_digits(s + 11, 2) || s[13] != ':' || !all_digits(s + 14, 2))
      return false;
    const long long hh = to_int(s + 11, 2), mi = to_int(s + 14, 2);
    if (hh > 23 || mi > 59) return false;
    secs += hh * 3600 + mi * 60;
    int pos = 16;
    if (len > 16) {
      if (s[16] != ':' || len < 19 || !all_digits(s + 17, 2)) return false;
      const long long ss = to_int(s + 17, 2);
      if (ss > 59) return false;
      secs += ss;
      pos = 19;
      if (len > 19) {
        if (s[19] != '.') return false;
        int nd = len - 20;
        if (nd < 1 || nd > 9 || !all_digits(s + 20, nd)) return false;
        long long f = to_int(s + 20, nd);
        for (int i = nd; i < 9; i++) f *= 10;
        frac_ns = f;
        pos = len;
      }
    }
    if (pos != len) return false;
  }
  *out = secs * 1000000000LL + frac_ns;
  return true;
}

// ---- GIL-free column accumulators (shared with pg_decode.cc) ---------------

#include "columns.h"

using Param = std::variant<std::string, long long, double>;

// Phase 1: everything between open and finalize runs WITHOUT the GIL.
// Returns empty string on success, else an error message.
std::string scan(const std::string &db_path, const std::string &sql,
                 const std::vector<Param> &params,
                 const SvMap &keymap,
                 std::vector<Col> &cols) {
  sqlite3 *db = nullptr;
  sqlite3_stmt *stmt = nullptr;
  auto fail = [&](const std::string &msg) {
    std::string full = msg;
    if (db) {
      full += ": ";
      full += sqlite3_errmsg(db);
    }
    if (stmt) sqlite3_finalize(stmt);
    if (db) sqlite3_close(db);
    return full;
  };
  if (sqlite3_open_v2(db_path.c_str(), &db, SQLITE_OPEN_READONLY, nullptr) !=
      SQLITE_OK)
    return fail("cannot open database");
  if (sqlite3_prepare_v2(db, sql.c_str(), -1, &stmt, nullptr) != SQLITE_OK)
    return fail("prepare failed");
  for (size_t i = 0; i < params.size(); i++) {
    int rc;
    const int pi = static_cast<int>(i + 1);
    if (auto *s = std::get_if<std::string>(&params[i]))
      rc = sqlite3_bind_text(stmt, pi, s->c_str(),
                             static_cast<int>(s->size()), SQLITE_TRANSIENT);
    else if (auto *v = std::get_if<long long>(&params[i]))
      rc = sqlite3_bind_int64(stmt, pi, *v);
    else
      rc = sqlite3_bind_double(stmt, pi, std::get<double>(params[i]));
    if (rc != SQLITE_OK) return fail("bind failed");
  }
  const int ncol = static_cast<int>(cols.size());
  if (sqlite3_column_count(stmt) != ncol)
    return fail("spec length != selected column count");

  int rc;
  while ((rc = sqlite3_step(stmt)) == SQLITE_ROW) {
    for (int ci = 0; ci < ncol; ci++) {
      Col &c = cols[ci];
      const int ty = sqlite3_column_type(stmt, ci);
      switch (c.spec) {
        case 'p': {
          if (ty != SQLITE_TEXT) return fail("key column must be TEXT");
          const char *sp = reinterpret_cast<const char *>(
              sqlite3_column_text(stmt, ci));
          auto it = sv_find(keymap, std::string_view(
              sp, static_cast<size_t>(sqlite3_column_bytes(stmt, ci))));
          if (it == keymap.end()) return fail("key value not in key_values");
          c.i32.push_back(it->second);
          break;
        }
        case 't': {
          if (ty != SQLITE_TEXT)
            return fail("timestamp column must be TEXT "
                        "(caller should fall back)");
          int64_t ns;
          if (!parse_iso_ns(reinterpret_cast<const char *>(
                                sqlite3_column_text(stmt, ci)),
                            sqlite3_column_bytes(stmt, ci), &ns))
            return fail("unparseable timestamp (caller should fall back)");
          c.i64.push_back(ns);
          break;
        }
        case 'f': {
          // TEXT is rejected rather than coerced: sqlite3_column_double
          // turns junk text into 0.0 silently, while the pandas fallback
          // raises on malformed numerics — falling back keeps that
          // fail-loudly contract.
          if (ty == SQLITE_NULL)
            c.f64.push_back(Py_NAN);
          else if (ty == SQLITE_INTEGER || ty == SQLITE_FLOAT)
            c.f64.push_back(sqlite3_column_double(stmt, ci));
          else
            return fail("non-numeric cell in float column "
                        "(caller should fall back)");
          break;
        }
        case 's':
        case 'c': {  // same interned scan; they differ at materialize
          if (ty == SQLITE_NULL) {
            c.i32.push_back(-1);
            break;
          }
          const char *sp = reinterpret_cast<const char *>(
              sqlite3_column_text(stmt, ci));
          const std::string_view key(
              sp, static_cast<size_t>(sqlite3_column_bytes(stmt, ci)));
          auto it = sv_find(c.intern, key);
          if (it == c.intern.end()) {
            it = c.intern
                     .emplace(std::string(key),
                              static_cast<int32_t>(c.distinct.size()))
                     .first;
            c.distinct.push_back(it->first);
          }
          c.i32.push_back(it->second);
          break;
        }
        case 'u':
        case 'b': {  // same arena scan; 'b' materialises lazily
          if (ty == SQLITE_NULL) {
            c.text.push_back({0, -1});
            break;
          }
          const char *sp = reinterpret_cast<const char *>(
              sqlite3_column_text(stmt, ci));
          const int sl = sqlite3_column_bytes(stmt, ci);
          c.text.push_back({c.arena.size(), sl});
          c.arena.append(sp, sl);
          break;
        }
        case 'o': {
          if (ty == SQLITE_NULL) {
            c.tag.push_back(O_NULL);
            c.i64.push_back(0);
            c.f64.push_back(0.0);
            c.text.push_back({0, -1});
          } else if (ty == SQLITE_INTEGER) {
            c.tag.push_back(O_INT);
            c.i64.push_back(sqlite3_column_int64(stmt, ci));
            c.f64.push_back(0.0);
            c.text.push_back({0, -1});
          } else if (ty == SQLITE_FLOAT) {
            c.tag.push_back(O_FLOAT);
            c.i64.push_back(0);
            c.f64.push_back(sqlite3_column_double(stmt, ci));
            c.text.push_back({0, -1});
          } else {
            const char *sp = reinterpret_cast<const char *>(
                sqlite3_column_text(stmt, ci));
            const int sl = sqlite3_column_bytes(stmt, ci);
            c.tag.push_back(O_TEXT);
            c.i64.push_back(0);
            c.f64.push_back(0.0);
            c.text.push_back({c.arena.size(), sl});
            c.arena.append(sp, sl);
          }
          break;
        }
      }
    }
  }
  if (rc != SQLITE_DONE) return fail("step failed");
  sqlite3_finalize(stmt);
  sqlite3_close(db);
  return "";
}

// err/numeric_array/materialize live in columns.h (shared with the
// Postgres COPY-binary decoder).

// fetch_table(db_path, sql, params, spec, key_values) -> tuple of arrays
//
// spec: one char per selected column —
//   p  TEXT key -> int32 code via the key_values list (error if unseen)
//   t  TEXT ISO8601 -> int64 epoch-ns
//   f  numeric -> float64 (NULL -> NaN; TEXT rejected)
//   s  TEXT -> object array, values interned per column
//   c  TEXT -> (int32 codes, vocab list) — interned like 's' but with NO
//      per-row Python objects (codes match pd.factorize's first-appearance
//      order; -1 = NULL)
//   u  TEXT -> object array, no interning (high-cardinality, e.g. names)
//   b  TEXT -> (uint8 arena, int64 starts, int32 lens) — like 'u' but with
//      NO per-row Python objects; cells decode lazily on the Python side
//      (len -1 = NULL)
//   o  object array preserving sqlite's native type (int/float/text/None)
PyObject *fetch_table(PyObject *, PyObject *args) {
  const char *db_path_c, *sql_c, *spec_c;
  PyObject *params_o, *keys_o;
  if (!PyArg_ParseTuple(args, "ssOsO", &db_path_c, &sql_c, &params_o, &spec_c,
                        &keys_o))
    return nullptr;
  if (!PySequence_Check(params_o) || !PySequence_Check(keys_o))
    return err("params and key_values must be sequences");

  const std::string db_path(db_path_c), sql(sql_c), spec(spec_c);
  std::vector<Col> cols(spec.size());
  for (size_t i = 0; i < spec.size(); i++) {
    cols[i].spec = spec[i];
    if (!strchr("ptfscubo", spec[i])) return err("unknown spec char");
  }

  // Extract params / keys into pure C++ while still holding the GIL.
  std::vector<Param> params;
  {
    PyObject *fast = PySequence_Fast(params_o, "params");
    if (!fast) return nullptr;
    const Py_ssize_t np = PySequence_Fast_GET_SIZE(fast);
    for (Py_ssize_t i = 0; i < np; i++) {
      PyObject *p = PySequence_Fast_GET_ITEM(fast, i);
      if (PyUnicode_Check(p)) {
        Py_ssize_t sl;
        const char *sp = PyUnicode_AsUTF8AndSize(p, &sl);
        if (!sp) {
          Py_DECREF(fast);
          return nullptr;
        }
        params.emplace_back(std::string(sp, sl));
      } else if (PyLong_Check(p)) {
        params.emplace_back(static_cast<long long>(PyLong_AsLongLong(p)));
        if (PyErr_Occurred()) {
          Py_DECREF(fast);
          return nullptr;
        }
      } else if (PyFloat_Check(p)) {
        params.emplace_back(PyFloat_AsDouble(p));
      } else {
        Py_DECREF(fast);
        return err("unsupported parameter type");
      }
    }
    Py_DECREF(fast);
  }
  SvMap keymap;
  if (!build_keymap(keys_o, keymap)) return nullptr;

  // Phase 1: the whole sqlite scan runs without the GIL.
  std::string scan_err;
  Py_BEGIN_ALLOW_THREADS;
  scan_err = scan(db_path, sql, params, keymap, cols);
  Py_END_ALLOW_THREADS;
  if (!scan_err.empty()) return err(scan_err);

  // Phase 2: materialize numpy arrays under the GIL.
  PyObject *out = PyTuple_New(static_cast<Py_ssize_t>(cols.size()));
  if (!out) return nullptr;
  for (size_t i = 0; i < cols.size(); i++) {
    PyObject *arr = materialize(cols[i]);
    if (!arr) {
      Py_DECREF(out);
      return nullptr;
    }
    PyTuple_SET_ITEM(out, static_cast<Py_ssize_t>(i), arr);
  }
  return out;
}

PyMethodDef methods[] = {
    {"fetch_table", fetch_table, METH_VARARGS,
     "fetch_table(db_path, sql, params, spec, key_values) -> tuple of numpy "
     "arrays"},
    {nullptr, nullptr, 0, nullptr}};

struct PyModuleDef moddef = {PyModuleDef_HEAD_INIT, "_tse1m_decode",
                             "sqlite -> numpy bulk decoder", -1, methods,
                             nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__tse1m_decode(void) {
  import_array();
  return PyModule_Create(&moddef);
}
