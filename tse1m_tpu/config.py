"""Configuration.

Reads the reference-compatible ``program/envFile.ini`` (reference:
``program/envFile.ini:1-6`` — a single ``[POSTGRES]`` section) and extends it
with a ``[FRAMEWORK]`` section carrying the ``backend = {pandas, jax_tpu}``
switch required by the north star (BASELINE.json) plus engine selection.

Study-wide constants mirror ``program/__module/queries1.py:3-4`` in the
reference (``LIMIT_DATE``, ``RESULT_TYPE``); they live here as typed config
rather than module globals so every layer shares one source of truth.
"""

from __future__ import annotations

import os
from configparser import ConfigParser
from dataclasses import dataclass, field

# Canonical result enum.  The reference is internally inconsistent: its
# analyzer emits {Success, Error, Unknown} while every query filters
# ('Finish','Halfway') (SURVEY.md §2.2).  We standardise on the DB-side
# vocabulary and map legacy analyzer values at ingest (db/ingest.py).
RESULT_OK = ("Finish", "Halfway")
BUILD_TYPES = ("Fuzzing", "Coverage", "Introspector", "Error")
FIXED_STATUSES = ("Fixed", "Fixed (Verified)")

DEFAULT_LIMIT_DATE = "2025-01-08"
DEFAULT_INI = "program/envFile.ini"


@dataclass
class PostgresConfig:
    database: str = "replication_db"
    user: str = "replication_user"
    password: str = "replication_pass"
    host: str = "db"
    port: int = 5432


@dataclass
class Config:
    # Analysis backend: "pandas" (host) or "jax_tpu" (device arrays + mesh).
    backend: str = "pandas"
    # Storage engine: "sqlite" (embedded, default in this environment) or
    # "postgres" (requires psycopg2; reference's engine).
    engine: str = "sqlite"
    sqlite_path: str = "data/database/tse1m.sqlite"
    postgres: PostgresConfig = field(default_factory=PostgresConfig)
    # Study-wide constants (queries1.py:3-4).
    limit_date: str = DEFAULT_LIMIT_DATE
    # Eligibility predicate threshold (rq1_detection_rate.py:144-151).
    min_coverage_days: int = 365
    # Statistical-significance filter (rq1_detection_rate.py:233).
    min_projects_per_iteration: int = 100
    # Artifact root (reference writes under data/result_data/).
    result_dir: str = "data/result_data"
    data_dir: str = "data"
    # C8's corpus-analysis output, consumed by RQ4a/RQ4b (rq4a_bug.py:34).
    corpus_csv: str = "data/processed_data/csv/project_corpus_analysis.csv"
    # Pre/post window half-width N and the G3/G4 boundary in days
    # (rq4a_bug.py:43-44).
    analysis_iterations: int = 7
    days_threshold: int = 7
    # Test-mode subset switch (rq1_detection_rate.py:20,155-158,233).
    test_mode: bool = False
    # -- resilience (resilience/) -----------------------------------------
    # Path to a FaultPlan JSON.  Honored two ways: TSE1M_FAULT_PLAN is
    # read directly by resilience/faults.py (so config-less seats like
    # subprocess checkpointers see the same plan), and an INI-configured
    # path is installed at CLI startup (cli._activate_config_fault_plan),
    # which also exports the env var for child processes.
    fault_plan: str | None = None
    # Shared retry engine knobs for DB statements/connects.
    db_retry_attempts: int = 4
    db_retry_base_delay: float = 0.1
    db_retry_max_delay: float = 5.0
    # Per-statement timeout: Postgres `SET statement_timeout`, sqlite
    # busy_timeout.  0 = engine default (off).
    db_statement_timeout_ms: int = 0
    # -- observability / dispatch (observability/, utils/compat.py) --------
    # Persistent XLA compilation-cache directory (None = off).  Repeat
    # runs skip kernel recompiles — each fresh compile costs several
    # dispatch round-trips (129 ms each on the measured tunneled-PJRT
    # link).  Activated by cli startup and bench.py via
    # utils.compat.enable_persistent_compilation_cache; env override
    # TSE1M_XLA_CACHE_DIR.
    xla_cache_dir: str | None = None
    # Persisted auto-router calibration (backend/auto.py): measured
    # per-RQ walls saved as JSON and reloaded by the next run on this
    # machine, so routing converges across processes instead of
    # re-learning per run.  None = in-memory only; env TSE1M_ROUTER_CAL.
    router_cal_path: str | None = None
    # Persistent content-addressed signature store for the cluster warm
    # path (cluster/store.py).  None = cold runs; env TSE1M_SIG_STORE;
    # CLI `cluster --sig-store`.
    sig_store: str | None = None

    @property
    def result_ok(self) -> tuple[str, ...]:
        return RESULT_OK


def load_config(ini_path: str | None = None) -> Config:
    """Load config from envFile.ini, tolerating the reference's bare-minimum
    ini (POSTGRES only) and environment overrides.

    Env overrides: TSE1M_BACKEND, TSE1M_ENGINE, TSE1M_SQLITE_PATH,
    TSE1M_TEST_MODE.
    """
    cfg = Config()
    path = ini_path or os.environ.get("TSE1M_ENVFILE", DEFAULT_INI)
    parser = ConfigParser()
    if path and os.path.exists(path):
        parser.read(path)
        if parser.has_section("POSTGRES"):
            pg = parser["POSTGRES"]
            cfg.postgres = PostgresConfig(
                database=pg.get("POSTGRES_DB", cfg.postgres.database),
                user=pg.get("POSTGRES_USER", cfg.postgres.user),
                password=pg.get("POSTGRES_PASSWORD", cfg.postgres.password),
                host=pg.get("POSTGRES_IP", cfg.postgres.host),
                port=pg.getint("POSTGRES_PORT", cfg.postgres.port),
            )
        if parser.has_section("FRAMEWORK"):
            fw = parser["FRAMEWORK"]
            cfg.backend = fw.get("backend", cfg.backend)
            cfg.engine = fw.get("engine", cfg.engine)
            cfg.sqlite_path = fw.get("sqlite_path", cfg.sqlite_path)
            cfg.limit_date = fw.get("limit_date", cfg.limit_date)
            cfg.result_dir = fw.get("result_dir", cfg.result_dir)
            cfg.corpus_csv = fw.get("corpus_csv", cfg.corpus_csv)
            cfg.test_mode = fw.getboolean("test_mode", cfg.test_mode)
            cfg.fault_plan = fw.get("fault_plan", cfg.fault_plan)
            cfg.db_retry_attempts = fw.getint("db_retry_attempts",
                                              cfg.db_retry_attempts)
            cfg.db_retry_base_delay = fw.getfloat("db_retry_base_delay",
                                                  cfg.db_retry_base_delay)
            cfg.db_retry_max_delay = fw.getfloat("db_retry_max_delay",
                                                 cfg.db_retry_max_delay)
            cfg.db_statement_timeout_ms = fw.getint(
                "db_statement_timeout_ms", cfg.db_statement_timeout_ms)
            cfg.xla_cache_dir = fw.get("xla_cache_dir", cfg.xla_cache_dir)
            cfg.router_cal_path = fw.get("router_cal_path",
                                         cfg.router_cal_path)
            cfg.sig_store = fw.get("sig_store", cfg.sig_store)

    cfg.backend = os.environ.get("TSE1M_BACKEND", cfg.backend)
    cfg.engine = os.environ.get("TSE1M_ENGINE", cfg.engine)
    cfg.sqlite_path = os.environ.get("TSE1M_SQLITE_PATH", cfg.sqlite_path)
    cfg.corpus_csv = os.environ.get("TSE1M_CORPUS_CSV", cfg.corpus_csv)
    cfg.result_dir = os.environ.get("TSE1M_RESULT_DIR", cfg.result_dir)
    if "TSE1M_TEST_MODE" in os.environ:
        cfg.test_mode = os.environ["TSE1M_TEST_MODE"].lower() in ("1", "true", "yes")
    cfg.fault_plan = os.environ.get("TSE1M_FAULT_PLAN", cfg.fault_plan)
    cfg.xla_cache_dir = os.environ.get("TSE1M_XLA_CACHE_DIR",
                                       cfg.xla_cache_dir)
    cfg.router_cal_path = os.environ.get("TSE1M_ROUTER_CAL",
                                         cfg.router_cal_path)
    cfg.sig_store = os.environ.get("TSE1M_SIG_STORE", cfg.sig_store)
    if "TSE1M_DB_RETRY_ATTEMPTS" in os.environ:
        cfg.db_retry_attempts = int(os.environ["TSE1M_DB_RETRY_ATTEMPTS"])
    if "TSE1M_DB_STATEMENT_TIMEOUT_MS" in os.environ:
        cfg.db_statement_timeout_ms = int(
            os.environ["TSE1M_DB_STATEMENT_TIMEOUT_MS"])
    if cfg.backend not in ("pandas", "jax_tpu", "auto"):
        raise ValueError(f"unknown backend {cfg.backend!r}; expected "
                         "'pandas', 'jax_tpu' or 'auto'")
    if cfg.engine not in ("sqlite", "postgres"):
        raise ValueError(f"unknown engine {cfg.engine!r}; expected 'sqlite' or 'postgres'")
    return cfg
