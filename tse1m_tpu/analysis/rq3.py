"""RQ3 — coverage change when bugs are detected vs not.

Re-implementation of ``program/research_questions/
rq3_diff_coverage_at_detection.py`` over backend primitives.  Artifact
parity (all under ``rq3/``):

- ``detected_coverage_changes.csv`` / ``non_detected_coverage_changes.csv``
  — header ``CoverageChangePercent,CoveredLinesChange,TotalLinesChange``
  (rq3:307-318; golden detected file has 5,465 rows).
- ``coverage_diff_boxplot.pdf`` — symlog side-by-side boxplot (rq3:161-179).
- ``coverage_diff_histograms.pdf`` — shared-bin histograms (rq3:181-198).
- ``detected.pdf`` / ``non_detected.pdf`` — single-group symlog boxplots
  (rq3:70-152,357-358).

Statistical tests stay host-side scipy on the already-reduced delta vectors
(SURVEY.md §7.2 step 6): Anderson-Darling normality per group (rq3:329-339),
Levene variance equality (rq3:344), Brunner-Munzel (rq3:349).
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .common import StudyContext, limit_date_ns
from ..config import Config
from ..utils.logging import get_logger
from ..utils.atomic import atomic_write
from ..utils.manifest import RunManifest
from ..utils.timing import PhaseTimer

log = get_logger("rq3")


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def summary_statistics(data: np.ndarray) -> dict:
    """The reference's summary table block (rq3:25-66)."""
    data = np.asarray(data, dtype=np.float64)
    n = data.size
    if n == 0:
        return {"count": 0}
    return {
        "count": int(n),
        "positive_pct": float((data > 0).sum() / n * 100),
        "zero_pct": float((data == 0).sum() / n * 100),
        "negative_pct": float((data < 0).sum() / n * 100),
        "mean": float(data.mean()),
        "median": float(np.median(data)),
        "std": float(data.std()),
        "min": float(data.min()),
        "q1": float(np.percentile(data, 25)),
        "q3": float(np.percentile(data, 75)),
        "max": float(data.max()),
    }


def print_summary_statistics(data: np.ndarray, name: str) -> dict:
    s = summary_statistics(data)
    print(f"\n--- Summary Statistics for '{name}' Group ---")
    if not s["count"]:
        print("No data available.")
        return s
    rows = [
        ("Count", f"{s['count']}"),
        ("Positive Change Rate (%)", f"{s['positive_pct']:.2f}"),
        ("Zero Change Rate (%)", f"{s['zero_pct']:.2f}"),
        ("Negative Change Rate (%)", f"{s['negative_pct']:.2f}"),
        ("Mean", f"{s['mean']:.4f}"),
        ("Median", f"{s['median']:.4f}"),
        ("Std. Deviation", f"{s['std']:.4f}"),
        ("Min", f"{s['min']:.4f}"),
        ("Q1", f"{s['q1']:.4f}"),
        ("Q3", f"{s['q3']:.4f}"),
        ("Max", f"{s['max']:.4f}"),
    ]
    print("+--------------------------+----------------------+")
    print("| Metric                   | Value                |")
    print("+--------------------------+----------------------+")
    for k, v in rows:
        print(f"| {k:<24} | {v:<20} |")
    print("+--------------------------+----------------------+")
    return s


def statistical_tests(detected: np.ndarray, non_detected: np.ndarray) -> dict:
    """Anderson-Darling per group, Levene, Brunner-Munzel (rq3:329-352)."""
    import warnings

    from scipy import stats

    out: dict = {}
    for name, data in (("detected", detected), ("non_detected", non_detected)):
        if data.size >= 3:
            with warnings.catch_warnings():
                # scipy >= 1.17 deprecates the critical-value result shape;
                # we keep it because the reference prints critical values
                # (rq3:331-333).
                warnings.simplefilter("ignore", FutureWarning)
                r = stats.anderson(data, dist="norm")
            out[f"anderson_{name}"] = {
                "statistic": float(r.statistic),
                "critical_values": [float(v) for v in r.critical_values],
                "significance_levels": [float(v) for v in r.significance_level],
            }
    if detected.size >= 2 and non_detected.size >= 2:
        stat, p = stats.levene(detected, non_detected)
        out["levene"] = {"statistic": float(stat), "p_value": float(p)}
        stat, p = stats.brunnermunzel(detected, non_detected)
        out["brunner_munzel"] = {"statistic": float(stat), "p_value": float(p)}
    return out


def save_changes_csv(path: str, pct, cov, tot) -> None:
    with atomic_write(path, newline="") as f:
        w = csv.writer(f)
        w.writerow(["CoverageChangePercent", "CoveredLinesChange",
                    "TotalLinesChange"])
        for row in zip(pct, cov, tot):
            w.writerow([row[0], _int_if_whole(row[1]), _int_if_whole(row[2])])


def _int_if_whole(x: float):
    # covered/total line deltas are integral counts; the reference writes
    # them as ints coming straight from the DB (rq3:299-300).
    return int(x) if float(x).is_integer() else x


def create_comparison_plots(out_dir: str, detected, non_detected) -> list[str]:
    """Side-by-side symlog boxplot + shared-bin histograms (rq3:157-198)."""
    plt = _plt()
    paths = []

    fig = plt.figure(figsize=(4, 3))
    box = plt.boxplot([detected, non_detected], patch_artist=True,
                      tick_labels=["Detected", "Not Detected"], showfliers=True)
    for patch, color in zip(box["boxes"], ["#A3BCE2", "#E2A3A3"]):
        patch.set_facecolor(color)
    plt.ylabel("Coverage Difference (%)")
    plt.yscale("symlog", linthresh=0.01)
    plt.grid(axis="y", linestyle="--", alpha=0.6)
    plt.tight_layout()
    p = os.path.join(out_dir, "coverage_diff_boxplot.pdf")
    plt.savefig(p)
    plt.close(fig)
    paths.append(p)

    both = np.concatenate([detected, non_detected])
    bins = np.linspace(both.min(), both.max(), 50) if both.size else 10
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(8, 3), sharey=True,
                                   sharex=True)
    ax1.hist(detected, bins=bins, color="skyblue", edgecolor="black")
    ax1.set_title("Detected")
    ax1.set_xlabel("Coverage Difference (%)")
    ax1.set_ylabel("Frequency")
    ax2.hist(non_detected, bins=bins, color="salmon", edgecolor="black")
    ax2.set_title("Not Detected")
    ax2.set_xlabel("Coverage Difference (%)")
    plt.tight_layout()
    p = os.path.join(out_dir, "coverage_diff_histograms.pdf")
    plt.savefig(p)
    plt.close(fig)
    paths.append(p)
    return paths


def create_boxplot(path: str, values) -> None:
    """Single-group symlog boxplot with mean marker (rq3:70-152)."""
    from matplotlib.ticker import FuncFormatter

    plt = _plt()
    edge = "#444444"
    fig = plt.figure(figsize=(2.0, 2.5))
    box = plt.boxplot(values, patch_artist=True, widths=0.5, showfliers=True)
    for patch in box["boxes"]:
        patch.set_facecolor("#e3eefa")
        patch.set_linewidth(0.7)
        patch.set_edgecolor(edge)
    plt.setp(box["medians"], color="#FF0000", linewidth=0.3)
    for whisker in box["whiskers"]:
        whisker.set_linewidth(0.7)
        whisker.set_color(edge)
    for cap in box["caps"]:
        cap.set_linewidth(0.7)
        cap.set_color(edge)
    for flier in box["fliers"]:
        flier.set(marker="o", alpha=0.5, markersize=2, markeredgewidth=0.2,
                  markeredgecolor="#c83c3c")
    plt.scatter(1, np.mean(values), color="#2f6ba3", marker="^", s=15,
                zorder=3, label="Mean")
    plt.ylabel("Coverage Difference")
    plt.xticks([])
    plt.yscale("symlog", linthresh=0.01)
    plt.ylim(-100, 100)
    ticks = [-100, -10, -1, -0.1, -0.01, 0, 0.01, 0.1, 1, 10, 100]
    plt.yticks(ticks)

    def fmt(x, pos):
        if x == 0:
            return "0"
        e = int(np.log10(abs(x)))
        return f"$-10^{{{e}}}$" if x < 0 else f"$10^{{{e}}}$"

    plt.gca().get_yaxis().set_major_formatter(FuncFormatter(fmt))
    plt.tight_layout(pad=0)
    plt.savefig(path, bbox_inches="tight")
    plt.close(fig)


def run_rq3(cfg: Config | None = None, db=None) -> dict:
    timer = PhaseTimer()
    print("--- RQ3 Analysis Started ---")
    with timer.phase("extract"):
        ctx = StudyContext.open(cfg, db=db, announce=False)
    manifest = RunManifest("rq3", ctx.backend.name)
    n_issues = len(ctx.arrays.issues)
    print(f"Fetched {n_issues} fixed issues from target projects.")

    with timer.phase("rq3_kernel"):
        result = ctx.backend.rq3_coverage_at_detection(
            ctx.arrays, limit_date_ns(ctx.cfg))
    detected = result.det_diff_percent
    non_detected = result.nondet_diff_percent
    print(f"\nFound {detected.size} instances of coverage change on bug "
          "detection.")

    out_dir = ctx.out_dir("rq3")
    with timer.phase("artifacts"):
        det_path = os.path.join(out_dir, "detected_coverage_changes.csv")
        save_changes_csv(det_path, detected, result.det_diff_covered,
                         result.det_diff_total)
        manifest.add_artifact(det_path)
        nondet_path = os.path.join(out_dir, "non_detected_coverage_changes.csv")
        save_changes_csv(nondet_path, non_detected,
                         result.nondet_diff_covered, result.nondet_diff_total)
        manifest.add_artifact(nondet_path)

        stats_summary = {
            "detected": print_summary_statistics(detected, "Detected"),
            "non_detected": print_summary_statistics(non_detected,
                                                     "Not Detected"),
            "detected_total": print_summary_statistics(
                result.det_diff_total, "Detected Total"),
        }
        tests = statistical_tests(detected, non_detected)
        for name in ("detected", "non_detected"):
            t = tests.get(f"anderson_{name}")
            if t:
                print("Detected" if name == "detected" else "Not Detected")
                print("Test statistic (A²):", t["statistic"])
        if "levene" in tests:
            print(f"Levene's test statistic: {tests['levene']['statistic']:.4f}")
            print(f"P-value: {tests['levene']['p_value']:.4f}")
        if "brunner_munzel" in tests:
            print(f"Brunner-Munzel W statistic: "
                  f"{tests['brunner_munzel']['statistic']:.4f}")
            print(f"P-value: {tests['brunner_munzel']['p_value']:.4f}")

        if detected.size and non_detected.size:
            for p in create_comparison_plots(out_dir, detected, non_detected):
                manifest.add_artifact(p)
            for name, vals in (("detected.pdf", detected),
                               ("non_detected.pdf", non_detected)):
                p = os.path.join(out_dir, name)
                create_boxplot(p, vals)
                manifest.add_artifact(p)

    manifest.record(
        n_issues=n_issues,
        n_detected=int(detected.size),
        n_non_detected=int(non_detected.size),
        summary=stats_summary,
        tests=tests,
    )
    manifest.record_backend(ctx.backend)
    manifest.save(out_dir, timer.as_dict())
    print("\n--- RQ3 Analysis Finished ---")
    return {"result": result, "summary": stats_summary, "tests": tests,
            "detected_csv": det_path}


def main() -> None:
    run_rq3()


if __name__ == "__main__":
    main()
