"""Seed-corpus grouping shared by RQ4a and RQ4b.

Mirrors the reference's categorisation of eligible projects by
seed-corpus-introduction timing (rq4a_bug.py:82-121; identical logic in
rq4b_coverage.py:164-230) from C8's ``project_corpus_analysis.csv``:

- G1 "No Corpus":      time_elapsed_seconds is NaN, plus every eligible
                       project absent from the CSV (rq4a:110-113).
- G2 "Initial Corpus": time_elapsed_seconds == 0.
- G3 "1-7 Days":       0 < s < days_threshold * 86400.
- G4 ">= 7 Days":      s >= days_threshold * 86400 (the pre/post cohort;
                       carries corpus_commit_time).

The G4 pre/post detection windows (rq4a:348-412) are computed here on host:
they touch O(|G4| x N) scalars — far below device-dispatch granularity —
and both backends share this exact code path so parity is structural.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np
import pandas as pd

from ..data.columnar import StudyArrays
from ..utils.logging import get_logger

log = get_logger("corpus")

GROUP_LABELS = {
    "group1": "Group A (No Corpus)",
    "group2": "Group B (Initial Corpus)",
    "group3": "Group D (1-5 Day Corpus)",
    "group4": "Group C (>5 Day Corpus)",
}


@dataclass
class CorpusGroups:
    groups: dict[str, set]          # group key -> project names
    corpus_time_ns: dict[str, int]  # project -> corpus_commit_time (ns); every
                                    # non-null-elapsed project (G2/G3/G4) that
                                    # has a parseable commit time (rq4b:216)

    def indices(self, key: str, project_index: dict[str, int]) -> np.ndarray:
        return np.array(sorted(project_index[p] for p in self.groups[key]
                               if p in project_index), dtype=np.int64)


def load_corpus_groups(csv_path: str, eligible: set,
                       days_threshold: int = 7) -> CorpusGroups:
    """rq4a_bug.py:82-121 — missing CSV file is an error; missing rows
    default to G1."""
    if not os.path.exists(csv_path):
        # The reference dies with a raw pandas traceback here; fail with
        # the fix instead (rq4a/rq4b consume C8's output, rq4a_bug.py:34).
        raise SystemExit(
            f"corpus analysis CSV not found at {csv_path}. Generate it "
            "first: `python -m tse1m_tpu.cli synth` (synthetic study) or "
            "`python -m tse1m_tpu.cli collect corpus` (real data); or "
            "point corpus_csv/TSE1M_CORPUS_CSV at an existing file.")
    df = pd.read_csv(csv_path)
    df["corpus_commit_time"] = pd.to_datetime(
        df["corpus_commit_time"], errors="coerce", utc=True, format="mixed")
    df = df[df["project_name"].isin(eligible)].copy()
    elapsed = pd.to_numeric(df["time_elapsed_seconds"], errors="coerce")
    bound = days_threshold * 86400
    null_g1 = elapsed.isna()
    groups = {
        "group1": set(df[null_g1]["project_name"]),
        "group2": set(df[(elapsed == 0) & ~null_g1]["project_name"]),
        "group3": set(df[(elapsed > 0) & (elapsed < bound)
                         & ~null_g1]["project_name"]),
        "group4": set(df[(elapsed >= bound) & ~null_g1]["project_name"]),
    }
    groups["group1"].update(eligible - set(df["project_name"]))

    with_corpus = df[~null_g1]
    corpus_time_ns = {}
    for name, t in zip(with_corpus["project_name"],
                       with_corpus["corpus_commit_time"]):
        if pd.notna(t):
            corpus_time_ns[name] = int(t.tz_convert(None).value
                                       if t.tzinfo else t.value)
    log.info("Projects categorized: G1=%d, G2=%d, G3=%d, G4=%d",
             *(len(groups[k]) for k in ("group1", "group2", "group3",
                                        "group4")))
    return CorpusGroups(groups=groups, corpus_time_ns=corpus_time_ns)


@dataclass
class G4PrePost:
    """Fixed-N pre/post windows around corpus introduction (rq4a:348-412).

    detect: [n_kept, 2N] bool, columns ordered step -N..-1, 1..N; kept
    projects pass the completeness filter (rq4a:374).  intro_iteration maps
    every G4 project (with builds data) to the iteration at which its
    corpus arrived (rq4a:246-299; 0 when the project has no builds)."""

    steps: np.ndarray               # [-N..-1, 1..N]
    detect: np.ndarray              # [n_kept, 2N] bool
    kept_projects: list[str]
    missing_pre: set
    intro_iteration: dict[str, int]

    @property
    def pre_any(self) -> np.ndarray:
        return self.detect[:, : self.detect.shape[1] // 2].any(axis=1)

    @property
    def post_any(self) -> np.ndarray:
        return self.detect[:, self.detect.shape[1] // 2:].any(axis=1)

    def transition_counts(self) -> dict:
        pre, post = self.pre_any, self.post_any
        return {
            "no_detection": int((~pre & ~post).sum()),
            "pre_only": int((pre & ~post).sum()),
            "pre_and_post": int((pre & post).sum()),
            "post_only": int((~pre & post).sum()),
        }

    def step_rates(self) -> np.ndarray:
        """Detection rate (%) per step column."""
        if self.detect.size == 0:
            return np.zeros(self.steps.size)
        return self.detect.mean(axis=0) * 100.0


def g4_prepost(arrays: StudyArrays, limit_date_ns: int,
               groups: CorpusGroups, n_windows: int) -> G4PrePost:
    N = n_windows
    pidx = arrays.project_index()
    fuzz_t = arrays.fuzz.columns["time_ns"]
    issue_t = arrays.issues.columns["time_ns"]

    steps = np.array([s for s in range(-N, N + 1) if s != 0], dtype=np.int64)
    rows, kept, missing, intro = [], [], set(), {}
    for name in sorted(groups.groups["group4"]):
        t_corpus = groups.corpus_time_ns.get(name)
        if t_corpus is None or name not in pidx:
            continue
        p = pidx[name]
        flo, fhi = arrays.fuzz.offsets[p], arrays.fuzz.offsets[p + 1]
        btimes = fuzz_t[flo:fhi][fuzz_t[flo:fhi] < limit_date_ns]
        # Introduction iteration = #builds strictly before corpus arrival
        # (rq4a:269); 0 when the project has no builds (rq4a:265-267).
        pos = int(np.searchsorted(btimes, t_corpus, side="left"))
        intro[name] = pos
        if btimes.size == 0 or pos == 0:
            continue  # no pre-introduction build (rq4a:365-366)
        idx_pre_last = pos - 1
        if (idx_pre_last - (N - 1) < 0) or (idx_pre_last + N >= btimes.size - 1):
            missing.add(name)  # incomplete N-window (rq4a:374-376)
            continue
        ilo, ihi = arrays.issues.offsets[p], arrays.issues.offsets[p + 1]
        itimes = issue_t[ilo:ihi]
        row = np.zeros(2 * N, dtype=bool)
        for j, s in enumerate(steps):
            idx = idx_pre_last - (-s - 1) if s < 0 else idx_pre_last + s
            t_start, t_end = btimes[idx], btimes[idx + 1]
            # any issue with t_start <= rts < t_end (rq4a:392,403)
            row[j] = (np.searchsorted(itimes, t_end, side="left")
                      - np.searchsorted(itimes, t_start, side="left")) > 0
        rows.append(row)
        kept.append(name)

    detect = (np.array(rows, dtype=bool) if rows
              else np.zeros((0, 2 * N), dtype=bool))
    return G4PrePost(steps=steps, detect=detect, kept_projects=kept,
                     missing_pre=missing, intro_iteration=intro)
