"""RQ1 — vulnerability detection rate over fuzzing iterations.

Re-implementation of ``program/research_questions/rq1_detection_rate.py``
over backend primitives.  Artifact parity:

- ``rq1_detection_rate_stats.csv`` — header
  ``Iteration,Total_Projects,Detected_Projects_Count`` (rq1:330-335;
  golden file first data row ``1,878,297``).
- ``rq1_raw_issues_for_analysis.csv`` — generic ``issue_i`` header over the
  SAME_DATE_BUILD_ISSUE row shape (rq1:23-43; queries1.py:45-57).
- ``rq1_detection_rate.pdf`` — the Figure-6 dual-axis plot (rq1:46-98).
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .common import StudyContext, fmt_ts_ns, limit_date_ns
from ..config import Config
from ..db import queries
from ..db.ingest import parse_array, pg_array_literal
from ..utils.logging import get_logger
from ..utils.atomic import atomic_write
from ..utils.manifest import RunManifest
from ..utils.timing import PhaseTimer

log = get_logger("rq1")


def save_raw_issues_csv(ctx: StudyContext, result, path: str) -> int:
    """Linked issues with their matched build, ordered by (project, rts) —
    the reference's raw-issues artifact (rq1:23-43)."""
    issues = ctx.arrays.issues
    fuzz = ctx.arrays.fuzz
    rows = []
    for p in range(ctx.arrays.n_projects):
        lo, hi = issues.offsets[p], issues.offsets[p + 1]
        for j in range(lo, hi):
            bi = result.link_idx[j]
            if bi < 0:
                continue
            rows.append([
                issues.columns["number"][j],
                ctx.projects[p],
                fmt_ts_ns(int(issues.columns["time_ns"][j])),
                fmt_ts_ns(int(fuzz.columns["time_ns"][bi])),
                "Fuzzing",
                fuzz.columns["result"][bi],
                fuzz.columns["name"][bi],
                pg_array_literal(parse_array(fuzz.columns["modules_raw"][bi])),
                pg_array_literal(parse_array(fuzz.columns["revisions_raw"][bi])),
            ])
    if not rows:
        log.warning("no linked issues; skipping %s", path)
        return 0
    header = [f"issue_{i}" for i in range(len(rows[0]))]
    with atomic_write(path, newline="") as f:
        w = csv.writer(f)
        w.writerow(header)
        w.writerows(rows)
    return len(rows)


def save_stats_csv(result, path: str) -> None:
    with atomic_write(path, newline="") as f:
        w = csv.writer(f)
        w.writerow(["Iteration", "Total_Projects", "Detected_Projects_Count"])
        for it, tot, det in zip(result.iterations, result.total_projects,
                                result.detected_counts):
            w.writerow([int(it), int(tot), int(det)])


def create_detection_rate_graph(result, path: str, file_format: str = "pdf") -> None:
    """Figure 6: detection-rate line on the primary axis over a
    project-population bar chart on the secondary axis (rq1:46-98)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rates = result.detection_rates
    fig, ax1 = plt.subplots(figsize=(5, 3))
    ax2 = ax1.twinx()
    ax1.set_zorder(ax2.get_zorder() + 1)
    ax1.patch.set_visible(False)
    ax1.plot(range(len(rates)), rates, color="b", marker="o", markersize=1.0,
             linewidth=1)
    ax1.set_ylabel("Percentage of Projects Detecting Bugs", y=0.45)
    ax1.set_xlabel("Fuzzing Session")
    ax2.bar(range(len(result.total_projects)), result.total_projects,
            color="#88c778", alpha=0.6)
    ax2.set_ylabel("Number of Projects")
    plt.tight_layout(pad=0.1)
    plt.savefig(path, format=file_format)
    plt.close(fig)


def late_stage_stats(result, threshold_pct: float = 5.0) -> dict:
    """Late-stage IQR/median/zero-rate block (rq1:241-268): stats over rates
    from the first iteration whose rate drops below the threshold."""
    rates = result.detection_rates
    below = np.flatnonzero(rates < threshold_pct)
    if len(below) == 0 or len(rates) == 0:
        return {}
    start = below[0]
    late = rates[start:]
    return {
        "first_below_iteration": int(result.iterations[start]),
        "min": float(late.min()),
        "max": float(late.max()),
        "p25": float(np.percentile(late, 25)),
        "p75": float(np.percentile(late, 75)),
        "median": float(np.median(late)),
        "mean": float(late.mean()),
        "zero_fraction": float((late == 0).mean()),
    }


def run_rq1(cfg: Config | None = None, db=None) -> dict:
    timer = PhaseTimer()
    with timer.phase("extract"):
        ctx = StudyContext.open(cfg, db=db)
    manifest = RunManifest("rq1", ctx.backend.name)

    # Unlinked-issue diagnostic (reference rq1:161-163): fixed issues of
    # eligible projects with no successful pre-cutoff fuzzing build before
    # their report time.
    sql, params = queries.issues_without_matching_build(
        ctx.projects, ctx.cfg.limit_date)
    n_unmatched = ctx.db.count(sql, params)
    print(f"Found {n_unmatched:,} issues without matching build.")

    with timer.phase("detect_kernel"):
        result = ctx.backend.rq1_detection(
            ctx.arrays, limit_date_ns(ctx.cfg), ctx.min_projects)

    n_issues = len(ctx.arrays.issues)
    n_linked = int(result.linked.sum())
    total_builds = int(len(ctx.arrays.fuzz))
    print(f"{ctx.arrays.n_projects:,} projects have {total_builds:,} "
          f"fuzzing builds. (in abstract)")
    if n_issues:
        print(f"linked {n_linked:,}({n_linked / n_issues * 100:.2f}%) issues "
              f"to buildlog data. {n_linked}/{n_issues}")
    print(f"Retained {len(result.iterations):,} iterations for the final analysis.")

    out_dir = ctx.out_dir("rq1")
    with timer.phase("artifacts"):
        stats_path = os.path.join(out_dir, "rq1_detection_rate_stats.csv")
        save_stats_csv(result, stats_path)
        manifest.add_artifact(stats_path)

        raw_path = os.path.join(out_dir, "rq1_raw_issues_for_analysis.csv")
        n_raw = save_raw_issues_csv(ctx, result, raw_path)
        if n_raw:
            manifest.add_artifact(raw_path)

        pdf_path = os.path.join(out_dir, "rq1_detection_rate.pdf")
        create_detection_rate_graph(result, pdf_path)
        manifest.add_artifact(pdf_path)

    late = late_stage_stats(result)
    if late:
        print(f"Late-stage (from iteration {late['first_below_iteration']}): "
              f"median {late['median']:.2f}%, IQR {late['p25']:.2f}-{late['p75']:.2f}%, "
              f"mean {late['mean']:.2f}%, zero {late['zero_fraction'] * 100:.2f}%")

    manifest.record(
        n_projects=ctx.arrays.n_projects,
        n_fuzz_builds=total_builds,
        n_issues=n_issues,
        n_linked=n_linked,
        n_unmatched=n_unmatched,
        n_iterations=len(result.iterations),
        late_stage=late,
    )
    manifest.record_backend(ctx.backend)
    manifest.save(out_dir, timer.as_dict())
    return {"result": result, "late": late, "stats_csv": stats_path}


def main() -> None:
    run_rq1()


if __name__ == "__main__":
    main()
