"""RQ4a — seed-corpus effect on bug detection.

Re-implementation of ``program/research_questions/rq4a_bug.py`` over backend
primitives.  Artifact parity (all under ``rq4/bug/``):

- ``rq4_g1_g2_detection_trend.csv`` — header
  ``Iteration,G1_Total_Projects,G1_Detected_Count,G1_Detection_Rate_pct,
  G2_Total_Projects,G2_Detected_Count,G2_Detection_Rate_pct``
  (rq4a:198-205; golden file has 1,600 rows).
- ``rq4_gc_introduction_iteration.csv`` — ``Project,Introduction_Iteration``
  ascending (rq4a:272-291; golden file has 86 rows).
- ``rq4_g1_g2_detection_trend.pdf`` — A-vs-B trend lines, x-range limited to
  the last iteration where both groups keep >= 100 projects (rq4a:749-784).
- ``rq4_gc_detection_trend.pdf`` — G4 pre/post step rates with the
  transition-count box (rq4a:513-568).
- ``rq4_gc_bug_detection_venn.pdf`` — pre/post detection Venn
  (rq4a:843-879; falls back to raw matplotlib circles when matplotlib-venn
  is absent, mirroring the reference's optional-import gate rq4a:13-17).

The reference's INCLUDE_MISSING_PRE_IN_G2 switch (rq4a:46, False) and the
dead ``analyze_g2_vs_g1_superiority`` / difference-graph paths
(rq4a:605-631,785) are not replicated; superiority is reported inline as the
live code does (rq4a:697-701).
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .common import StudyContext, limit_date_ns
from .corpus import GROUP_LABELS, g4_prepost, load_corpus_groups
from ..config import Config
from ..utils.logging import get_logger
from ..utils.atomic import atomic_write
from ..utils.manifest import RunManifest
from ..utils.timing import PhaseTimer

log = get_logger("rq4a")


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def save_trend_csv(result, path: str) -> None:
    g1r, g2r = result.rates("g1"), result.rates("g2")
    with atomic_write(path, newline="") as f:
        w = csv.writer(f)
        w.writerow(["Iteration", "G1_Total_Projects", "G1_Detected_Count",
                    "G1_Detection_Rate_pct", "G2_Total_Projects",
                    "G2_Detected_Count", "G2_Detection_Rate_pct"])
        for i in range(result.iterations.size):
            w.writerow([int(result.iterations[i]), int(result.g1_total[i]),
                        int(result.g1_detected[i]), g1r[i],
                        int(result.g2_total[i]), int(result.g2_detected[i]),
                        g2r[i]])


def save_intro_csv(prepost, path: str) -> int:
    rows = sorted(prepost.intro_iteration.items(), key=lambda kv: kv[1])
    with atomic_write(path, newline="") as f:
        w = csv.writer(f)
        w.writerow(["Project", "Introduction_Iteration"])
        w.writerows(rows)
    return len(rows)


def plot_g1_g2_trend(result, max_valid_iteration: int, path: str) -> None:
    plt = _plt()
    keep = result.iterations <= max_valid_iteration
    it = result.iterations[keep]
    plt.figure(figsize=(5, 3))
    plt.plot(it, result.rates("g1")[keep], color="#1f77b4", linestyle="-",
             label=GROUP_LABELS["group1"], linewidth=1, marker="o",
             markersize=1)
    plt.plot(it, result.rates("g2")[keep], color="#ff7f0e", linestyle="-",
             label=GROUP_LABELS["group2"], linewidth=1, alpha=0.7,
             marker="o", markersize=1)
    plt.xlabel("Fuzzing Session")
    plt.ylabel("Percentage of Projects Detecting Bugs", y=0.45)
    plt.legend()
    plt.grid(True, linestyle="--", alpha=0.6)
    if it.size and it.max() > 500:
        from matplotlib.ticker import MaxNLocator

        plt.gca().xaxis.set_major_locator(
            MaxNLocator(integer=True, prune="upper"))
    plt.tight_layout(pad=0.1)
    plt.savefig(path, format="pdf")
    plt.close()


def plot_g4_trend(prepost, n_windows: int, path: str) -> None:
    plt = _plt()
    rates = prepost.step_rates()
    if rates.size == 0:
        return
    N = n_windows
    sort_idx = [s + N if s < 0 else s + N - 1 for s in prepost.steps]
    plt.figure(figsize=(5, 3))
    plt.plot(sort_idx, rates, color="#2ca02c", linestyle="-", marker="o",
             markersize=5, linewidth=1.5)
    plt.axvline(x=(N - 1) + 0.5, color="r", linestyle="--", linewidth=1.0,
                label="Corpus Specification")
    plt.xlabel("Fuzzing Session (Relative Step: Pre/Post)")
    plt.ylabel("Percentage of Projects Detecting Bugs", y=0.45)
    labels = [f"-{-s}" if s < 0 else f"+{s}" for s in prepost.steps]
    plt.xticks(sort_idx, labels, rotation=0)
    plt.ylim(0, 32)
    plt.legend(loc="upper left")
    plt.grid(True, linestyle="--", alpha=0.6)
    plt.tight_layout(pad=0.1)
    tc = prepost.transition_counts()
    text = "\n".join([
        f"no detection: {tc['no_detection']:>2} project",
        f"pre only detection: {tc['pre_only']:>2} project",
        f"pre&post detection: {tc['pre_and_post']:>2} project",
        f"post only detection: {tc['post_only']:>2} project",
    ])
    plt.gca().text(0.98, 0.05, text, transform=plt.gca().transAxes,
                   ha="right", va="bottom", fontsize=9,
                   fontfamily="monospace",
                   bbox=dict(facecolor="white", alpha=0.85,
                             edgecolor=(0, 0, 0, 0.35), linewidth=0.8))
    plt.savefig(path, format="pdf")
    plt.close()


def plot_transition_venn(prepost, path: str) -> None:
    """Pre/post detection Venn (rq4a:843-879).  matplotlib-venn is optional
    in the reference too; without it we draw the two-circle diagram with
    plain matplotlib so the artifact always exists."""
    plt = _plt()
    tc = prepost.transition_counts()
    pre_only, post_only = tc["pre_only"], tc["post_only"]
    both, neither = tc["pre_and_post"], tc["no_detection"]
    total = len(prepost.kept_projects)
    try:
        from matplotlib_venn import venn2

        plt.figure(figsize=(5, 4))
        v = venn2(subsets=(pre_only, post_only, both),
                  set_labels=("Detected in Pre", "Detected in Post"))
        for pid, color in (("10", "skyblue"), ("01", "lightgreen"),
                           ("11", "violet")):
            patch = v.get_patch_by_id(pid)
            if patch:
                patch.set_alpha(0.5)
                patch.set_color(color)
        plt.title("Bug Detection Overlap (Group C)")
        plt.text(0, -0.65, f"Neither Detected: {neither}\n(Total: {total})",
                 ha="center", fontsize=9)
    except ImportError:
        fig, ax = plt.subplots(figsize=(5, 4))
        for cx, color in ((-0.45, "skyblue"), (0.45, "lightgreen")):
            ax.add_patch(plt.Circle((cx, 0), 0.9, alpha=0.5, color=color))
        ax.text(-0.85, 0, str(pre_only), ha="center", fontsize=12)
        ax.text(0.85, 0, str(post_only), ha="center", fontsize=12)
        ax.text(0, 0, str(both), ha="center", fontsize=12)
        ax.text(-0.45, 1.05, "Detected in Pre", ha="center", fontsize=10)
        ax.text(0.45, 1.05, "Detected in Post", ha="center", fontsize=10)
        ax.text(0, -1.3, f"Neither Detected: {neither}\n(Total: {total})",
                ha="center", fontsize=9)
        ax.set_xlim(-1.8, 1.8)
        ax.set_ylim(-1.6, 1.3)
        ax.set_aspect("equal")
        ax.axis("off")
        ax.set_title("Bug Detection Overlap (Group C)")
    plt.savefig(path, bbox_inches="tight")
    plt.close()


def first_below(rates: np.ndarray, threshold: float = 5.0) -> int:
    below = np.flatnonzero(rates < threshold)
    return int(below[0]) if below.size else len(rates)


def run_rq4a(cfg: Config | None = None, db=None) -> dict:
    timer = PhaseTimer()
    print("--- Starting RQ4 Bug Detection Trend Analysis ---")
    with timer.phase("extract"):
        ctx = StudyContext.open(cfg, db=db, announce=False)
    manifest = RunManifest("rq4a", ctx.backend.name)
    lim = limit_date_ns(ctx.cfg)
    N = ctx.cfg.analysis_iterations

    groups = load_corpus_groups(ctx.cfg.corpus_csv, set(ctx.projects),
                                ctx.cfg.days_threshold)
    pidx = ctx.arrays.project_index()
    g1_idx = groups.indices("group1", pidx)
    g2_idx = groups.indices("group2", pidx)

    with timer.phase("trend_kernel"):
        result = ctx.backend.rq4a_detection_trend(
            ctx.arrays, lim, g1_idx, g2_idx, ctx.min_projects)
    with timer.phase("g4_prepost"):
        prepost = g4_prepost(ctx.arrays, lim, groups, N)

    out_dir = ctx.out_dir("rq4/bug")
    with timer.phase("artifacts"):
        trend_csv = os.path.join(out_dir, "rq4_g1_g2_detection_trend.csv")
        save_trend_csv(result, trend_csv)
        manifest.add_artifact(trend_csv)

        intro_csv = os.path.join(out_dir, "rq4_gc_introduction_iteration.csv")
        n_intro = save_intro_csv(prepost, intro_csv)
        manifest.add_artifact(intro_csv)

        # Console reporting block (rq4a:694-747).
        g1r, g2r = result.rates("g1"), result.rates("g2")
        n_valid = result.iterations.size
        print(f"Groups used: {GROUP_LABELS['group1']} "
              f"({len(groups.groups['group1'])} projects), "
              f"{GROUP_LABELS['group2']} "
              f"({len(groups.groups['group2'])} projects)")
        superior = int((g2r > g1r).sum())
        pct = superior / n_valid * 100 if n_valid else 0.0
        print("Count of Group B exceeding Group A within valid data range: "
              f"{superior}/{n_valid} ({pct:.2f}%)")
        for label, rates in (("Group A", g1r), ("Group B", g2r)):
            fb = first_below(rates)
            if fb < len(rates):
                print(f"{label}: {int(result.iterations[fb])}th iteration "
                      f"fell below 5% (value: {rates[fb]:.2f}%)")
                late = rates[fb:]
                iqr = np.subtract(*np.percentile(late, [75, 25]))
                print(f"{label}: median {np.median(late):.2f}, IQR {iqr:.2f}")
            else:
                print(f"{label}: No iteration fell below 5%")

        max_valid = int(result.iterations.max()) if n_valid else 0
        print(f"\n[Graph Limit Info] Max iteration where both groups "
              f"maintained >= {ctx.min_projects} projects: {max_valid}")

        trend_pdf = os.path.join(out_dir, "rq4_g1_g2_detection_trend.pdf")
        plot_g1_g2_trend(result, max_valid, trend_pdf)
        manifest.add_artifact(trend_pdf)

        # G4 block (rq4a:788-801).
        intro_vals = np.array([v for v in prepost.intro_iteration.values()
                               if v > 0])
        if intro_vals.size:
            print(f"[RESULT] Introduction Iteration (N={intro_vals.size}): "
                  f"mean {intro_vals.mean():.2f}, "
                  f"median {np.median(intro_vals):.1f}, "
                  f"min {intro_vals.min()}, max {intro_vals.max()}")
        rates = prepost.step_rates()
        n_kept = len(prepost.kept_projects)
        pre_rate = float(rates[:N].mean()) if n_kept else 0.0
        post_rate = float(rates[N:].mean()) if n_kept else 0.0
        print(f"Average Pre-Introduction Detection Rate:  {pre_rate:.2f}%")
        print(f"Average Post-Introduction Detection Rate: {post_rate:.2f}%")
        print(f"Effect (Post - Pre): {post_rate - pre_rate:+.2f} points")
        tc = prepost.transition_counts()
        print("\n=== Group C Pre/Post Detection Transition ===")
        print(f"Total Projects: {n_kept}")
        print(f" (i)-(iii) Detected in Pre AND Detected in Post: "
              f"{tc['pre_and_post']}")
        print(f" (i)-(iv)  Detected in Pre AND NOT Detected in Post: "
              f"{tc['pre_only']}")
        print(f" (ii)-(iii) NOT Detected in Pre AND Detected in Post: "
              f"{tc['post_only']}")
        print(f" (ii)-(iv)  NOT Detected in Pre AND NOT Detected in Post: "
              f"{tc['no_detection']}")
        print(f"Valid project count for Group C: {n_kept}")

        g4_pdf = os.path.join(out_dir, "rq4_gc_detection_trend.pdf")
        plot_g4_trend(prepost, N, g4_pdf)
        if os.path.exists(g4_pdf):
            manifest.add_artifact(g4_pdf)
        venn_pdf = os.path.join(out_dir, "rq4_gc_bug_detection_venn.pdf")
        if n_kept:
            plot_transition_venn(prepost, venn_pdf)
            manifest.add_artifact(venn_pdf)

    manifest.record(
        n_projects=ctx.arrays.n_projects,
        group_sizes={k: len(v) for k, v in groups.groups.items()},
        n_valid_iterations=n_valid,
        g2_superiority={"count": superior, "total": n_valid, "pct": pct},
        g4={"n_kept": n_kept, "n_intro": n_intro,
            "missing_pre": len(prepost.missing_pre),
            "pre_rate": pre_rate, "post_rate": post_rate,
            "transitions": tc},
    )
    manifest.record_backend(ctx.backend)
    manifest.save(out_dir, timer.as_dict())
    print("--- RQ4 Bug Detection Trend Analysis Finished ---")
    return {"result": result, "prepost": prepost, "groups": groups,
            "trend_csv": trend_csv, "intro_csv": intro_csv}


def main() -> None:
    run_rq4a()


if __name__ == "__main__":
    main()
