"""RQ2 coverage trends — re-implementation of
``program/research_questions/rq2_coverage_count.py``.

Artifact parity (all under ``rq2/``):
- ``coverage_by_session_index.csv`` — ragged rows, row i = every project's
  coverage% at its i-th session (rq2_coverage_count.py:347-352).
- ``all_project_corr_hist.pdf`` — histogram of per-project Spearman
  correlations (rq2:376-384).
- ``session_coverage_boxplot.pdf`` — boxplots every 100 sessions with the
  >=100-project filter (rq2:386-435).
- ``average_median_lineplot.pdf`` — mean/median trend (rq2:460-474).
- ``session_coverage_distribution_trend.pdf`` — percentile bands (rq2:123-242).
- ``projects/<corr>_<project>.pdf`` — per-project trend charts when
  |corr| > 0.5 (rq2:324-327).

Statistical tests (Shapiro-Wilk normality per project and on the median
trend, Spearman of the median trend) stay host-side scipy on
already-reduced vectors (SURVEY.md §7.2 step 6).
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .common import StudyContext, limit_date_ns
from ..config import Config
from ..utils.logging import get_logger
from ..utils.atomic import atomic_write
from ..utils.manifest import RunManifest
from ..utils.timing import PhaseTimer

log = get_logger("rq2b")


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def save_ragged_csv(result, path: str) -> int:
    """Row i = coverage values of every project alive at session i."""
    S = result.matrix.shape[1]
    with atomic_write(path, newline="") as f:
        w = csv.writer(f)
        if S == 0:
            w.writerow([])
            return 0
        for s in range(S):
            col = result.matrix[result.mask[:, s], s]
            w.writerow([float(v) for v in col])
    return S


def plot_corr_hist(spearman: np.ndarray, path: str) -> None:
    plt = _plt()
    valid = spearman[~np.isnan(spearman)]
    plt.figure(figsize=(5, 3))
    plt.hist(valid, bins=40, color="skyblue", edgecolor="black", alpha=0.8)
    plt.xlabel("Correlation")
    plt.ylabel("Frequency")
    plt.tight_layout(pad=0.2)
    plt.savefig(path, format="pdf")
    plt.close()


def plot_session_boxplot(result, path: str, min_projects: int,
                         step: int = 100) -> None:
    """Boxplot every `step` sessions over sessions with >= min_projects
    (rq2:386-435): coverage boxes over a project-count bar background."""
    plt = _plt()
    S = result.matrix.shape[1]
    data, labels = [], []
    for s in range(0, S, step):
        col = result.matrix[result.mask[:, s], s]
        if col.size >= min_projects:
            data.append(col)
            labels.append(s + 1)
    if not data:
        return
    plt.figure(figsize=(7.5, 4.5))
    ax1 = plt.gca()
    ax2 = ax1.twinx()
    ax1.set_zorder(ax2.get_zorder() + 1)
    ax1.patch.set_visible(False)
    ax2.bar(range(1, len(data) + 1), [len(d) for d in data],
            color="#88c778", alpha=0.6, zorder=1)
    ax2.set_ylabel("Number of Projects")
    box = ax1.boxplot(data, patch_artist=True, zorder=3)
    for patch in box["boxes"]:
        patch.set_facecolor("#e3eefa")
    for median in box["medians"]:
        median.set_color("#000000")
    for i, d in enumerate(data, start=1):
        ax1.scatter(i, np.mean(d), color="#215F9A", marker="^", zorder=4, s=8)
    ax1.set_ylabel("Coverage (%)")
    ax1.set_ylim(0, 100)
    ax1.set_xlabel("Coverage Measurement Count")
    pos = list(range(1, len(data) + 1))[::2]
    ax1.set_xticks(pos)
    ax1.set_xticklabels(labels[::2], rotation=45)
    plt.tight_layout(pad=0.2)
    plt.savefig(path, format="pdf", transparent=True)
    plt.close()


def plot_mean_median(result, path: str, min_projects: int) -> None:
    plt = _plt()
    enough = result.counts >= min_projects
    mean = result.mean[enough]
    median = result.percentiles[2][enough]  # PCTS index 2 = 50
    idx = list(range(int(enough.sum())))
    plt.figure(figsize=(6, 4))
    plt.plot(idx, mean, label="Average", marker="o", color="blue",
             markersize=1, linewidth=1)
    plt.plot(idx, median, label="Median", marker="s", color="orange",
             markersize=1, linewidth=1)
    plt.xlabel(f"Session Index (with >= {min_projects} projects)")
    plt.ylabel("Coverage (%)")
    plt.title("Average and Median Coverage Over Time")
    plt.legend()
    plt.grid(True, linestyle="--", alpha=0.5)
    plt.tight_layout()
    plt.savefig(path, format="pdf")
    plt.close()


def plot_distribution_trend(result, path: str, min_projects: int) -> None:
    """Percentile-band distribution plot (rq2:123-242) over sessions with
    >= min_projects data points."""
    plt = _plt()
    enough = result.counts >= min_projects
    if not enough.any():
        return
    idx = list(range(int(enough.sum())))
    p5, p25, p50, p75, p95 = (result.percentiles[i][enough] for i in range(5))
    mean = result.mean[enough]
    counts = result.counts[enough]

    fig, (ax_num, ax_cov) = plt.subplots(
        2, 1, figsize=(10, 6), sharex=True,
        gridspec_kw={"height_ratios": [1, 3]})
    ax_num.plot(idx, counts, color="tab:blue", linewidth=1.5)
    ax_num.set_ylabel("#Projects")
    ax_num.set_ylim(bottom=0)
    ax_num.set_title("Coverage Percentage across Fuzzing Sessions")

    cmap = plt.get_cmap("Blues")
    ax_cov.fill_between(idx, p25, p75, color=cmap(0.8), alpha=0.35,
                        label="Percentile 25-75%", zorder=1)
    ax_cov.fill_between(idx, p5, p95, color=cmap(0.4), alpha=0.28, zorder=0)
    ax_cov.plot(idx, p5, color="#6889df", linewidth=1.3,
                label="Percentile 5-95%", zorder=3)
    ax_cov.plot(idx, p95, color="#6889df", linewidth=1.3, zorder=3)
    ax_cov.plot(idx, p50, color="#2ca02c", linewidth=2, label="Median", zorder=4)
    ax_cov.plot(idx, mean, color="#ffb43b", linewidth=2, label="Mean", zorder=4)
    for x in range(0, len(idx), 100):
        ax_cov.axvline(x=x, color="gray", linewidth=0.5, linestyle="--",
                       alpha=0.5)
    ax_cov.set_xticks(range(0, len(idx), 200))
    ax_cov.set_ylabel("Line Coverage %")
    ax_cov.set_xlabel("Coverage Measurement Count (Sessions)")
    ax_cov.set_ylim(0, 100)
    if len(idx) > 1:
        ax_cov.set_xlim(left=0, right=len(idx) - 1)
    handles, labels = ax_cov.get_legend_handles_labels()
    fig.legend(handles, labels, loc="lower center",
               bbox_to_anchor=(0.5, -0.05), ncol=4, frameon=False)
    fig.tight_layout()
    plt.subplots_adjust(bottom=0.2)
    fig.savefig(path, bbox_inches="tight")
    plt.close(fig)


def plot_project_trend(trend: np.ndarray, path: str) -> None:
    """Single-project coverage% chart (rq2:23-120, simplified to the
    coverage line; emitted when |spearman| > 0.5)."""
    plt = _plt()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fig, ax = plt.subplots(figsize=(5, 3))
    ax.plot(range(len(trend)), trend, color="red", alpha=0.7, linewidth=1.3)
    ax.set_ylabel("Coverage (%)")
    ax.set_ylim(0, 105)
    ax.set_xlabel("Coverage Measurement Count")
    fig.tight_layout()
    fig.savefig(path, bbox_inches="tight")
    plt.close(fig)


def run_rq2_trends(cfg: Config | None = None, db=None,
                   per_project_figures: bool = True) -> dict:
    from scipy.stats import shapiro, spearmanr

    timer = PhaseTimer()
    with timer.phase("extract"):
        ctx = StudyContext.open(cfg, db=db, announce=False)
    manifest = RunManifest("rq2_trends", ctx.backend.name)

    with timer.phase("trend_kernel"):
        result = ctx.backend.rq2_trends(ctx.arrays, limit_date_ns(ctx.cfg))

    # Shapiro-Wilk normality per project (rq2:305-314) — host scipy on the
    # already-reduced per-project trends.
    tested = normal = 0
    for p in range(ctx.arrays.n_projects):
        trend = result.matrix[p, result.mask[p]]
        if len(trend) >= 3:
            tested += 1
            try:
                _, sw_p = shapiro(trend)
                if sw_p > 0.05:
                    normal += 1
            except ValueError:  # shapiro rejects degenerate trends
                pass
    if tested:
        print(f"Projects tested for normality (N >= 3 sessions): {tested}")
        print(f"Projects whose coverage trend follows normal distribution "
              f"(p > 0.05): {normal}")
        print(f"Percentage of normally distributed projects: "
              f"{normal / tested * 100:.2f}%")

    valid = result.spearman[~np.isnan(result.spearman)]
    print(f"Total projects processed: {len(result.spearman)}")
    print(f"Number of projects with valid correlation: {len(valid)}")
    if len(valid):
        print(f"Average correlation: {np.mean(valid):.4f}, "
              f"Median correlation: {np.median(valid):.4f}")

    out_dir = ctx.out_dir("rq2")
    min_p = ctx.min_projects
    with timer.phase("artifacts"):
        csv_path = os.path.join(out_dir, "coverage_by_session_index.csv")
        save_ragged_csv(result, csv_path)
        manifest.add_artifact(csv_path)

        hist = os.path.join(out_dir, "all_project_corr_hist.pdf")
        plot_corr_hist(result.spearman, hist)
        manifest.add_artifact(hist)

        boxp = os.path.join(out_dir, "session_coverage_boxplot.pdf")
        plot_session_boxplot(result, boxp, min_p)

        linep = os.path.join(out_dir, "average_median_lineplot.pdf")
        plot_mean_median(result, linep, min_p)

        dist = os.path.join(out_dir, "session_coverage_distribution_trend.pdf")
        plot_distribution_trend(result, dist, min_p)

        if per_project_figures:
            for p in range(ctx.arrays.n_projects):
                corr = result.spearman[p]
                if not np.isnan(corr) and abs(corr) > 0.5:
                    trend = result.matrix[p, result.mask[p]]
                    fig_path = os.path.join(
                        out_dir, "projects",
                        f"{corr:.4f}_{ctx.projects[p]}.pdf")
                    plot_project_trend(trend, fig_path)

    # Median-trend stats (rq2:437-458).
    enough = result.counts >= min_p
    median_trend = result.percentiles[2][enough]
    stats = {}
    if len(median_trend) > 1:
        rho, pval = spearmanr(range(len(median_trend)), median_trend)
        stats["median_trend_spearman"] = (float(rho), float(pval))
        print("Spearman correlation (Session Index vs. Median):",
              (float(rho), float(pval)))
    if len(median_trend) >= 3:
        _, sw_p = shapiro(median_trend)
        stats["median_trend_shapiro_p"] = float(sw_p)
        print(f"Shapiro-Wilk test for 'median_trend' "
              f"(N={len(median_trend)}): p-value = {sw_p:.4f}")

    manifest.record(
        n_projects=len(result.spearman),
        n_sessions=int(result.matrix.shape[1]),
        n_sessions_min_projects=int(enough.sum()),
        normality={"tested": tested, "normal": normal},
        **{k: v for k, v in stats.items()},
    )
    manifest.record_backend(ctx.backend)
    manifest.save(out_dir, timer.as_dict())
    return {"result": result, "stats": stats, "csv": csv_path}


def main() -> None:
    run_rq2_trends()


if __name__ == "__main__":
    main()
