"""Shared analysis plumbing: study context (config + DB + columnar arrays),
artifact helpers, and the study-design printout mirrored from the reference
transcript (rq1_detection_rate.py:121-153)."""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np
import pandas as pd

from ..backend import get_backend
from ..backend.base import Backend
from ..config import Config, FIXED_STATUSES, load_config
from ..data.columnar import StudyArrays
from ..db import queries
from ..db.connection import DB
from ..utils.logging import get_logger

log = get_logger("analysis")


def limit_date_ns(cfg: Config) -> int:
    return int(np.datetime64(cfg.limit_date, "ns").astype(np.int64))


def fmt_ts_ns(ns: int) -> str:
    """Format an epoch-ns timestamp like psycopg2's str(datetime): seconds,
    with fractional part only when non-zero (golden CSVs show both forms)."""
    t = pd.Timestamp(ns)
    base = t.strftime("%Y-%m-%d %H:%M:%S")
    if t.microsecond:
        return f"{base}.{t.microsecond:06d}"
    return base


@dataclass
class StudyContext:
    cfg: Config
    db: DB
    backend: Backend
    projects: list[str]
    arrays: StudyArrays

    @classmethod
    def open(cls, cfg: Config | None = None, db: DB | None = None,
             announce: bool = True) -> "StudyContext":
        cfg = cfg or load_config()
        own_db = db is None
        if own_db:
            db = DB(config=cfg).connect()
        db.require_study_tables()

        if announce:
            n_all, p_all = _issue_counts(db, cfg, fixed=False)
            n_fix, p_fix = _issue_counts(db, cfg, fixed=True)
            print(f"Found {n_all:,} issues from {p_all:,} projects before "
                  f"{cfg.limit_date}. (in study design)")
            print(f"Found {n_fix:,} fixed issues from {p_fix:,} projects before "
                  f"{cfg.limit_date}. (in study design)")

        sql, params = queries.eligible_projects(cfg.min_coverage_days, cfg.limit_date)
        projects = sorted(r[0] for r in db.query(sql, params))
        if announce:
            print(f"Found {len(projects):,} projects with at least "
                  f"{cfg.min_coverage_days} coverage reports.")
        if cfg.test_mode:
            projects = projects[:10]
            print(f"[TEST MODE] Limiting to the first {len(projects)} projects.")

        arrays = StudyArrays.from_db(db, cfg, projects=projects)
        return cls(cfg=cfg, db=db, backend=get_backend(cfg), projects=projects,
                   arrays=arrays)

    @property
    def min_projects(self) -> int:
        return 1 if self.cfg.test_mode else self.cfg.min_projects_per_iteration

    def out_dir(self, sub: str) -> str:
        path = os.path.join(self.cfg.result_dir, sub)
        os.makedirs(path, exist_ok=True)
        return path


def _issue_counts(db: DB, cfg: Config, fixed: bool) -> tuple[int, int]:
    sql = "SELECT COUNT(*), COUNT(DISTINCT project) FROM issues WHERE rts < ?"
    params: tuple = (cfg.limit_date,)
    if fixed:
        sql += f" AND status IN {queries._in(FIXED_STATUSES)}"
        params += FIXED_STATUSES
    (n, p), = db.query(sql, params)
    return n, p
