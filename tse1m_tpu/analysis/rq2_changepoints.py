"""RQ2 change-point extraction — re-implementation of
``program/research_questions/rq2_coverage_and_added.py``.

Artifact parity (note: the reference writes this analysis under the *rq3*
result dir, rq2_coverage_and_added.py:14-15 — kept for drop-in parity):

- ``rq3/change_analysis/<project>.csv`` — one CSV per project with a change
  row per (group i -> group i+1) revision change (rq2:96-102 header).
- ``rq3/all_coverage_change_analysis.csv`` — all projects merged (rq2:232-238).
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .common import StudyContext, fmt_ts_ns, limit_date_ns
from ..config import Config
from ..db.ingest import parse_array, pg_array_literal
from ..utils.logging import get_logger
from ..utils.atomic import atomic_write
from ..utils.manifest import RunManifest
from ..utils.timing import PhaseTimer

log = get_logger("rq2a")

HEADER = [
    "project", "timecreated_i", "modules_i", "revisions_i",
    "timecreated_i+1", "modules_i+1", "revisions_i+1",
    "covered_line_i", "total_line_i",
    "covered_line_i+1", "total_line_i+1",
    "diff_total_line", "diff_coverage",
]


def change_rows(ctx: StudyContext, result) -> dict[str, list[list]]:
    """Per-project lists of CSV rows in reference column order."""
    covb = ctx.arrays.covb
    t = covb.columns["time_ns"]
    # Raw DB text, parsed per boundary row only — the change set is tiny
    # next to the full coverage-build table (from_db keeps columns raw).
    mods_raw = covb.columns["modules_raw"]
    revs_raw = covb.columns["revisions_raw"]
    diff_total = result.diff_total_line
    diff_cov = result.diff_coverage
    per_project: dict[str, list[list]] = {}
    for k in range(len(result.project_idx)):
        p = int(result.project_idx[k])
        e, s1 = int(result.end_i[k]), int(result.start_ip1[k])
        row = [
            ctx.projects[p],
            fmt_ts_ns(int(t[e])),
            pg_array_literal(parse_array(mods_raw[e])),
            pg_array_literal(parse_array(revs_raw[e])),
            fmt_ts_ns(int(t[s1])),
            pg_array_literal(parse_array(mods_raw[s1])),
            pg_array_literal(parse_array(revs_raw[s1])),
            result.covered_i[k], result.total_i[k],
            result.covered_ip1[k], result.total_ip1[k],
            diff_total[k], diff_cov[k],
        ]
        per_project.setdefault(ctx.projects[p], []).append(row)
    return per_project


def run_rq2_changepoints(cfg: Config | None = None, db=None) -> dict:
    timer = PhaseTimer()
    with timer.phase("extract"):
        ctx = StudyContext.open(cfg, db=db, announce=False)
    manifest = RunManifest("rq2_changepoints", ctx.backend.name)

    with timer.phase("changepoint_kernel"):
        result = ctx.backend.rq2_change_points(ctx.arrays, limit_date_ns(ctx.cfg))

    n_changes = len(result.project_idx)
    log.info("found %d change points across %d projects", n_changes,
             len(np.unique(result.project_idx)))

    out_dir = ctx.out_dir("rq3")  # reference writes rq2a artifacts under rq3
    change_dir = os.path.join(out_dir, "change_analysis")
    os.makedirs(change_dir, exist_ok=True)

    with timer.phase("artifacts"):
        per_project = change_rows(ctx, result)
        all_rows = []
        for project, rows in per_project.items():
            path = os.path.join(change_dir, f"{project}.csv")
            with atomic_write(path, newline="") as f:
                w = csv.writer(f)
                w.writerow(HEADER)
                w.writerows(rows)
            all_rows.extend(rows)
        merged = os.path.join(out_dir, "all_coverage_change_analysis.csv")
        if all_rows:
            with atomic_write(merged, newline="") as f:
                w = csv.writer(f)
                w.writerow(HEADER)
                w.writerows(all_rows)
            manifest.add_artifact(merged)

    manifest.record(n_changes=n_changes, n_projects=len(per_project))
    manifest.record_backend(ctx.backend)
    manifest.save(out_dir, timer.as_dict())
    return {"result": result, "merged_csv": merged if all_rows else None}


def main() -> None:
    run_rq2_changepoints()


if __name__ == "__main__":
    main()
