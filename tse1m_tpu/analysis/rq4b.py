"""RQ4b — seed-corpus effect on coverage.

Re-implementation of ``program/research_questions/rq4b_coverage.py`` (live
paths only; the reference's disabled violin/nested/custom-color variants,
rq4b:1241-1259, are not replicated).  Artifacts under ``rq4/coverage/``:

- ``coverage_delta_timeseries_linear.pdf`` — pre/post delta boxplots around
  corpus introduction for G3+G4 (rq4b:1041-1118).
- ``g2_g1_boxplot_comparison.pdf`` — side-by-side G1/G2 coverage boxplots
  every 100 sessions until either group drops below 100 projects
  (rq4b:491-637).
- ``g2_g1_trend_stats.csv`` — the per-session percentile/count table the
  reference builds in memory (rq4b:938-976, headers ``Session,G2_25,...``)
  but never writes; persisted here so the summary is reproducible.

Console parity: per-session Brunner-Munzel significance summary with
first-significant session, Q1/Median/Q3 win ratios, Spearman trend
correlations (rq4b:799-908); initial-coverage Mann-Whitney U, Cliff's
delta, Brunner-Munzel, Levene (rq4b:248-313); per-step coverage medians
(rq4b:1060-1085).
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .common import StudyContext, limit_date_ns
from .corpus import CorpusGroups, load_corpus_groups
from ..backend.pandas_backend import floor_day_ns
from ..config import Config
from ..utils.logging import get_logger
from ..utils.atomic import atomic_write
from ..utils.manifest import RunManifest
from ..utils.timing import PhaseTimer

log = get_logger("rq4b")

PERCENTILES = (25, 50, 75)
BOXPLOT_STEP = 100


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


# -- Analysis 2: pre/post coverage deltas (rq4b:725-797) ---------------------

def coverage_deltas(arrays, groups: CorpusGroups, n_iters: int) -> dict:
    """Pre/post coverage around corpus introduction for G3+G4 projects.

    Reference semantics (rq4b:744-794): last/first ``n_iters`` non-null > 0
    coverage rows strictly before / from the corpus *date* on; projects
    missing a full window on either side are dropped (missing-pre ones
    recorded).  Deltas are relative to Pre-1 (the most recent pre row).
    The reference query is date-unbounded; our extraction window ends at
    limit_date + 1 day, which covers every real corpus introduction."""
    target = groups.groups["group3"] | groups.groups["group4"]
    pidx = arrays.project_index()
    N = n_iters
    out = {
        "pre_deltas": np.zeros((0, N)), "post_deltas": np.zeros((0, N)),
        "pre_coverages": np.zeros((0, N)), "post_coverages": np.zeros((0, N)),
        "group_num": np.zeros(0, dtype=np.int64),
        "projects": [], "missing_pre": set(),
    }
    out["post_truncated"] = set()
    pre_rows, post_rows, gnum, kept = [], [], [], []
    for name in sorted(target):
        t_corpus = groups.corpus_time_ns.get(name)
        if t_corpus is None or name not in pidx:
            continue
        p = pidx[name]
        seg = arrays.cov.segment(p)
        sel = (~np.isnan(seg["coverage"])) & (seg["coverage"] > 0)
        dates = seg["date_ns"][sel]
        cov = seg["coverage"][sel]
        corpus_day = floor_day_ns(np.int64(t_corpus))
        k = int(np.searchsorted(dates, corpus_day, side="left"))
        pre = cov[max(0, k - N):k][::-1]     # Pre-1 first (DESC order)
        post = cov[k:k + N]
        if pre.size < N or post.size < N:
            if pre.size == 0:
                out["missing_pre"].add(name)
            elif pre.size >= N:  # hence post.size < N
                # The reference's pre/post queries are date-unbounded
                # (rq4b:758-774); our extraction stops at limit_date + 1 day,
                # so a full-pre project short only on the post side may be a
                # casualty of the truncated window — record it so the
                # deviation is observable.
                out["post_truncated"].add(name)
            continue
        pre_rows.append(pre)
        post_rows.append(post)
        gnum.append(4 if name in groups.groups["group4"] else 3)
        kept.append(name)
    if kept:
        pre_m = np.array(pre_rows)
        post_m = np.array(post_rows)
        base = pre_m[:, 0:1]
        out.update(
            pre_deltas=base - pre_m,          # [n, N], col i = Pre-(i+1)
            post_deltas=post_m - base,        # [n, N], col i = Post-(i+1)
            pre_coverages=pre_m, post_coverages=post_m,
            group_num=np.array(gnum), projects=kept,
        )
    return out


# -- Analysis 1: initial coverage stats (rq4b:248-313) ----------------------

def initial_coverage_stats(g2_cov: np.ndarray, g1_cov: np.ndarray) -> dict:
    from scipy.stats import brunnermunzel, levene, mannwhitneyu

    n2, n1 = len(g2_cov), len(g1_cov)
    if n2 == 0 or n1 == 0:
        return {"n_g2": n2, "n_g1": n1}
    _, p_mw = mannwhitneyu(g2_cov, g1_cov, alternative="two-sided")
    u1, _ = mannwhitneyu(g2_cov, g1_cov, alternative="greater")
    cliffs = (2 * u1) / (n2 * n1) - 1
    bm_stat, p_bm = brunnermunzel(g2_cov, g1_cov, alternative="two-sided")
    lv_stat, p_lv = levene(g2_cov, g1_cov)
    return {
        "n_g2": n2, "n_g1": n1,
        "mannwhitney_p_two_sided": float(p_mw),
        "cliffs_delta": float(cliffs),
        "brunner_stat": float(bm_stat), "brunner_p": float(p_bm),
        "levene_stat": float(lv_stat), "levene_p": float(p_lv),
    }


# -- Analysis 3: per-session BM + trend summary (rq4b:799-1012) -------------

def session_bm_pvalues(result, g1_idx, g2_idx, min_n: int = 5) -> np.ndarray:
    """Two-sided Brunner-Munzel per session where both groups have >= min_n
    values (rq4b:978-985)."""
    import warnings

    from scipy.stats import brunnermunzel

    S = result.matrix.shape[1]
    p_values = np.full(S, np.nan)
    for s in range(S):
        g2_d = result.matrix[g2_idx, s][result.mask[g2_idx, s]]
        g1_d = result.matrix[g1_idx, s][result.mask[g1_idx, s]]
        if g2_d.size >= min_n and g1_d.size >= min_n:
            try:
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    _, p_values[s] = brunnermunzel(g2_d, g1_d,
                                                   alternative="two-sided")
            except ValueError:  # brunnermunzel rejects degenerate groups
                pass
    return p_values


def summarize_trends(result, p_values: np.ndarray,
                     min_projects: int) -> dict:
    """The reference's trend summary block (rq4b:799-1012): slice to the
    LAST session where both groups hold >= min_projects, then report BM
    significance, per-percentile win ratios, and Spearman correlations."""
    from scipy.stats import spearmanr

    both = (result.g1_counts >= min_projects) & (result.g2_counts >= min_projects)
    if not both.any():
        return {"valid_sessions": 0}
    last = int(np.flatnonzero(both)[-1])
    sl = slice(0, last + 1)
    p = p_values[sl]
    valid_p = ~np.isnan(p)
    sig = valid_p & (p < 0.05)
    first_sig = int(np.flatnonzero(sig)[0]) + 1 if sig.any() else None

    g2p, g1p = result.g2_percentiles[:, sl], result.g1_percentiles[:, sl]
    ok = ~(np.isnan(g2p).any(axis=0) | np.isnan(g1p).any(axis=0))
    n_cmp = int(ok.sum())
    wins = {}
    spearman = {}
    if n_cmp:
        it = np.arange(1, n_cmp + 1)
        for i, pct in enumerate(result.percentiles):
            wins[pct] = int((g2p[i, ok] > g1p[i, ok]).sum())
            cg1, pg1 = spearmanr(it, g1p[i, ok])
            cg2, pg2 = spearmanr(it, g2p[i, ok])
            spearman[pct] = {"g1": (float(cg1), float(pg1)),
                             "g2": (float(cg2), float(pg2))}
    return {
        "valid_sessions": last + 1,
        "bm_significant": int(sig.sum()),
        "bm_valid": int(valid_p.sum()),
        "first_significant_session": first_sig,
        "comparison_n": n_cmp,
        "wins": wins,
        "spearman": spearman,
    }


def print_trend_summary(summary: dict, percentiles=PERCENTILES) -> None:
    print("\n=== Trend Analysis Summary (Trend Summary) ===")
    if not summary.get("valid_sessions"):
        print("No sessions met the condition.")
        return
    print(f"Target Valid Period: 1 ~ {summary['valid_sessions']} Sessions")
    if summary["bm_valid"]:
        pct = summary["bm_significant"] / summary["bm_valid"] * 100
        print("Brunner-Munzel Test Significant Difference (p<0.05) Rate: "
              f"{summary['bm_significant']}/{summary['bm_valid']} ({pct:.2f}%)")
        if summary["first_significant_session"]:
            print("First significant difference detected at: "
                  f"{summary['first_significant_session']}th session")
        else:
            print("No significant difference detected.")
    n = summary["comparison_n"]
    if n:
        names = {25: "Q1", 50: "Median", 75: "Q3"}
        print(f"Group B > Group A Ratio (N={n}):")
        for pct in percentiles:
            w = summary["wins"][pct]
            print(f"  - {names.get(pct, pct):<18}: {w}/{n} ({w / n * 100:.2f}%)")
        print(f"\nSpearman Rank Correlation with Coverage Measurement Count "
              f"(N={n}):")
        for glabel, gkey in (("Group A (No Corpus)", "g1"),
                             ("Group B (Initial Corpus)", "g2")):
            print(f" [{glabel}]")
            for pct in percentiles:
                c, p = summary["spearman"][pct][gkey]
                print(f"  - {names.get(pct, pct):<15} : corr={c:.4f}, "
                      f"p-value={p:.4e}")
    print("============================================\n")


def save_trend_csv(result, p_values, path: str) -> None:
    S = result.matrix.shape[1]
    with atomic_write(path, newline="") as f:
        w = csv.writer(f)
        header = ["Session"]
        for g in ("G2", "G1"):
            header += [f"{g}_{p}" for p in result.percentiles]
            header.append(f"{g}_Count")
        header.append("BM_p_value")
        w.writerow(header)
        for s in range(S):
            row = [s + 1]
            row += [result.g2_percentiles[i, s]
                    for i in range(len(result.percentiles))]
            row.append(int(result.g2_counts[s]))
            row += [result.g1_percentiles[i, s]
                    for i in range(len(result.percentiles))]
            row.append(int(result.g1_counts[s]))
            row.append(p_values[s])
            w.writerow(row)


# -- Plots -------------------------------------------------------------------

def plot_coverage_deltas(deltas: dict, n_iters: int, path: str) -> None:
    """Pre/post delta boxplots, chronological t=-N..-1,1..N (rq4b:1041-1118)."""
    plt = _plt()
    if not deltas["projects"]:
        return
    N = n_iters
    data, labels, colors = [], [], []
    for i in range(N - 1, -1, -1):
        data.append(deltas["pre_deltas"][:, i])
        labels.append(f"-{i + 1}")
        colors.append("#ffcc99")
    for i in range(N):
        data.append(deltas["post_deltas"][:, i])
        labels.append(f"{i + 1}")
        colors.append("#99ff99")
    fig, ax = plt.subplots(figsize=(5, 3))
    box = ax.boxplot(data, patch_artist=True, widths=0.6,
                     flierprops=dict(markersize=2))
    for patch, c in zip(box["boxes"], colors):
        patch.set_facecolor(c)
        patch.set_alpha(0.6)
        patch.set_edgecolor("#333333")
    for part in ("whiskers", "caps", "medians"):
        for line in box[part]:
            line.set_color("#333333")
    ax.set_xticks(range(1, 2 * N + 1))
    ax.set_xticklabels(labels)
    ax.set_ylim(-50, 50)
    ax.set_ylabel("Coverage Delta (Relative to Pre-1)")
    ax.set_xlabel("Time Step (t)")
    ax.axhline(0, ls="--", color="black", linewidth=1.0)
    ax.axvline(N + 0.5, ls=":", color="red", linewidth=1.5)
    plt.tight_layout()
    plt.savefig(path, format="pdf")
    plt.close(fig)


def plot_comparative_boxplot(result, g1_idx, g2_idx, min_projects: int,
                             path: str, step: int = BOXPLOT_STEP) -> None:
    """Side-by-side G1/G2 boxplots every `step` sessions, cut at the first
    sampled session where either group < min_projects (rq4b:491-637)."""
    plt = _plt()
    S = result.matrix.shape[1]
    sessions, data_a, data_b = [], [], []
    for idx in range(0, S, step):
        a = result.matrix[g1_idx, idx][result.mask[g1_idx, idx]]
        b = result.matrix[g2_idx, idx][result.mask[g2_idx, idx]]
        if a.size < min_projects or b.size < min_projects:
            break
        sessions.append(idx + 1)
        data_a.append(a)
        data_b.append(b)
    if not sessions:
        log.warning("No sufficient data for boxplot.")
        return
    fig, ax1 = plt.subplots(figsize=(5, 3))
    central = np.arange(len(sessions))
    w, d = 0.25, 0.125
    bp_a = ax1.boxplot(data_a, positions=central - d, widths=w,
                       patch_artist=True, showfliers=False)
    bp_b = ax1.boxplot(data_b, positions=central + d, widths=w,
                       patch_artist=True, showfliers=False)
    for bp, face, edge, ls in ((bp_a, "#66b3ff", "#104e8b", "--"),
                               (bp_b, "#ff9999", "#d65f00", "-")):
        for box in bp["boxes"]:
            box.set(facecolor=face, edgecolor=edge, linewidth=1.0, alpha=0.6,
                    linestyle=ls)
        for part in ("whiskers", "caps"):
            for line in bp[part]:
                line.set(color=edge, linewidth=1.0, linestyle=ls)
        for median in bp["medians"]:
            median.set(color=edge, linewidth=1.2)
    from matplotlib.patches import Patch

    ax1.set_ylabel("Coverage (%)")
    ax1.set_xlabel("Coverage Measurement Count")
    ax1.set_ylim(0, 100)
    ax1.set_yticks([0, 20, 40, 60, 80, 100])
    ax1.set_xticks(central)
    ax1.set_xticklabels(sessions, rotation=45)
    ax1.set_xlim(left=-0.5, right=len(sessions) - 0.5)
    ax1.legend(handles=[
        Patch(facecolor="#66b3ff", edgecolor="#333333", alpha=0.6,
              label="Group A (No Seed)"),
        Patch(facecolor="#ff9999", edgecolor="#333333", alpha=0.6,
              label="Group B (Initial Seed)"),
    ], loc="upper left", fontsize="small", ncol=2)
    plt.tight_layout()
    plt.savefig(path, format="pdf", bbox_inches="tight")
    plt.close(fig)


# -- Entry point -------------------------------------------------------------

def run_rq4b(cfg: Config | None = None, db=None) -> dict:
    timer = PhaseTimer()
    with timer.phase("extract"):
        ctx = StudyContext.open(cfg, db=db, announce=False)
    manifest = RunManifest("rq4b", ctx.backend.name)
    lim = limit_date_ns(ctx.cfg)
    N = ctx.cfg.analysis_iterations

    groups = load_corpus_groups(ctx.cfg.corpus_csv, set(ctx.projects),
                                ctx.cfg.days_threshold)
    print("\n=== Number of Projects by Group ===")
    for i, key in enumerate(("group1", "group2", "group3", "group4"), 1):
        print(f"Group {i}: {len(groups.groups[key])} projects")
    pidx = ctx.arrays.project_index()
    g1_idx = groups.indices("group1", pidx)
    g2_idx = groups.indices("group2", pidx)

    with timer.phase("trend_kernel"):
        result = ctx.backend.rq4b_group_trends(ctx.arrays, lim, g1_idx,
                                               g2_idx, PERCENTILES)
    with timer.phase("bm_tests"):
        p_values = session_bm_pvalues(result, g1_idx, g2_idx)
    summary = summarize_trends(result, p_values, ctx.min_projects)
    print_trend_summary(summary)

    with timer.phase("deltas"):
        deltas = coverage_deltas(ctx.arrays, groups, N)
    print("\n=== Analysis 2: Pre/Post Corpus Introduction Difference "
          "Analysis (Group C: Strict Filter Applied) ===")
    print(f"Number of projects meeting conditions and analyzed: "
          f"{len(deltas['projects'])}")
    if deltas["post_truncated"]:
        log.warning(
            "%d project(s) dropped with a full pre but short post window; "
            "coverage extraction ends at limit_date + 1 day while the "
            "reference's pre/post queries are date-unbounded",
            len(deltas["post_truncated"]))
    if deltas["projects"]:
        print("\n--- Coverage Median for Each Step (Group C) ---")
        for i in reversed(range(N)):
            med = np.median(deltas["pre_coverages"][:, i])
            print(f" Pre-{i + 1:<3}: {med:.2f} "
                  f"(N={deltas['pre_coverages'].shape[0]})")
        for i in range(N):
            med = np.median(deltas["post_coverages"][:, i])
            print(f" Post-{i + 1:<2}: {med:.2f} "
                  f"(N={deltas['post_coverages'].shape[0]})")

    # Analysis 1: initial coverage = session-1 column of the trend matrix
    # (first non-null > 0 coverage row per project, rq4b:230-239).
    if result.matrix.shape[1]:
        first_col = result.matrix[:, 0]
        first_mask = result.mask[:, 0]
        g2_cov = first_col[g2_idx][first_mask[g2_idx]]
        g1_cov = first_col[g1_idx][first_mask[g1_idx]]
    else:
        g2_cov = np.array([])
        g1_cov = np.array([])
    print("\n=== Analysis 1: G2 vs G1 Initial Coverage Comparison ===")
    print(f"Number of Group 2 projects: {len(groups.groups['group2'])}")
    print(f"Number of Group 1 projects: {len(groups.groups['group1'])}")
    init_stats = initial_coverage_stats(g2_cov, g1_cov)
    for k, v in init_stats.items():
        print(f"[RESULT] {k}: {v}")

    out_dir = ctx.out_dir("rq4/coverage")
    with timer.phase("artifacts"):
        trend_csv = os.path.join(out_dir, "g2_g1_trend_stats.csv")
        save_trend_csv(result, p_values, trend_csv)
        manifest.add_artifact(trend_csv)
        delta_pdf = os.path.join(out_dir,
                                 "coverage_delta_timeseries_linear.pdf")
        plot_coverage_deltas(deltas, N, delta_pdf)
        if os.path.exists(delta_pdf):
            manifest.add_artifact(delta_pdf)
        box_pdf = os.path.join(out_dir, "g2_g1_boxplot_comparison.pdf")
        plot_comparative_boxplot(result, g1_idx, g2_idx, ctx.min_projects,
                                 box_pdf)
        if os.path.exists(box_pdf):
            manifest.add_artifact(box_pdf)

    manifest.record(
        group_sizes={k: len(v) for k, v in groups.groups.items()},
        trend_summary=summary,
        initial_coverage=init_stats,
        deltas={"n_projects": len(deltas["projects"]),
                "missing_pre": len(deltas["missing_pre"]),
                "post_truncated": len(deltas["post_truncated"])},
    )
    manifest.record_backend(ctx.backend)
    manifest.save(out_dir, timer.as_dict())
    print("--- Analysis Finished ---")
    return {"result": result, "p_values": p_values, "summary": summary,
            "deltas": deltas, "initial_stats": init_stats,
            "trend_csv": trend_csv}


def main() -> None:
    run_rq4b()


if __name__ == "__main__":
    main()
