"""Per-RQ routing backend (the resolved form of ``backend = auto``) —
self-calibrating.

Round-4 measurement on the 1M-build study (BENCH_r04): the best engine is
per-RQ, not global.  The host oracle wins the RQs whose pandas form is a
handful of vectorized array ops, while the device wins the ones whose host
form walks per-project/per-group loops — even over a tunneled PJRT link
where every device call pays ~110 ms round-trip.  On co-located TPU
hardware (round-trip ~0.1-0.2 ms) the device wins everything above a few
thousand rows.

Round 4 shipped hand-fitted cost constants for this decision; the round-4
verdict correctly called that a per-machine magic-number table.  The
router now *measures*: the bootstrap priors below steer only the first
call per RQ, and every completed call updates an EWMA of that
(rq, engine)'s observed cost per row on the running machine.  Subsequent
calls route to the engine with the lower predicted wall, so a slower host
CPU or a co-located TPU shifts the crossovers automatically (asserted by
tests/test_backend_auto.py's slow-host flip test).  The first device call
per RQ is excluded from the EWMA — it pays one-time jit compilation.
``calibration()`` exposes the learned state; analysis drivers record it
in the run manifest (utils/manifest.py).

Both engines are bit-parity-tested against each other (tests/test_*.py,
bench parity gates), so routing is purely a performance decision.
"""

from __future__ import annotations

import time

from .base import Backend
from ..observability import record_degradation
from ..resilience import fault_point, is_device_loss
from ..utils.logging import get_logger

log = get_logger("backend.auto")

# Bootstrap priors (estimated host seconds per relevant row, from the
# round-4 measured suite at ~1M builds).  Only the FIRST call per RQ can
# be routed by these; measurements replace them immediately after.
_PRIOR_HOST_COEF = {
    "rq1": 2e-8,
    "rq2cp": 2.5e-6,
    "rq2tr": 8e-7,
    "rq3": 1.1e-6,
    "rq4a": 2e-8,
    "rq4b": 3e-7,
    "suite": 4.5e-6,   # six host RQs over the shared tables
}
# Unobserved-device prior: one fused dispatch + one fetch + margin, in
# link round-trips.  Replaced by the measured device wall after one call.
_RTT_MULTIPLE = 4.0
# EWMA weight of the newest observation — heavy enough to adapt within a
# couple of calls, light enough that one noisy wall doesn't flap routing.
_EWMA_ALPHA = 0.5
# Exploration band: an engine that has never been measured on this
# machine is tried once as long as its bootstrap prior is within this
# factor of the measured incumbent.  Without it the router starves the
# unmeasured engine forever: BENCH_r05's rq2tr stuck on the measured
# host (0.31 s/call) while the never-tried device runs it in 0.14 s,
# because the device prior (4 RTTs ≈ 0.5 s) always lost the argmin.
_EXPLORE_FACTOR = 5.0

# Which study tables set each RQ's "relevant rows" scale.
_RQ_TABLES = {
    "rq1": ("fuzz",),
    "rq2cp": ("covb",),
    "rq2tr": ("cov",),
    "rq3": ("fuzz", "covb", "cov"),
    "rq4a": ("fuzz",),
    "rq4b": ("cov",),
    "suite": ("fuzz", "covb", "cov"),
}


class AutoBackend(Backend):
    """Routes each RQ call to the engine measured to win on this machine.

    ``rtt_s`` is the measured device dispatch round-trip
    (`backend._dispatch_rtt_s`); both engines are constructed lazily and
    share the device backend's per-study cache."""

    name = "auto"

    def __init__(self, rtt_s: float, cal_path: str | None = None):
        self._rtt_s = float(rtt_s)
        self._jax = None
        self._pd = None
        # (rq, engine) -> EWMA of observed seconds per relevant row.  The
        # device observation folds its fixed round-trip into the per-row
        # cost at the observed scale — accurate while call sizes are
        # stable (the normal analysis pattern), re-measured when not.
        self._cost: dict = {}
        self._dev_compiled: set = set()  # rqs whose device path is warm
        # Record-and-reuse (the BENCH_r05 mispick fix, second half): with
        # ``cal_path`` set, measured per-row costs persist through the
        # shared machine-calibration file (utils/calibration.py — schema-
        # versioned, per-entry TTL) and seed the next process on the SAME
        # machine — a fresh bench or CLI run routes on last round's
        # measurements instead of re-paying the bootstrap priors'
        # mistakes.  The TTL is what fixes the time-of-day drift: a
        # midnight link measurement cannot route the afternoon.
        self._cal_path = cal_path or None
        # Device-loss failover ledger: after repeated device failures the
        # router stops picking the jax engine mid-run and the host oracle
        # carries the remaining RQs (both engines are parity-tested, so
        # this degrades speed, never results).
        self._device_failures = 0
        self._device_lost = False
        self._load_calibration()

    def _load_calibration(self) -> None:
        if not self._cal_path:
            return
        from ..utils.calibration import load_calibration

        saved = load_calibration(self._cal_path)["cost_per_row"]
        for key, cost in saved.items():
            rq, _, eng = key.partition(":")
            if rq in _PRIOR_HOST_COEF and eng in ("jax", "pandas"):
                self._cost[(rq, eng)] = float(cost)
        if self._cost:
            log.info("router calibration reloaded from %s (%d entries)",
                     self._cal_path, len(self._cost))

    def _save_calibration(self) -> None:
        if not self._cal_path:
            return
        from ..utils.calibration import update_calibration

        update_calibration(
            self._cal_path,
            cost_per_row=self.calibration()["cost_per_row"])

    def _jax_be(self) -> Backend:
        if self._jax is None:
            from .jax_backend import JaxBackend

            self._jax = JaxBackend()
        return self._jax

    def _pd_be(self) -> Backend:
        if self._pd is None:
            from .pandas_backend import PandasBackend

            self._pd = PandasBackend()
        return self._pd

    def _predict(self, rq: str, engine: str, rows: int) -> float:
        c = self._cost.get((rq, engine))
        if c is not None:
            return max(rows, 1) * c
        if engine == "pandas":
            return max(rows, 1) * _PRIOR_HOST_COEF[rq]
        return _RTT_MULTIPLE * self._rtt_s

    def _pick(self, rq: str, rows: int) -> tuple:
        if self._device_lost:
            return "pandas", self._pd_be()
        pj = self._predict(rq, "jax", rows)
        pp = self._predict(rq, "pandas", rows)
        mj = (rq, "jax") in self._cost
        mp = (rq, "pandas") in self._cost
        if mj != mp:
            # One engine is measured, the other still runs on a bootstrap
            # prior; priors lose to measurements by default, so force one
            # trial of the unmeasured engine unless its prior already
            # loses hopelessly (> _EXPLORE_FACTOR× the incumbent).  Regret
            # is bounded at one mispredicted call per (rq, engine); the
            # measurement it buys fixes routing for the rest of the run
            # (and, via cal_path, for future runs).
            name, prior, incumbent = (("jax", pj, pp) if not mj
                                      else ("pandas", pp, pj))
            if prior <= _EXPLORE_FACTOR * incumbent:
                return name, (self._jax_be() if name == "jax"
                              else self._pd_be())
        if pj < pp:
            return "jax", self._jax_be()
        return "pandas", self._pd_be()

    def _observe(self, rq: str, engine: str, rows: int,
                 wall_s: float) -> None:
        key = (rq, engine)
        c = wall_s / max(rows, 1)
        prev = self._cost.get(key)
        self._cost[key] = (c if prev is None
                           else _EWMA_ALPHA * c + (1 - _EWMA_ALPHA) * prev)
        self._save_calibration()

    # Device failures tolerated before the router declares the device
    # lost and routes every remaining call to the host oracle.
    _DEVICE_FAIL_LIMIT = 2

    def _run(self, rq: str, arrays, method: str, *args, **kw):
        rows = self._rows(arrays, *_RQ_TABLES[rq])
        engine, be = self._pick(rq, rows)
        t0 = time.perf_counter()
        try:
            if engine == "jax":
                fault_point("backend.device.call")
            out = getattr(be, method)(arrays, *args, **kw)
        except Exception as e:
            if engine != "jax" or not is_device_loss(e):
                raise
            # TPU->CPU failover mid-run: the device (or its tunneled
            # link) died.  Re-run THIS call on the host oracle — results
            # are parity-tested identical — and after _DEVICE_FAIL_LIMIT
            # failures stop routing to the device at all.
            self._device_failures += 1
            record_degradation(
                "device_call_failover", site=f"backend.{rq}",
                detail={"error": f"{type(e).__name__}: {e}"[:200],
                        "failures": self._device_failures})
            log.warning("%s: device call failed (%s); re-running on the "
                        "host oracle", rq, e)
            if (self._device_failures >= self._DEVICE_FAIL_LIMIT
                    and not self._device_lost):
                self._device_lost = True
                record_degradation("device_failover", site="backend.auto",
                                   detail={"to": "pandas",
                                           "failures": self._device_failures})
                log.warning("device declared lost after %d failure(s); "
                            "routing all remaining RQs to the host oracle",
                            self._device_failures)
            return getattr(self._pd_be(), method)(arrays, *args, **kw)
        wall = time.perf_counter() - t0
        if engine == "jax" and rq not in self._dev_compiled:
            # First device call pays one-time jit compilation; recording
            # it would bias routing against the device for the whole run.
            self._dev_compiled.add(rq)
        else:
            self._observe(rq, engine, rows, wall)
        return out

    def calibration(self) -> dict:
        """Learned routing state, for the run manifest."""
        return {
            "dispatch_rtt_s": self._rtt_s,
            "cost_per_row": {f"{rq}:{eng}": cost
                             for (rq, eng), cost in sorted(self._cost.items())},
        }

    @staticmethod
    def _rows(arrays, *tables) -> int:
        return int(sum(len(getattr(arrays, t)) for t in tables))

    def rq1_detection(self, arrays, limit_date_ns, min_projects):
        return self._run("rq1", arrays, "rq1_detection", limit_date_ns,
                         min_projects)

    def rq2_change_points(self, arrays, limit_date_ns):
        return self._run("rq2cp", arrays, "rq2_change_points", limit_date_ns)

    def rq2_trends(self, arrays, limit_date_ns):
        return self._run("rq2tr", arrays, "rq2_trends", limit_date_ns)

    def rq3_coverage_at_detection(self, arrays, limit_date_ns):
        return self._run("rq3", arrays, "rq3_coverage_at_detection",
                         limit_date_ns)

    def rq4a_detection_trend(self, arrays, limit_date_ns, g1_idx, g2_idx,
                             min_projects):
        return self._run("rq4a", arrays, "rq4a_detection_trend",
                         limit_date_ns, g1_idx, g2_idx, min_projects)

    def rq4b_group_trends(self, arrays, limit_date_ns, g1_idx, g2_idx,
                          percentiles=(25, 50, 75)):
        return self._run("rq4b", arrays, "rq4b_group_trends", limit_date_ns,
                         g1_idx, g2_idx, percentiles)

    def rq_suite(self, arrays, limit_date_ns, min_projects, g1_idx, g2_idx,
                 percentiles=(25, 50, 75)):
        """Whole-suite routing: the device's fused one-dispatch suite
        (jax_backend.rq_suite) vs the host's six sequential calls, by the
        same measured-cost rule."""
        return self._run("suite", arrays, "rq_suite", limit_date_ns,
                         min_projects, g1_idx, g2_idx, percentiles)
