"""Per-RQ routing backend (the resolved form of ``backend = auto``).

Round-4 measurement on the 1M-build study (BENCH_r04): the best engine is
per-RQ, not global.  The host oracle wins the RQs whose pandas form is a
handful of vectorized array ops (rq1 18 ms, rq4a 13 ms), while the device
wins the ones whose host form walks per-project/per-group loops (rq2
change points 1.80 s -> 0.48 s, rq3 1.29 s -> 0.21 s) — even over a
tunneled PJRT link where every device call pays ~110 ms round-trip.  On
co-located TPU hardware (round-trip ~0.1-0.2 ms) the device wins
everything above a few thousand rows.

One rule covers both regimes: route an RQ to the device when its estimated
host cost exceeds a few link round-trips,

    rows * host_cost_per_row > _RTT_MULTIPLE * dispatch_rtt

with per-RQ cost coefficients fitted from the measured suite.  The two
engines are bit-parity-tested against each other (tests/test_*.py,
bench_rq_suite), so routing is a pure performance decision.
"""

from __future__ import annotations

import numpy as np

from .base import Backend
from ..utils.logging import get_logger

log = get_logger("backend.auto")

# Estimated host seconds per relevant row, fitted from BENCH_r04 at ~1M
# builds (713k coverage builds, 415k coverage days, 10k issues):
#   rq1   0.018 s / 1.0M fuzz rows      (vectorized searchsorted)
#   rq2cp 1.80 s  / 713k covb rows      (per-project group loop)
#   rq2tr 0.34 s  / 415k cov rows       (matrix build + scipy loops)
#   rq3   1.29 s  / 1.14M rows          (three per-issue scans)
#   rq4a  0.013 s / 1.0M fuzz rows      (vectorized)
#   rq4b  0.13 s  / 415k cov rows       (nanpercentile columns)
_COEF = {
    "rq1": 2e-8,
    "rq2cp": 2.5e-6,
    "rq2tr": 8e-7,
    "rq3": 1.1e-6,
    "rq4a": 2e-8,
    "rq4b": 3e-7,
}
# Device path must beat the host estimate by this many dispatch round-trips
# before it is chosen — one fused dispatch + one fetch + margin.
_RTT_MULTIPLE = 4.0


class AutoBackend(Backend):
    """Routes each RQ call to the engine predicted to win on this machine.

    ``rtt_s`` is the measured device dispatch round-trip
    (`backend._dispatch_rtt_s`); both engines are constructed lazily and
    share the device backend's per-study cache."""

    name = "auto"

    def __init__(self, rtt_s: float):
        self._rtt_s = float(rtt_s)
        self._jax = None
        self._pd = None

    def _engine(self, key: str, rows: int) -> Backend:
        use_jax = rows * _COEF[key] > _RTT_MULTIPLE * self._rtt_s
        if use_jax:
            if self._jax is None:
                from .jax_backend import JaxBackend

                self._jax = JaxBackend()
            return self._jax
        if self._pd is None:
            from .pandas_backend import PandasBackend

            self._pd = PandasBackend()
        return self._pd

    @staticmethod
    def _rows(arrays, *tables) -> int:
        return int(sum(len(getattr(arrays, t)) for t in tables))

    def rq1_detection(self, arrays, limit_date_ns, min_projects):
        be = self._engine("rq1", self._rows(arrays, "fuzz"))
        return be.rq1_detection(arrays, limit_date_ns, min_projects)

    def rq2_change_points(self, arrays, limit_date_ns):
        be = self._engine("rq2cp", self._rows(arrays, "covb"))
        return be.rq2_change_points(arrays, limit_date_ns)

    def rq2_trends(self, arrays, limit_date_ns):
        be = self._engine("rq2tr", self._rows(arrays, "cov"))
        return be.rq2_trends(arrays, limit_date_ns)

    def rq3_coverage_at_detection(self, arrays, limit_date_ns):
        be = self._engine("rq3", self._rows(arrays, "fuzz", "covb", "cov"))
        return be.rq3_coverage_at_detection(arrays, limit_date_ns)

    def rq4a_detection_trend(self, arrays, limit_date_ns, g1_idx, g2_idx,
                             min_projects):
        be = self._engine("rq4a", self._rows(arrays, "fuzz"))
        return be.rq4a_detection_trend(arrays, limit_date_ns, g1_idx,
                                       g2_idx, min_projects)

    def rq4b_group_trends(self, arrays, limit_date_ns, g1_idx, g2_idx,
                          percentiles=(25, 50, 75)):
        be = self._engine("rq4b", self._rows(arrays, "cov"))
        return be.rq4b_group_trends(arrays, limit_date_ns, g1_idx, g2_idx,
                                    percentiles)
