"""Backend primitive interface + shared result types.

Each primitive corresponds to a hot loop in the reference (SURVEY.md §3);
both engines must agree exactly (parity-tested on fixtures).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass

import numpy as np

from ..data.columnar import StudyArrays


@dataclass
class RQ1Result:
    """Per-iteration detection stats (rq1_detection_rate.py:189-268).

    iterations: retained 1-based iteration numbers (>= min-projects filter),
    ascending; total_projects / detected_counts align with it.
    iteration_of_issue: for every fixed issue row in arrays.issues, the
    number of fuzzing builds strictly before its report time.
    link_idx: index into arrays.fuzz rows of the latest *successful* build
    strictly before the report (and before the study cutoff), -1 if none —
    the SAME_DATE_BUILD_ISSUE join (queries1.py:15-58).
    """

    iterations: np.ndarray
    total_projects: np.ndarray
    detected_counts: np.ndarray
    iteration_of_issue: np.ndarray
    link_idx: np.ndarray

    @property
    def detection_rates(self) -> np.ndarray:
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(self.total_projects > 0,
                            self.detected_counts / self.total_projects * 100.0, 0.0)

    @property
    def linked(self) -> np.ndarray:
        return self.link_idx >= 0


class Backend(abc.ABC):
    name: str

    @abc.abstractmethod
    def rq1_detection(self, arrays: StudyArrays, limit_date_ns: int,
                      min_projects: int) -> RQ1Result:
        ...
