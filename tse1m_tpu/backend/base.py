"""Backend primitive interface + shared result types.

Each primitive corresponds to a hot loop in the reference (SURVEY.md §3);
both engines must agree exactly (parity-tested on fixtures).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass

import numpy as np

from ..data.columnar import StudyArrays


@dataclass
class RQ1Result:
    """Per-iteration detection stats (rq1_detection_rate.py:189-268).

    iterations: retained 1-based iteration numbers (>= min-projects filter),
    ascending; total_projects / detected_counts align with it.
    iteration_of_issue: for every fixed issue row in arrays.issues, the
    number of fuzzing builds strictly before its report time.
    link_idx: index into arrays.fuzz rows of the latest *successful* build
    strictly before the report (and before the study cutoff), -1 if none —
    the SAME_DATE_BUILD_ISSUE join (queries1.py:15-58).
    """

    iterations: np.ndarray
    total_projects: np.ndarray
    detected_counts: np.ndarray
    iteration_of_issue: np.ndarray
    link_idx: np.ndarray

    @property
    def detection_rates(self) -> np.ndarray:
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(self.total_projects > 0,
                            self.detected_counts / self.total_projects * 100.0, 0.0)

    @property
    def linked(self) -> np.ndarray:
        return self.link_idx >= 0


@dataclass
class RQ2ChangePointsResult:
    """Revision change points per project (rq2_coverage_and_added.py:126-219).

    Flat arrays over all change points, project-major in covb row order.
    end_i / start_ip1 index into arrays.covb rows: the last build of group i
    and the first build of group i+1.  covered/total are the same-day
    total_coverage rows (NaN where no date match); diffs are NaN unless both
    sides are valid with non-zero total (reference rq2:189-200).
    """

    project_idx: np.ndarray
    end_i: np.ndarray
    start_ip1: np.ndarray
    covered_i: np.ndarray
    total_i: np.ndarray
    covered_ip1: np.ndarray
    total_ip1: np.ndarray

    def _valid(self):
        vi = ~np.isnan(self.total_i) & (self.total_i != 0)
        vp = ~np.isnan(self.total_ip1) & (self.total_ip1 != 0)
        return vi, vp

    @property
    def diff_total_line(self) -> np.ndarray:
        vi, vp = self._valid()
        return np.where(vi & vp, self.total_ip1 - self.total_i, np.nan)

    @property
    def diff_coverage(self) -> np.ndarray:
        vi, vp = self._valid()
        with np.errstate(invalid="ignore", divide="ignore"):
            ci = np.where(vi, self.covered_i / self.total_i * 100.0, np.nan)
            cp = np.where(vp, self.covered_ip1 / self.total_ip1 * 100.0, np.nan)
        return np.where(vi & vp, cp - ci, np.nan)


@dataclass
class RQ2TrendsResult:
    """Per-project coverage%-vs-session trends (rq2_coverage_count.py).

    matrix: [P, S] coverage% padded with NaN (S = longest trend); mask marks
    valid cells.  Trends keep the reference's skip-zero-total rule
    (rq2:300-303): sessions with total_line == 0 are dropped, then the rest
    are re-indexed densely.  spearman aligns with arrays.projects;
    percentiles rows follow PCTS; mean/counts are per session index.
    """

    PCTS = (5, 25, 50, 75, 95)

    matrix: np.ndarray
    mask: np.ndarray
    spearman: np.ndarray
    percentiles: np.ndarray  # [len(PCTS), S]
    mean: np.ndarray         # [S]
    counts: np.ndarray       # [S]


@dataclass
class RQ3Result:
    """Coverage change at detection vs elsewhere
    (rq3_diff_coverage_at_detection.py:202-302).

    Detected rows: for each fixed issue that links to a fuzzing build, a
    nearby successful coverage build with identical revisions (<24h gap),
    and a day-after coverage report — the (prev, day-after) coverage delta.
    Non-detected rows: every other consecutive coverage-day pair of projects
    with >= 1 fixed issue, excluding pairs whose current date equals a
    detected issue's report date (the reference's exclusion key, rq3:249-251).
    det_issue_idx indexes into arrays.issues rows; *_project_idx into
    arrays.projects.
    """

    det_diff_percent: np.ndarray
    det_diff_covered: np.ndarray
    det_diff_total: np.ndarray
    det_project_idx: np.ndarray
    det_issue_idx: np.ndarray
    det_issue_time_ns: np.ndarray
    nondet_diff_percent: np.ndarray
    nondet_diff_covered: np.ndarray
    nondet_diff_total: np.ndarray
    nondet_project_idx: np.ndarray


@dataclass
class RQ4aTrendResult:
    """G1-vs-G2 detection-rate trend (rq4a_bug.py:302-346,156-207).

    Unlike RQ1, iteration totals count ALL fuzzing builds before the cutoff
    regardless of result (rq4a:128-134), and a project counts as detecting
    at iteration k when k = #builds strictly before a fixed issue's report
    time is > 0 — no successful-build linkage required (rq4a:343-346).
    iterations holds only rows where BOTH groups have >= min_projects
    (rq4a:170-177); per-group arrays align with it.
    """

    iterations: np.ndarray
    g1_total: np.ndarray
    g1_detected: np.ndarray
    g2_total: np.ndarray
    g2_detected: np.ndarray

    def rates(self, group: str) -> np.ndarray:
        tot = getattr(self, f"{group}_total")
        det = getattr(self, f"{group}_detected")
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(tot > 0, det / tot * 100.0, 0.0)


@dataclass
class RQ4bTrendsResult:
    """Per-session coverage% distributions for two corpus groups
    (rq4b_coverage.py:910-1015).

    Trends are the raw ``coverage`` column (non-null, > 0, pre-cutoff,
    rq4b:315-326) re-indexed densely per project — NOT covered/total like
    RQ2.  matrix/mask are [P, S] over ALL projects (S = longest trend);
    group percentile rows follow ``percentiles`` and counts are per-session
    group populations.
    """

    percentiles: tuple
    matrix: np.ndarray            # [P, S] float64, NaN-padded
    mask: np.ndarray              # [P, S] bool
    g1_percentiles: np.ndarray    # [K, S]
    g1_counts: np.ndarray         # [S]
    g2_percentiles: np.ndarray    # [K, S]
    g2_counts: np.ndarray         # [S]


class Backend(abc.ABC):
    name: str

    @abc.abstractmethod
    def rq1_detection(self, arrays: StudyArrays, limit_date_ns: int,
                      min_projects: int) -> RQ1Result:
        ...

    @abc.abstractmethod
    def rq2_change_points(self, arrays: StudyArrays,
                          limit_date_ns: int) -> RQ2ChangePointsResult:
        ...

    @abc.abstractmethod
    def rq2_trends(self, arrays: StudyArrays,
                   limit_date_ns: int) -> RQ2TrendsResult:
        ...

    @abc.abstractmethod
    def rq3_coverage_at_detection(self, arrays: StudyArrays,
                                  limit_date_ns: int) -> RQ3Result:
        ...

    @abc.abstractmethod
    def rq4a_detection_trend(self, arrays: StudyArrays, limit_date_ns: int,
                             g1_idx: np.ndarray, g2_idx: np.ndarray,
                             min_projects: int) -> RQ4aTrendResult:
        ...

    @abc.abstractmethod
    def rq4b_group_trends(self, arrays: StudyArrays, limit_date_ns: int,
                          g1_idx: np.ndarray, g2_idx: np.ndarray,
                          percentiles: tuple = (25, 50, 75)
                          ) -> RQ4bTrendsResult:
        ...

    def rq_suite(self, arrays: StudyArrays, limit_date_ns: int,
                 min_projects: int, g1_idx: np.ndarray, g2_idx: np.ndarray,
                 percentiles: tuple = (25, 50, 75)) -> dict:
        """All six RQs over one study: {'rq1', 'rq2cp', 'rq2tr', 'rq3',
        'rq4a', 'rq4b'} -> result objects.  Default: six sequential calls.
        The device backend overrides this with a single fused dispatch
        (jax_backend._rq_suite_kernel) so the whole suite costs one
        round-trip on a remote link."""
        return {
            "rq1": self.rq1_detection(arrays, limit_date_ns, min_projects),
            "rq2cp": self.rq2_change_points(arrays, limit_date_ns),
            "rq2tr": self.rq2_trends(arrays, limit_date_ns),
            "rq3": self.rq3_coverage_at_detection(arrays, limit_date_ns),
            "rq4a": self.rq4a_detection_trend(arrays, limit_date_ns,
                                              g1_idx, g2_idx, min_projects),
            "rq4b": self.rq4b_group_trends(arrays, limit_date_ns,
                                           g1_idx, g2_idx, percentiles),
        }
