"""The {pandas, jax_tpu} dispatcher (north star, BASELINE.json): analysis
scripts call :func:`get_backend` and receive the primitive set; which engine
answers is decided by ``program/envFile.ini`` / ``TSE1M_BACKEND``."""

from __future__ import annotations

from ..config import Config


def get_backend(cfg: Config):
    if cfg.backend == "jax_tpu":
        from .jax_backend import JaxBackend

        return JaxBackend()
    from .pandas_backend import PandasBackend

    return PandasBackend()


__all__ = ["get_backend"]
