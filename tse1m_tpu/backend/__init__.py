"""The {pandas, jax_tpu, auto} dispatcher (north star, BASELINE.json):
analysis scripts call :func:`get_backend` and receive the primitive set;
which engine answers is decided by ``program/envFile.ini`` /
``TSE1M_BACKEND``.

``auto`` resolves per machine: the device backend only pays when device
dispatch is local-class.  Over a tunneled/remote PJRT link every call
carries the network round-trip (~110 ms measured on this environment's
tunnel), which no amount of kernel fusion can hide for the millisecond-
scale RQ reductions of an extracted study — so auto picks the host oracle
there, and the TPU backend on co-located hardware (TPU VM / pod), where
the same fused kernels win.  The round-trip probe runs once per process.
"""

from __future__ import annotations

from ..config import Config
from ..utils.logging import get_logger

log = get_logger("backend")

# Local PCIe/ICI-attached dispatch round-trips are O(100us); anything
# slower than this is a remote link where the host oracle wins the
# ms-scale RQ calls (round-3/4 measurements: 0.1-0.2ms co-located,
# ~110ms tunneled).
_LOCAL_RTT_S = 0.005

_auto_choice: str | None = None


def _dispatch_rtt_s() -> float:
    """Median round-trip of a tiny jitted op + 4-byte fetch (the only
    honest sync over a tunnel — block_until_ready returns early there)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    f = jax.jit(lambda v: v + 1)
    v = jnp.zeros(8, jnp.int32)
    int(np.asarray(f(v))[0])  # compile + warm
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        int(np.asarray(f(v))[0])
        samples.append(time.perf_counter() - t0)
    return sorted(samples)[1]


def resolve_auto_backend() -> str:
    """'jax_tpu' when a TPU is attached with local-class dispatch latency,
    else 'pandas'.  Cached for the process lifetime."""
    global _auto_choice
    if _auto_choice is None:
        # auto is the shipped default, so it must never be the reason an
        # analysis run dies: any jax bring-up or probe failure (stale
        # libtpu, device held by another process) resolves to the host
        # engine that needs neither.
        try:
            import jax

            if jax.default_backend() != "tpu":
                _auto_choice = "pandas"
            else:
                rtt = _dispatch_rtt_s()
                _auto_choice = "jax_tpu" if rtt < _LOCAL_RTT_S else "pandas"
                log.info("backend=auto: TPU dispatch RTT %.1f ms -> %s",
                         rtt * 1e3, _auto_choice)
        except Exception as e:
            log.warning("backend=auto: device probe failed (%s: %s); "
                        "using pandas", type(e).__name__, e)
            _auto_choice = "pandas"
    return _auto_choice


def get_backend(cfg: Config):
    choice = cfg.backend
    if choice == "auto":
        choice = resolve_auto_backend()
    if choice == "jax_tpu":
        from .jax_backend import JaxBackend

        return JaxBackend()
    from .pandas_backend import PandasBackend

    return PandasBackend()


__all__ = ["get_backend", "resolve_auto_backend"]
