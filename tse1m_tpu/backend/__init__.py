"""The {pandas, jax_tpu, auto} dispatcher (north star, BASELINE.json):
analysis scripts call :func:`get_backend` and receive the primitive set;
which engine answers is decided by ``program/envFile.ini`` /
``TSE1M_BACKEND``.

``auto`` (the shipped default) resolves to a per-RQ router
(`auto.AutoBackend`): each RQ call goes to the engine predicted to win on
this machine, using the measured device dispatch round-trip and per-RQ
host-cost estimates.  Off-TPU it is simply the host oracle.  Round-4
measurement behind this: on a tunneled PJRT link (~110 ms round-trip) the
device still wins the loop-heavy RQs at the 1M-build scale (rq2 change
points 1.80 s -> 0.48 s, rq3 1.29 s -> 0.21 s) while the host wins the
vectorized ones (rq1 18 ms) — so neither pure engine is the right default.
The round-trip probe runs once per process.
"""

from __future__ import annotations

from ..config import Config
from ..utils.logging import get_logger

log = get_logger("backend")


def _dispatch_rtt_s() -> float:
    """Median round-trip of a tiny jitted op + 4-byte fetch (the only
    honest sync over a tunnel — block_until_ready returns early there)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    f = jax.jit(lambda v: v + 1)
    v = jnp.zeros(8, jnp.int32)
    int(np.asarray(f(v))[0])  # compile + warm
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        int(np.asarray(f(v))[0])
        samples.append(time.perf_counter() - t0)
    return sorted(samples)[1]


_auto_rtt_s: float | None = None


def _probed_rtt_s() -> float | None:
    """Cached per-process dispatch round-trip on TPU; None when the device
    probe is unavailable (no TPU, or bring-up failed)."""
    global _auto_rtt_s
    if _auto_rtt_s is None:
        # auto is the shipped default, so it must never be the reason an
        # analysis run dies: any jax bring-up or probe failure (stale
        # libtpu, device held by another process) resolves to the host
        # engine that needs neither.
        try:
            import jax

            if jax.default_backend() != "tpu":
                _auto_rtt_s = -1.0
            else:
                _auto_rtt_s = _dispatch_rtt_s()
                log.info("backend=auto: TPU dispatch RTT %.1f ms "
                         "(per-RQ routing active)", _auto_rtt_s * 1e3)
        except Exception as e:
            from ..resilience import reraise_if_fault

            reraise_if_fault(e)  # a game-day fault here must not be
            #                      misread as "no TPU available"
            log.warning("backend=auto: device probe failed (%s: %s); "
                        "using pandas", type(e).__name__, e)
            _auto_rtt_s = -1.0
    return None if _auto_rtt_s < 0 else _auto_rtt_s


def get_backend(cfg: Config):
    choice = cfg.backend
    if choice == "auto":
        rtt = _probed_rtt_s()
        if rtt is None:
            from .pandas_backend import PandasBackend

            return PandasBackend()
        import os

        from .auto import AutoBackend

        # Record-and-reuse: measured per-RQ walls persist here and seed
        # the next process's routing (TSE1M_ROUTER_CAL env or the INI's
        # router_cal_path; empty/unset = in-memory only).  Env is read
        # here, not only in load_config, because bench.py constructs
        # Config() directly.
        cal = os.environ.get("TSE1M_ROUTER_CAL",
                             getattr(cfg, "router_cal_path", None) or "")
        return AutoBackend(rtt, cal_path=cal or None)
    if choice == "jax_tpu":
        from .jax_backend import JaxBackend

        return JaxBackend()
    from .pandas_backend import PandasBackend

    return PandasBackend()


__all__ = ["get_backend"]
