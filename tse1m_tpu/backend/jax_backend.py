"""Device (jax_tpu) backend.

The reference's two RQ1 hot loops — 10m51s + 19m29s on the author's laptop
(rq1_detection_rate.py:361,367) — become one jitted kernel: a CSR binary
search for issue->iteration indexing and linkage, a bincount survival curve
for per-iteration populations, and a boolean scatter for unique detected
projects.  Timestamps ride as two int32 lanes (seconds, ns remainder) so
sub-second ordering matches the host backend exactly without enabling x64.

Dispatch economics (single device): the study's CSR arrays are uploaded to
the device ONCE per (StudyArrays, limit_date) and cached on the StudyArrays
instance (`_study_cache`), and each RQ runs as ONE fused jit call returning
ONE packed result buffer — so an RQ call costs one dispatch round-trip and
one device->host fetch instead of re-staging ~30 MB of host arrays per call
(the round-3 profile: 0.75 s/call re-upload vs ~0.11 s link round-trip
floor on a tunneled PJRT backend).
"""
# graftlint: disable-file=wire-layer -- the per-study device cache IS this plane's transfer seat: arrays stage once per (study, cutoff) and reuse is pinned by tests/test_device_cache.py under the transfer guard

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .base import (Backend, RQ1Result, RQ2ChangePointsResult, RQ2TrendsResult,
                   RQ3Result, RQ4aTrendResult, RQ4bTrendsResult)
from .pandas_backend import DAY_NS, HOUR_NS, floor_day_ns
from ..data.columnar import StudyArrays, ns_to_device_pair
from ..ops.segment import (counts_to_survival, masked_mean, masked_spearman,
                           segment_searchsorted,
                           unique_pairs_count_per_iteration)
from ..parallel import rq_mesh


def masked_csr(offsets: np.ndarray, mask: np.ndarray):
    """Filter a CSR view by a row mask: returns (original row indices of the
    kept rows, new per-segment offsets).  Robust to empty segments — offsets
    are boundary values of the running kept-row count."""
    pos = np.flatnonzero(mask)
    running = np.concatenate([[0], np.cumsum(mask.astype(np.int64))])
    return pos, running[offsets]


# ---------------------------------------------------------------------------
# Device-resident study cache
# ---------------------------------------------------------------------------

def _study_cache(arrays: StudyArrays) -> dict:
    """The per-StudyArrays device cache.

    Stored on the StudyArrays instance (immutable after construction): all
    six RQ kernels share the same value-side CSR arrays, so the H2D staging
    happens once per study instead of once per RQ call.  Cutoff-dependent
    entries (the masked CSR views) carry the limit in their key, so a
    cutoff sweep re-derives only those while the big cutoff-independent
    lanes (full fuzz times, issues, valid-coverage rows) stay resident."""
    fp = tuple(_table_token(t) for t in
               (arrays.fuzz, arrays.covb, arrays.issues, arrays.cov))
    cache = getattr(arrays, "_jax_dev_cache", None)
    if cache is None or cache.get("fp") != fp:
        # fp guards shallow copies that swap a table out (and with it the
        # case of two StudyArrays sharing one cache attribute object).
        cache = {"fp": fp}
        arrays._jax_dev_cache = cache
    return cache


_table_tokens = iter(range(1 << 62))


def _table_token(table) -> int:
    """Monotonic identity token per Segmented (set on first use).  Unlike
    id(), tokens are never reused after an object dies, so a freed table
    whose address is recycled can't alias a cache entry."""
    tok = getattr(table, "_cache_token", None)
    if tok is None:
        tok = table._cache_token = next(_table_tokens)
    return tok


def _cached(cache: dict, key: str, build):
    if key not in cache:
        cache[key] = build()
    return cache[key]


# How many distinct cutoffs keep their masked device views resident.  A
# sweep touches cutoffs mostly in sequence; beyond this the oldest cutoff's
# entries are dropped so HBM use stays bounded (the cutoff-independent lanes
# are never evicted).
_MAX_CUTOFFS = 2


def _touch_limit(cache: dict, limit_date_ns: int) -> None:
    """Record cutoff use order and evict the oldest cutoff's `...:{limit}`
    entries once more than _MAX_CUTOFFS are resident."""
    limits = cache.setdefault("_limits", [])
    if limit_date_ns in limits:
        limits.remove(limit_date_ns)
    limits.append(limit_date_ns)
    while len(limits) > _MAX_CUTOFFS:
        old = limits.pop(0)
        suffix = f":{old}"
        for k in [k for k in cache if k.endswith(suffix)]:
            del cache[k]


def _dev_fuzz(arrays: StudyArrays, cache: dict):
    """(fs_d, fns_d, foff32_d): full fuzz two-lane times, device-resident."""
    def build():
        fs, fns = ns_to_device_pair(arrays.fuzz.columns["time_ns"])
        return (jax.device_put(fs), jax.device_put(fns),
                jax.device_put(arrays.fuzz.offsets.astype(np.int32)))
    return _cached(cache, "fuzz", build)


def _host_fuzz_ok(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    """Host (pos, offsets) of the ok & pre-cutoff fuzz CSR — shared by RQ1's
    linkage side and RQ3's last-successful-build scan (rq3:269)."""
    def build():
        t = arrays.fuzz.columns["time_ns"]
        return masked_csr(arrays.fuzz.offsets,
                          arrays.fuzz.columns["ok"] & (t < limit_date_ns))
    return _cached(cache, f"fuzz_ok_host:{limit_date_ns}", build)


def _dev_fuzz_ok(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    """(oks_d, okns_d, okoff32_d, okpos32_d): device CSR of ok pre-cutoff
    fuzz builds.  Times are gathered ON DEVICE from the cached full-fuzz
    lanes, so only the ~4 B/row position index crosses the link."""
    def build():
        pos, off = _host_fuzz_ok(arrays, cache, limit_date_ns)
        fs_d, fns_d, _ = _dev_fuzz(arrays, cache)
        pos_d = jax.device_put(pos.astype(np.int32))
        return (jnp.take(fs_d, pos_d), jnp.take(fns_d, pos_d),
                jax.device_put(off.astype(np.int32)), pos_d)
    return _cached(cache, f"fuzz_ok:{limit_date_ns}", build)


def _dev_issues(arrays: StudyArrays, cache: dict):
    """(is_d, ins_d, seg32_d): issue report times and their project
    segments (the query side of every RQ searchsorted)."""
    def build():
        seg = np.repeat(np.arange(arrays.n_projects),
                        arrays.issues.counts()).astype(np.int32)
        is_, ins = ns_to_device_pair(arrays.issues.columns["time_ns"])
        return (jax.device_put(is_), jax.device_put(ins),
                jax.device_put(seg))
    return _cached(cache, "issues", build)


def _host_covb_cut(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    """Host (pos, offsets) of coverage builds before cutoff+1 day (RQ3's
    first-coverage-build scan fetches to the boundary day, rq3:263)."""
    def build():
        t = arrays.covb.columns["time_ns"]
        return masked_csr(arrays.covb.offsets, t < limit_date_ns + DAY_NS)
    return _cached(cache, f"covb_cut_host:{limit_date_ns}", build)


def _dev_covb_cut(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    def build():
        pos, off = _host_covb_cut(arrays, cache, limit_date_ns)
        cts, ctn = ns_to_device_pair(arrays.covb.columns["time_ns"][pos])
        return (jax.device_put(cts), jax.device_put(ctn),
                jax.device_put(off.astype(np.int32)))
    return _cached(cache, f"covb_cut:{limit_date_ns}", build)


def _host_cov_valid(arrays: StudyArrays, cache: dict):
    """Host (pos, offsets) of non-null daily-coverage rows (RQ3's day-after
    join side, rq3:287-293)."""
    def build():
        return masked_csr(arrays.cov.offsets,
                          ~np.isnan(arrays.cov.columns["covered"]))
    return _cached(cache, "cov_valid_host", build)


def _dev_cov_valid(arrays: StudyArrays, cache: dict):
    def build():
        pos, off = _host_cov_valid(arrays, cache)
        dts, dtn = ns_to_device_pair(arrays.cov.columns["date_ns"][pos])
        return (jax.device_put(dts), jax.device_put(dtn),
                jax.device_put(off.astype(np.int32)))
    return _cached(cache, "cov_valid", build)


def _host_cov_cut(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    """Host (pos, offsets) of pre-cutoff daily-coverage rows (RQ2's same-day
    join side; dates ascend per segment so the mask keeps a prefix)."""
    def build():
        return masked_csr(arrays.cov.offsets,
                          arrays.cov.columns["date_ns"] < limit_date_ns)
    return _cached(cache, f"cov_cut_host:{limit_date_ns}", build)


def _dev_cov_cut(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    def build():
        pos, off = _host_cov_cut(arrays, cache, limit_date_ns)
        ds, dns = ns_to_device_pair(arrays.cov.columns["date_ns"][pos])
        return (jax.device_put(ds), jax.device_put(dns),
                jax.device_put(off.astype(np.int32)))
    return _cached(cache, f"cov_cut:{limit_date_ns}", build)


def _host_fuzz_cut(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    """Host (pos, offsets) of ALL pre-cutoff fuzz builds regardless of
    result — RQ4a counts every build (rq4a_bug.py:128-134)."""
    def build():
        t = arrays.fuzz.columns["time_ns"]
        return masked_csr(arrays.fuzz.offsets, t < limit_date_ns)
    return _cached(cache, f"fuzz_cut_host:{limit_date_ns}", build)


def _dev_fuzz_cut(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    def build():
        pos, off = _host_fuzz_cut(arrays, cache, limit_date_ns)
        fs_d, fns_d, _ = _dev_fuzz(arrays, cache)
        pos_d = jax.device_put(pos.astype(np.int32))
        return (jnp.take(fs_d, pos_d), jnp.take(fns_d, pos_d),
                jax.device_put(off.astype(np.int32)))
    return _cached(cache, f"fuzz_cut:{limit_date_ns}", build)


def _dev_rq3_targets(arrays: StudyArrays, cache: dict):
    """(qts_d, qtn_d): day-after-report midnights, the RQ3 day join key."""
    def build():
        target = floor_day_ns(arrays.issues.columns["time_ns"]) + DAY_NS
        qts, qtn = ns_to_device_pair(target)
        return jax.device_put(qts), jax.device_put(qtn)
    return _cached(cache, "rq3_targets", build)


def _rq2cp_bounds(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    """Host group-boundary structure for RQ2 change points (the reference's
    shift/cumsum grouping, rq2_coverage_and_added.py:129-149) + the staged
    device query lanes for the date join.  Deterministic per (study,
    cutoff), so cached like the CSR views — this is the dominant host cost
    of an rq2cp call (~0.25 s at the 1M bench scale)."""
    def build():
        covb_t = arrays.covb.columns["time_ns"]
        ghash = arrays.covb.columns["grouphash"]
        seg_all = np.repeat(np.arange(arrays.n_projects),
                            arrays.covb.counts())
        _, cov_offsets = _host_cov_cut(arrays, cache, limit_date_ns)
        has_cov = np.diff(cov_offsets) > 0
        keep = ((covb_t < limit_date_ns) & arrays.covb.columns["ok"]
                & has_cov[seg_all])
        rows = np.flatnonzero(keep)
        if rows.size == 0:
            return None
        seg = seg_all[rows]
        g = ghash[rows]
        new_group = np.concatenate(
            [[True], (g[1:] != g[:-1]) | (seg[1:] != seg[:-1])])
        start_pos = np.flatnonzero(new_group)
        starts = rows[start_pos]
        ends = rows[np.concatenate([start_pos[1:] - 1, [rows.size - 1]])]
        gseg = seg[start_pos]
        pair = np.flatnonzero(gseg[:-1] == gseg[1:])
        end_i = ends[pair]
        start_ip1 = starts[pair + 1]
        proj = gseg[pair]
        if end_i.size == 0:
            return None
        q_days = np.concatenate([floor_day_ns(covb_t[end_i]),
                                 floor_day_ns(covb_t[start_ip1])])
        q_seg = np.concatenate([proj, proj]).astype(np.int32)
        qs, qns = ns_to_device_pair(q_days)
        return {"end_i": end_i, "start_ip1": start_ip1, "proj": proj,
                "q_days": q_days, "q_seg": q_seg,
                "qs_d": jax.device_put(qs), "qns_d": jax.device_put(qns),
                "qseg_d": jax.device_put(q_seg)}
    return _cached(cache, f"rq2cp_bounds:{limit_date_ns}", build)


def _trend_matrix(arrays: StudyArrays, sel: np.ndarray,
                  values: np.ndarray):
    """Scatter selected coverage rows into a padded [P, S] matrix + mask
    (the reference's ragged coverage_by_session_index aggregation,
    rq2_coverage_count.py:330-333)."""
    P = arrays.n_projects
    seg_all = np.repeat(np.arange(P), arrays.cov.counts())
    lens = np.bincount(seg_all[sel], minlength=P)
    S = int(lens.max()) if lens.size else 0
    matrix = np.full((P, S), np.nan)
    mask = np.zeros((P, S), dtype=bool)
    if S:
        kept_seg = seg_all[sel]
        pos_in_proj = np.arange(int(sel.sum())) - np.repeat(
            np.concatenate([[0], np.cumsum(lens)[:-1]]), lens)
        matrix[kept_seg, pos_in_proj] = values[sel]
        mask[kept_seg, pos_in_proj] = True
    return matrix, mask


def _rq2tr_prep(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    """RQ2-trends host+device prep, cached per (study, cutoff): the padded
    trend matrix, its device copies, and the percentile order-statistic
    index plan (lo/hi/frac) the fused kernel consumes."""
    def build():
        P = arrays.n_projects
        cov = arrays.cov
        coverage = cov.columns["coverage"]
        covered = cov.columns["covered"]
        total = cov.columns["total"]
        sel = ((~np.isnan(coverage)) & (coverage != 0) & (total != 0)
               & ~np.isnan(total) & ~np.isnan(covered)
               & (cov.columns["date_ns"] < limit_date_ns))
        with np.errstate(invalid="ignore", divide="ignore"):
            vals = covered / total * 100.0
        matrix, mask = _trend_matrix(arrays, sel, vals)
        S = matrix.shape[1]
        q = np.array(RQ2TrendsResult.PCTS, dtype=np.float32)
        n_valid = mask.sum(axis=0).astype(np.int32)
        pos = (n_valid.astype(np.float32) - np.float32(1.0)) \
            * q[:, None] / np.float32(100.0)
        lo = np.clip(np.floor(pos).astype(np.int32), 0, max(P - 1, 0))
        hi = np.clip(lo + 1, 0, max(P - 1, 0))
        frac = pos - lo.astype(np.float32)
        return {"matrix": matrix, "mask": mask, "n_valid": n_valid,
                "lo": lo, "hi": hi, "frac": frac, "S": S}
    return _cached(cache, f"rq2tr_prep:{limit_date_ns}", build)


def _rq2tr_dev(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    """Device copies of the trend matrix + index plan — built only on the
    single-device path (the mesh kernels consume the host matrix), so a
    mesh run never ships these [P, S] lanes over the link."""
    def build():
        prep = _rq2tr_prep(arrays, cache, limit_date_ns)
        return (jax.device_put(prep["matrix"].astype(np.float32)),
                jax.device_put(prep["mask"]),
                jax.device_put(prep["lo"]), jax.device_put(prep["hi"]))
    return _cached(cache, f"rq2tr_dev:{limit_date_ns}", build)


def _rq4b_matrix(arrays: StudyArrays, cache: dict, limit_date_ns: int):
    """RQ4b's padded coverage matrix, cached per (study, cutoff) — the
    scatter is identical across g1/g2 calls; only the float64 group
    percentile reductions (host) depend on the group split."""
    def build():
        cov = arrays.cov
        coverage = cov.columns["coverage"]
        sel = ((~np.isnan(coverage)) & (coverage > 0)
               & (cov.columns["date_ns"] < limit_date_ns))
        return _trend_matrix(arrays, sel, coverage)
    return _cached(cache, f"rq4b_matrix:{limit_date_ns}", build)


# ---------------------------------------------------------------------------
# Fused kernels (one dispatch + one packed D2H fetch per RQ call)
# ---------------------------------------------------------------------------

_seg_searchsorted_jit = jax.jit(segment_searchsorted,
                                static_argnames=("side",))


def _rq1_body(fuzz_s, fuzz_ns, fuzz_offsets, ok_s, ok_ns, ok_offsets,
              ok_orig_idx, issue_s, issue_ns, issue_seg,
              n_projects: int, max_iter: int):
    # Iteration of each issue: #builds (any result) strictly before rts.
    iteration_of_issue = segment_searchsorted(
        fuzz_s, fuzz_offsets, issue_s, issue_seg, side="left",
        values_lo=fuzz_ns, queries_lo=issue_ns)

    # Linkage: latest successful pre-cutoff build strictly before rts.
    pos = segment_searchsorted(ok_s, ok_offsets, issue_s, issue_seg, side="left",
                               values_lo=ok_ns, queries_lo=issue_ns)
    has_link = pos > 0
    if ok_orig_idx.shape[0]:
        gather = jnp.clip(ok_offsets[issue_seg] + pos - 1, 0, ok_orig_idx.shape[0] - 1)
        link_idx = jnp.where(has_link, ok_orig_idx[gather], -1)
    else:
        link_idx = jnp.full(issue_seg.shape, -1, dtype=jnp.int32)

    counts = fuzz_offsets[1:] - fuzz_offsets[:-1]
    totals = counts_to_survival(counts, max_iter)

    det_iter = jnp.where(has_link, iteration_of_issue, 0)
    detected = unique_pairs_count_per_iteration(issue_seg, det_iter,
                                                n_projects, max_iter)
    return iteration_of_issue, link_idx, totals, detected


@partial(jax.jit, static_argnames=("n_projects", "max_iter"))
def _rq1_kernel_packed(fuzz_s, fuzz_ns, fuzz_offsets, ok_s, ok_ns, ok_offsets,
                       ok_orig_idx, issue_s, issue_ns, issue_seg,
                       n_projects: int, max_iter: int):
    """`_rq1_body` with the four outputs packed into ONE int32 vector
    [it(Q), link(Q), totals(max_iter), detected(max_iter)] so the whole RQ
    costs a single device->host fetch."""
    it, li, totals, detected = _rq1_body(
        fuzz_s, fuzz_ns, fuzz_offsets, ok_s, ok_ns, ok_offsets, ok_orig_idx,
        issue_s, issue_ns, issue_seg, n_projects, max_iter)
    return jnp.concatenate([it.astype(jnp.int32), li.astype(jnp.int32),
                            totals, detected])


def _rq3_body(fts, ftn, f_off, cts, ctn, c_off, dts, dtn, v_off,
              is_, ins, seg, qts, qtn):
    """RQ3's three per-issue linear scans (rq3:269,273,287-293) as one fused
    dispatch: last ok fuzz build before rts, first coverage build after rts,
    and the day-after coverage row — stacked [3, Q] for a single fetch."""
    pos_f = segment_searchsorted(fts, f_off, is_, seg, side="left",
                                 values_lo=ftn, queries_lo=ins)
    pos_c = segment_searchsorted(cts, c_off, is_, seg, side="right",
                                 values_lo=ctn, queries_lo=ins)
    pos_d = segment_searchsorted(dts, v_off, qts, seg, side="left",
                                 values_lo=dtn, queries_lo=qtn)
    return jnp.stack([pos_f, pos_c, pos_d])


_rq3_kernel = jax.jit(_rq3_body)


def _rq4a_body(fts, ftn, f_off, is_, ins, seg, gid, sel1, sel2,
               n_projects: int, max_iter: int):
    """RQ4a's G1/G2 loop (rq4a_bug.py:324-346) in one dispatch: one
    searchsorted maps every grouped issue to its iteration; per-group
    survival curves come from a weighted bincount (weight = group
    membership) and detected-project counts from the boolean scatter.
    Packed output: [ks(Q), g1_tot, g1_det, g2_tot, g2_det] int32."""
    ks = segment_searchsorted(fts, f_off, is_, seg, side="left",
                              values_lo=ftn, queries_lo=ins)
    counts = f_off[1:] - f_off[:-1]
    clipped = jnp.clip(counts, 0, max_iter)

    def group(sel, g):
        w = sel.astype(jnp.int32)
        # Weighted survival: #group projects with >= k builds.  Equals
        # counts_to_survival(counts[sel & counts > 0]) — zero-count rows
        # appear in every cumsum term and cancel against w.sum().
        hist = jnp.zeros(max_iter + 1, jnp.int32).at[clipped].add(w)
        tot = w.sum() - jnp.cumsum(hist)[:-1]
        det = unique_pairs_count_per_iteration(
            seg, jnp.where(gid == g, ks, 0), n_projects, max_iter)
        return tot, det

    t1, d1 = group(sel1, 1)
    t2, d2 = group(sel2, 2)
    return jnp.concatenate([ks, t1, d1, t2, d2])


_rq4a_kernel = jax.jit(_rq4a_body, static_argnames=("n_projects",
                                                    "max_iter"))


def _rq2tr_body(mj, kj, lo, hi):
    """RQ2 trends' device work in one dispatch: per-project Spearman, the
    per-session sort + two order-statistic gathers (the rounding-free part
    of masked_percentile — the float32 lerp replays on host, same op order,
    so results stay bit-identical to the eager kernel; see
    rq_mesh.percentile_by_session_mesh), and the per-session mean.  Counts
    stay on host (the caller already holds mask.sum(axis=0)).
    Packed float32: [spear(P), vlo(K*S), vhi(K*S), mean(S)]."""
    spear = masked_spearman(mj, kj)
    cols, colmask = mj.T, kj.T
    big = jnp.finfo(jnp.float32).max
    srt = jnp.sort(jnp.where(colmask, cols, big), axis=-1)
    vlo = jnp.take_along_axis(srt, lo.T, axis=-1).T
    vhi = jnp.take_along_axis(srt, hi.T, axis=-1).T
    mean = masked_mean(cols, colmask)
    return jnp.concatenate([spear, vlo.ravel(), vhi.ravel(), mean])


_rq2_trends_kernel = jax.jit(_rq2tr_body)


def _pack_cp_lane(cp_pos, cp16: bool):
    """rq2cp's boundary-join lane is ~2 int32 per change point — the fat
    D2H lane of both the fused suite and the standalone rq2cp call.  When
    every coverage segment is shorter than 2^15 rows (caller-checked) the
    positions fit int16: pack pairs into int32, halving the fetch."""
    if not cp16:
        return cp_pos.astype(jnp.int32)
    nb = cp_pos.shape[0]
    cp = cp_pos.astype(jnp.int16)
    if nb % 2:
        cp = jnp.concatenate([cp, jnp.zeros(1, jnp.int16)])
    return jax.lax.bitcast_convert_type(cp.reshape(-1, 2), jnp.int32)


def _unpack_cp_lane(lane: np.ndarray, nb: int, cp16: bool) -> np.ndarray:
    if not cp16:
        return lane
    return lane.view(np.int16)[:nb].astype(np.int64)


@partial(jax.jit, static_argnames=("cp16",))
def _rq2cp_join_kernel(ds, dns, off, qs, qns, qseg, cp16: bool):
    """Standalone rq2cp date join: one searchsorted + the packed lane."""
    pos = segment_searchsorted(ds, off, qs, qseg, side="left",
                               values_lo=dns, queries_lo=qns)
    return _pack_cp_lane(pos, cp16)


@partial(jax.jit, static_argnames=("n_projects", "max_iter1", "max_iter4",
                                   "cp16"))
def _rq_suite_kernel(fs, fns, foff, oks, okns, okoff, okpos, is_, ins, seg,
                     cts, ctn, coff, dts, dtn, voff, qts, qtn,
                     f4s, f4ns, f4off, i4s, i4ns, seg4, gid4, sel1, sel2,
                     cps, cpns, cpoff, cqs, cqns, cqseg,
                     mj, kj, lo, hi,
                     n_projects: int, max_iter1: int, max_iter4: int,
                     cp16: bool):
    """ALL SIX RQ device bodies in ONE dispatch returning ONE packed int32
    buffer — on a tunneled PJRT link each dispatch + fetch costs a ~0.11 s
    round-trip, so running the suite as six calls pays that six times for
    kernels that each compute in microseconds.  Shares the same cached CSR
    lanes and the same bodies as the per-RQ kernels, so results are
    bit-identical (asserted by bench parity + tests/test_rq_suite.py).
    Layout: [rq1: it(Q) link(Q) totals(M1) det(M1) | rq3: 3Q |
    rq4a: Q4+4*M4 | rq2cp: NB | rq2tr (float32 bitcast): P+2KS+S]."""
    it, li, totals, detected = _rq1_body(
        fs, fns, foff, oks, okns, okoff, okpos, is_, ins, seg,
        n_projects, max_iter1)
    rq3 = _rq3_body(oks, okns, okoff, cts, ctn, coff, dts, dtn, voff,
                    is_, ins, seg, qts, qtn)
    rq4a = _rq4a_body(f4s, f4ns, f4off, i4s, i4ns, seg4, gid4, sel1, sel2,
                      n_projects, max_iter4)
    cp_pos = segment_searchsorted(cps, cpoff, cqs, cqseg, side="left",
                                  values_lo=cpns, queries_lo=cqns)
    cp_lane = _pack_cp_lane(cp_pos, cp16)
    tr = _rq2tr_body(mj, kj, lo, hi)
    return jnp.concatenate([
        it.astype(jnp.int32), li.astype(jnp.int32), totals, detected,
        rq3.reshape(-1).astype(jnp.int32), rq4a.astype(jnp.int32), cp_lane,
        jax.lax.bitcast_convert_type(tr, jnp.int32)])


def _rq1_post(it, li, totals, detected, min_projects: int) -> RQ1Result:
    """RQ1 host tail (the >=min_projects filter, rq1:232-239) — shared by
    the per-RQ call and the fused suite."""
    totals = np.asarray(totals, dtype=np.int64)
    detected = np.asarray(detected, dtype=np.int64)
    keep = totals >= min_projects
    return RQ1Result(
        iterations=np.flatnonzero(keep) + 1,
        total_projects=totals[keep],
        detected_counts=detected[keep],
        iteration_of_issue=np.asarray(it, dtype=np.int64),
        link_idx=np.asarray(li, dtype=np.int64),
    )


def _rq4a_post(g1_tot, g1_det, g2_tot, g2_det,
               min_projects: int) -> RQ4aTrendResult:
    """RQ4a host tail (the both-groups >=min_projects filter,
    rq4a_bug.py:171-179) — shared by the per-RQ call and the fused suite."""
    valid = (g1_tot >= min_projects) & (g2_tot >= min_projects)
    keep = np.flatnonzero(valid)
    return RQ4aTrendResult(
        iterations=keep + 1,
        g1_total=g1_tot[keep], g1_detected=g1_det[keep],
        g2_total=g2_tot[keep], g2_detected=g2_det[keep],
    )


class JaxBackend(Backend):
    """mesh: "auto" (default) shards the RQ reductions over all visible
    devices when there is more than one (the north star's psum/pmean mesh
    collectives); None forces the single-device kernels; a
    `jax.sharding.Mesh` uses that mesh.  Both paths are bit-identical —
    sharding axes keep float reductions device-local and only integer
    partials cross the mesh (see parallel/rq_mesh.py).

    Single-device calls go through the device-resident study cache (module
    docstring): value-side CSR arrays upload once per (study, cutoff) and
    every RQ is one fused dispatch + one packed fetch."""

    name = "jax_tpu"

    def __init__(self, mesh="auto"):
        self._mesh = rq_mesh.auto_mesh() if mesh == "auto" else mesh

    def _seg_searchsorted(self, values_s, offsets, queries_s, seg,
                          side, values_lo, queries_lo) -> np.ndarray:
        """Two-lane per-segment searchsorted, sharded over the query axis
        when a mesh is active (bit-identical either way — every query's
        binary search is independent)."""
        if self._mesh is not None:
            return rq_mesh.segment_searchsorted_mesh(
                self._mesh, values_s, offsets, queries_s, seg, side,
                values_lo, queries_lo)
        return np.asarray(_seg_searchsorted_jit(
            values_s, np.asarray(offsets, np.int32),
            queries_s, np.asarray(seg, np.int32), side=side,
            values_lo=values_lo, queries_lo=queries_lo))

    def rq1_detection(self, arrays: StudyArrays, limit_date_ns: int,
                      min_projects: int) -> RQ1Result:
        P = arrays.n_projects
        n_issues = len(arrays.issues)
        n_builds = arrays.fuzz.counts()
        max_iter = int(n_builds.max()) if len(arrays.fuzz) else 0
        if max_iter == 0:
            return RQ1Result(np.empty(0, np.int64), np.empty(0, np.int64),
                             np.empty(0, np.int64),
                             np.zeros(n_issues, np.int64),
                             np.full(n_issues, -1, np.int64))

        if self._mesh is not None and n_issues:
            btimes_ns = arrays.fuzz.columns["time_ns"]
            fs, fns = ns_to_device_pair(btimes_ns)
            ok_pos, ok_offsets = masked_csr(
                arrays.fuzz.offsets,
                arrays.fuzz.columns["ok"] & (btimes_ns < limit_date_ns))
            issue_seg = np.repeat(np.arange(P), arrays.issues.counts())
            is_, ins = ns_to_device_pair(arrays.issues.columns["time_ns"])
            it, li, detected = rq_mesh.rq1_kernel_mesh(
                self._mesh, fs, fns, arrays.fuzz.offsets,
                fs[ok_pos], fns[ok_pos], ok_offsets, ok_pos,
                is_, ins, issue_seg, n_projects=P, max_iter=max_iter)
            totals = counts_to_survival(jnp.asarray(n_builds), max_iter)
            it = np.asarray(it, dtype=np.int64)
            li = np.asarray(li, dtype=np.int64)
        else:
            cache = _study_cache(arrays)
            _touch_limit(cache, limit_date_ns)
            fs_d, fns_d, foff_d = _dev_fuzz(arrays, cache)
            oks_d, okns_d, okoff_d, okpos_d = _dev_fuzz_ok(
                arrays, cache, limit_date_ns)
            is_d, ins_d, seg_d = _dev_issues(arrays, cache)
            packed = np.asarray(_rq1_kernel_packed(
                fs_d, fns_d, foff_d, oks_d, okns_d, okoff_d, okpos_d,
                is_d, ins_d, seg_d, n_projects=P, max_iter=max_iter))
            it = packed[:n_issues].astype(np.int64)
            li = packed[n_issues:2 * n_issues].astype(np.int64)
            totals = packed[2 * n_issues:2 * n_issues + max_iter]
            detected = packed[2 * n_issues + max_iter:]
        return _rq1_post(it, li, totals, detected, min_projects)

    def rq2_change_points(self, arrays: StudyArrays,
                          limit_date_ns: int) -> RQ2ChangePointsResult:
        """Group-boundary detection is vectorised numpy (irregular/cheap);
        the date-equality join runs as one device searchsorted over the CSR
        coverage-date arrays — sharded over the boundary axis when a mesh
        is active — and the final float64 gathers stay on host so values
        are bit-exact vs the pandas backend."""
        cache = _study_cache(arrays)
        _touch_limit(cache, limit_date_ns)
        bounds = _rq2cp_bounds(arrays, cache, limit_date_ns)
        if bounds is None:
            e = np.empty(0, np.int64)
            f = np.empty(0, np.float64)
            return RQ2ChangePointsResult(e, e, e, f, f, f, f)
        if self._mesh is not None:
            cov_pos, cov_offsets = _host_cov_cut(arrays, cache,
                                                 limit_date_ns)
            ds, dns = ns_to_device_pair(
                arrays.cov.columns["date_ns"][cov_pos])
            pos = self._seg_searchsorted(ds, cov_offsets, bounds["qs_d"],
                                         bounds["q_seg"], "left",
                                         dns, bounds["qns_d"])
        else:
            ds_d, dns_d, covoff_d = _dev_cov_cut(arrays, cache, limit_date_ns)
            _, cov_off_h = _host_cov_cut(arrays, cache, limit_date_ns)
            cp16 = bool(np.diff(cov_off_h).max(initial=0) < (1 << 15))
            nb = bounds["q_seg"].size
            pos = _unpack_cp_lane(
                np.asarray(_rq2cp_join_kernel(
                    ds_d, dns_d, covoff_d, bounds["qs_d"], bounds["qns_d"],
                    bounds["qseg_d"], cp16=cp16)), nb, cp16)
        return self._rq2cp_post(arrays, cache, limit_date_ns, bounds, pos)

    def _rq2cp_post(self, arrays: StudyArrays, cache: dict,
                    limit_date_ns: int, bounds: dict,
                    pos: np.ndarray) -> RQ2ChangePointsResult:
        """Host tail of RQ2 change points: gather the joined coverage rows
        (float64, bit-exact vs pandas) — shared by the per-RQ call and the
        fused suite."""
        cov_pos, cov_offsets = _host_cov_cut(arrays, cache, limit_date_ns)
        cov_days = arrays.cov.columns["date_ns"][cov_pos]
        cov_covered = arrays.cov.columns["covered"][cov_pos]
        cov_total = arrays.cov.columns["total"][cov_pos]
        q_seg, q_days = bounds["q_seg"], bounds["q_days"]
        gidx = cov_offsets[q_seg] + pos
        in_seg = gidx < cov_offsets[q_seg + 1]
        safe = np.clip(gidx, 0, max(cov_pos.size - 1, 0))
        matched = in_seg & (cov_days[safe] == q_days)
        covered = np.where(matched, cov_covered[safe], np.nan)
        total = np.where(matched, cov_total[safe], np.nan)
        n = bounds["end_i"].size
        return RQ2ChangePointsResult(
            project_idx=bounds["proj"].astype(np.int64),
            end_i=bounds["end_i"].astype(np.int64),
            start_ip1=bounds["start_ip1"].astype(np.int64),
            covered_i=covered[:n], total_i=total[:n],
            covered_ip1=covered[n:], total_ip1=total[n:],
        )

    def rq3_coverage_at_detection(self, arrays: StudyArrays,
                                  limit_date_ns: int) -> RQ3Result:
        """Vectorised form of the reference's per-issue scans (rq3:241-302):
        the three linear searches per issue (last fuzz build, first coverage
        build, day-after coverage row) become ONE fused device dispatch of
        three segment-searchsorteds over cached masked CSR arrays; the final
        float64 delta gathers stay on host for bit-exactness vs the pandas
        oracle.  Same three documented deviations as the pandas backend."""
        P = arrays.n_projects
        issue_t = arrays.issues.columns["time_ns"]
        cache = _study_cache(arrays)
        _touch_limit(cache, limit_date_ns)

        fuzz_t = arrays.fuzz.columns["time_ns"]
        f_pos, f_off = _host_fuzz_ok(arrays, cache, limit_date_ns)
        covb_t = arrays.covb.columns["time_ns"]
        c_pos, c_off = _host_covb_cut(arrays, cache, limit_date_ns)

        issue_seg = np.repeat(np.arange(P), arrays.issues.counts())
        seg32 = issue_seg.astype(np.int32)
        target = floor_day_ns(issue_t) + DAY_NS
        if self._mesh is not None:
            v_pos, v_off = _host_cov_valid(arrays, cache)
            days = arrays.cov.columns["date_ns"][v_pos]
            is_, ins = ns_to_device_pair(issue_t)
            fts, ftn = ns_to_device_pair(fuzz_t[f_pos])
            cts, ctn = ns_to_device_pair(covb_t[c_pos])
            # Last successful fuzzing build strictly before rts (rq3:269).
            pos_f = self._seg_searchsorted(fts, f_off, is_, seg32, "left",
                                           ftn, ins)
            # First coverage build strictly after rts (rq3:273).
            pos_c = self._seg_searchsorted(cts, c_off, is_, seg32, "right",
                                           ctn, ins)
            # Day-after coverage row (rq3:287-293).
            dts, dtn = ns_to_device_pair(days)
            qts, qtn = ns_to_device_pair(target)
            pos_d = self._seg_searchsorted(dts, v_off, qts, seg32, "left",
                                           dtn, qtn)
        else:
            fts_d, ftn_d, foff_d, _ = _dev_fuzz_ok(arrays, cache,
                                                   limit_date_ns)
            cts_d, ctn_d, coff_d = _dev_covb_cut(arrays, cache, limit_date_ns)
            dts_d, dtn_d, voff_d = _dev_cov_valid(arrays, cache)
            is_d, ins_d, seg_d = _dev_issues(arrays, cache)
            qts_d, qtn_d = _dev_rq3_targets(arrays, cache)
            pos3 = np.asarray(_rq3_kernel(
                fts_d, ftn_d, foff_d, cts_d, ctn_d, coff_d,
                dts_d, dtn_d, voff_d, is_d, ins_d, seg_d, qts_d, qtn_d))
            pos_f, pos_c, pos_d = pos3[0], pos3[1], pos3[2]
        return self._rq3_post(arrays, cache, limit_date_ns,
                              pos_f, pos_c, pos_d)

    def _rq3_post(self, arrays: StudyArrays, cache: dict, limit_date_ns: int,
                  pos_f, pos_c, pos_d) -> RQ3Result:
        """Host tail of RQ3 (the candidate gates of rq3:266-302 + the
        non-detected day pairs of rq3:246-257) — shared by the per-RQ call
        and the fused suite.  All float math is float64 on host, bit-exact
        vs the pandas oracle."""
        P = arrays.n_projects
        issue_t = arrays.issues.columns["time_ns"]
        n_issues = issue_t.size
        fuzz_t = arrays.fuzz.columns["time_ns"]
        covb_t = arrays.covb.columns["time_ns"]
        f_pos, f_off = _host_fuzz_ok(arrays, cache, limit_date_ns)
        c_pos, c_off = _host_covb_cut(arrays, cache, limit_date_ns)
        v_pos, v_off = _host_cov_valid(arrays, cache)
        days = arrays.cov.columns["date_ns"][v_pos]
        covered = arrays.cov.columns["covered"][v_pos]
        total = arrays.cov.columns["total"][v_pos]
        issue_seg = np.repeat(np.arange(P), arrays.issues.counts())
        target = floor_day_ns(issue_t) + DAY_NS
        # Projects must have all three inputs (rq3:266).
        has_all = ((np.diff(f_off) > 0) & (np.diff(c_off) > 0)
                   & (np.diff(v_off) > 0))
        can_detect = bool(n_issues and f_pos.size and c_pos.size
                          and v_pos.size)

        if can_detect:
            cand = (has_all[issue_seg] & (pos_f > 0)
                    & (pos_c < np.diff(c_off)[issue_seg]))
            k_glob = np.where(cand, f_off[issue_seg] + pos_f - 1, 0)
            m_glob = np.where(cand, c_off[issue_seg] + pos_c, 0)
            m_glob = np.clip(m_glob, 0, c_pos.size - 1)
            cand &= arrays.covb.columns["ok"][c_pos[m_glob]]
            cand &= (covb_t[c_pos[m_glob]]
                     - fuzz_t[f_pos[k_glob]]) <= 24 * HOUR_NS
            if cand.any():
                rev_eq = np.zeros(n_issues, dtype=bool)
                ci = np.flatnonzero(cand)
                rev_eq[ci] = (arrays.fuzz_revhash_at(f_pos[k_glob[ci]])
                              == arrays.covb_revhash_at(c_pos[m_glob[ci]]))
                cand &= rev_eq
            i_glob = np.where(cand, v_off[issue_seg] + pos_d, 0)
            in_seg = pos_d < np.diff(v_off)[issue_seg]
            safe = np.clip(i_glob, 0, max(days.size - 1, 0))
            cand &= (in_seg & (i_glob > v_off[issue_seg])
                     & (days[safe] == target) & (covered[safe] != 0)
                     & (total[np.maximum(safe - 1, 0)] > 0) & (total[safe] > 0))
            di = np.flatnonzero(cand)
            gi = i_glob[di]
        else:
            di = np.empty(0, np.int64)
            gi = np.empty(0, np.int64)
        det_pct = ((covered[gi] / total[gi]
                    - covered[gi - 1] / total[gi - 1]) * 100.0)

        # Non-detected: all other consecutive coverage-day pairs of projects
        # with >= 1 fixed issue (rq3:246-257), excluding pairs whose current
        # date equals a detected issue's report date.
        has_issues = arrays.issues.counts() > 0
        row_seg = np.repeat(np.arange(P), np.diff(v_off))
        not_start = np.ones(days.size, dtype=bool)
        not_start[v_off[:-1][v_off[:-1] < days.size]] = False
        pair_i = np.flatnonzero(not_start)
        pair_seg = row_seg[pair_i]
        keep = (has_issues[pair_seg] & (total[pair_i - 1] > 0)
                & (total[pair_i] > 0))
        if di.size:
            det_key = (issue_seg[di].astype(np.int64) << 32) | (
                floor_day_ns(issue_t[di]) // DAY_NS)
            pair_key = (pair_seg.astype(np.int64) << 32) | (days[pair_i] // DAY_NS)
            keep &= ~np.isin(pair_key, det_key)
        ni = pair_i[keep]
        nd_pct = ((covered[ni] / total[ni]
                   - covered[ni - 1] / total[ni - 1]) * 100.0)

        return RQ3Result(
            det_diff_percent=det_pct,
            det_diff_covered=covered[gi] - covered[gi - 1],
            det_diff_total=total[gi] - total[gi - 1],
            det_project_idx=issue_seg[di].astype(np.int64),
            det_issue_idx=di.astype(np.int64),
            det_issue_time_ns=issue_t[di],
            nondet_diff_percent=nd_pct,
            nondet_diff_covered=covered[ni] - covered[ni - 1],
            nondet_diff_total=total[ni] - total[ni - 1],
            nondet_project_idx=pair_seg[keep].astype(np.int64),
        )

    def rq4a_detection_trend(self, arrays: StudyArrays, limit_date_ns: int,
                             g1_idx: np.ndarray, g2_idx: np.ndarray,
                             min_projects: int) -> RQ4aTrendResult:
        """Device form of the reference's G1/G2 loop (rq4a_bug.py:324-346):
        one segment-searchsorted maps every issue of both groups to its
        iteration; per-group populations are bincount survival curves and
        detected-project counts a boolean scatter — the same kernel shapes
        as RQ1 but over ALL builds (no result filter) per rq4a:128-134.
        Single-device, the whole G1/G2 computation is one fused dispatch
        (`_rq4a_kernel`) over the cached pre-cutoff CSR."""
        P = arrays.n_projects
        cache = _study_cache(arrays)
        _touch_limit(cache, limit_date_ns)
        f_pos, f_off = _host_fuzz_cut(arrays, cache, limit_date_ns)
        counts = np.diff(f_off)
        in_g = np.zeros(P, dtype=np.int8)  # 1 -> g1, 2 -> g2
        in_g[np.asarray(g1_idx, dtype=np.int64)] = 1
        in_g[np.asarray(g2_idx, dtype=np.int64)] = 2
        max_iter = int(counts[in_g > 0].max()) if (in_g > 0).any() else 0
        if max_iter == 0:
            e = np.empty(0, np.int64)
            return RQ4aTrendResult(e, e, e, e, e)

        issue_seg = np.repeat(np.arange(P), arrays.issues.counts())
        issue_mask = in_g[issue_seg] > 0
        qi = np.flatnonzero(issue_mask)
        issue_t = arrays.issues.columns["time_ns"][qi]
        is_, ins = ns_to_device_pair(issue_t)
        seg_q = issue_seg[qi].astype(np.int32)
        gid = in_g[issue_seg[qi]].astype(np.int32)

        if self._mesh is not None:
            fuzz_t = arrays.fuzz.columns["time_ns"]
            fts, ftn = ns_to_device_pair(fuzz_t[f_pos])
            ks = self._seg_searchsorted(fts, f_off, is_, seg_q, "left",
                                        ftn, ins)
            both = {}
            for key, g in (("g1", 1), ("g2", 2)):
                sel = in_g == g
                tot = np.asarray(counts_to_survival(
                    jnp.asarray(counts[sel & (counts > 0)]), max_iter),
                    dtype=np.int64)
                gi = gid == g
                det = np.asarray(unique_pairs_count_per_iteration(
                    jnp.asarray(seg_q[gi], jnp.int32),
                    jnp.asarray(ks[gi], jnp.int32), P, max_iter),
                    dtype=np.int64)
                both[key] = (tot, det)
            g1_tot, g1_det = both["g1"]
            g2_tot, g2_det = both["g2"]
        else:
            fts_d, ftn_d, fcoff_d = _dev_fuzz_cut(arrays, cache,
                                                  limit_date_ns)
            q = qi.size
            packed = np.asarray(_rq4a_kernel(
                fts_d, ftn_d, fcoff_d, is_, ins, seg_q, gid,
                (in_g == 1), (in_g == 2), n_projects=P, max_iter=max_iter))
            g1_tot = packed[q:q + max_iter].astype(np.int64)
            g1_det = packed[q + max_iter:q + 2 * max_iter].astype(np.int64)
            g2_tot = packed[q + 2 * max_iter:q + 3 * max_iter].astype(np.int64)
            g2_det = packed[q + 3 * max_iter:].astype(np.int64)

        return _rq4a_post(g1_tot, g1_det, g2_tot, g2_det, min_projects)

    def rq4b_group_trends(self, arrays: StudyArrays, limit_date_ns: int,
                          g1_idx: np.ndarray, g2_idx: np.ndarray,
                          percentiles: tuple = (25, 50, 75)
                          ) -> RQ4bTrendsResult:
        """Vectorised form of rq4b_coverage.py:914-976: the padded trend
        matrix is scattered on host (irregular) and the per-session per-group
        percentile reductions run as float64 nanpercentile columns — host,
        not device, so win-count comparisons downstream are bit-exact vs the
        pandas oracle (see the float32 note below)."""
        cache = _study_cache(arrays)
        _touch_limit(cache, limit_date_ns)
        matrix, mask = _rq4b_matrix(arrays, cache, limit_date_ns)
        S = matrix.shape[1]

        import warnings

        q = np.array(percentiles, dtype=np.float64)
        out = {}
        for key, idx in (("g1", np.asarray(g1_idx, dtype=np.int64)),
                         ("g2", np.asarray(g2_idx, dtype=np.int64))):
            if S == 0 or idx.size == 0:
                out[key] = (np.full((len(percentiles), S), np.nan),
                            np.zeros(S, dtype=np.int64))
                continue
            # Percentiles reduce in float64 (advisor contract): a float32
            # reduction diverges from the pandas oracle at ~1e-5 relative —
            # enough to flip summarize_trends' G2>G1 win counts.  On a mesh
            # the float64 sort + order-statistic selection shards the
            # session axis on device and the host applies numpy's _lerp, so
            # values stay bit-identical to np.nanpercentile.
            if self._mesh is not None:
                pcts = rq_mesh.nanpercentile_by_session_mesh(
                    matrix[idx], q, self._mesh)
                counts = rq_mesh.counts_by_project_psum(mask[idx], self._mesh)
            else:
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore", RuntimeWarning)
                    pcts = np.nanpercentile(matrix[idx], q, axis=0)
                counts = mask[idx].sum(axis=0)
            out[key] = (pcts, counts)
        return RQ4bTrendsResult(
            percentiles=tuple(percentiles), matrix=matrix, mask=mask,
            g1_percentiles=out["g1"][0], g1_counts=out["g1"][1],
            g2_percentiles=out["g2"][0], g2_counts=out["g2"][1],
        )

    def rq_suite(self, arrays: StudyArrays, limit_date_ns: int,
                 min_projects: int, g1_idx: np.ndarray, g2_idx: np.ndarray,
                 percentiles: tuple = (25, 50, 75)) -> dict:
        """All six RQs as ONE device dispatch + ONE packed fetch
        (`_rq_suite_kernel`) — the per-RQ path pays the ~0.11 s tunneled
        round-trip six times; this pays it once.  RQ4b's host-only float64
        percentiles run while the device dispatch is in flight.  Falls back
        to six sequential calls on a mesh or on degenerate shapes (empty
        study, no grouped projects) where the individual methods' guards
        apply."""
        P = arrays.n_projects
        n_issues = len(arrays.issues)
        max_iter1 = int(arrays.fuzz.counts().max()) if len(arrays.fuzz) else 0
        if self._mesh is not None or max_iter1 == 0 or n_issues == 0:
            return super().rq_suite(arrays, limit_date_ns, min_projects,
                                    g1_idx, g2_idx, percentiles)
        cache = _study_cache(arrays)
        _touch_limit(cache, limit_date_ns)
        bounds = _rq2cp_bounds(arrays, cache, limit_date_ns)
        prep2 = _rq2tr_prep(arrays, cache, limit_date_ns)
        f_pos4, f_off4 = _host_fuzz_cut(arrays, cache, limit_date_ns)
        counts4 = np.diff(f_off4)
        in_g = np.zeros(P, dtype=np.int8)
        in_g[np.asarray(g1_idx, dtype=np.int64)] = 1
        in_g[np.asarray(g2_idx, dtype=np.int64)] = 2
        max_iter4 = int(counts4[in_g > 0].max()) if (in_g > 0).any() else 0
        if bounds is None or prep2["S"] == 0 or max_iter4 == 0:
            return super().rq_suite(arrays, limit_date_ns, min_projects,
                                    g1_idx, g2_idx, percentiles)
        issue_seg = np.repeat(np.arange(P), arrays.issues.counts())
        qi = np.flatnonzero(in_g[issue_seg] > 0)
        i4s, i4ns = ns_to_device_pair(arrays.issues.columns["time_ns"][qi])
        seg4 = issue_seg[qi].astype(np.int32)
        gid4 = in_g[issue_seg[qi]].astype(np.int32)

        fs_d, fns_d, foff_d = _dev_fuzz(arrays, cache)
        oks_d, okns_d, okoff_d, okpos_d = _dev_fuzz_ok(arrays, cache,
                                                       limit_date_ns)
        is_d, ins_d, seg_d = _dev_issues(arrays, cache)
        cts_d, ctn_d, coff_d = _dev_covb_cut(arrays, cache, limit_date_ns)
        dts_d, dtn_d, voff_d = _dev_cov_valid(arrays, cache)
        qts_d, qtn_d = _dev_rq3_targets(arrays, cache)
        f4s_d, f4ns_d, f4off_d = _dev_fuzz_cut(arrays, cache, limit_date_ns)
        ds_d, dns_d, covoff_d = _dev_cov_cut(arrays, cache, limit_date_ns)
        _, cov_off_h = _host_cov_cut(arrays, cache, limit_date_ns)
        cp16 = bool(np.diff(cov_off_h).max(initial=0) < (1 << 15))
        packed_d = _rq_suite_kernel(
            fs_d, fns_d, foff_d, oks_d, okns_d, okoff_d, okpos_d,
            is_d, ins_d, seg_d,
            cts_d, ctn_d, coff_d, dts_d, dtn_d, voff_d, qts_d, qtn_d,
            f4s_d, f4ns_d, f4off_d, i4s, i4ns, seg4, gid4,
            (in_g == 1), (in_g == 2),
            ds_d, dns_d, covoff_d,
            bounds["qs_d"], bounds["qns_d"], bounds["qseg_d"],
            *_rq2tr_dev(arrays, cache, limit_date_ns),
            n_projects=P, max_iter1=max_iter1, max_iter4=max_iter4,
            cp16=cp16)
        # The dispatch is async: overlap RQ4b's host-side float64
        # percentile reductions with the device execution + fetch latency.
        rq4b = self.rq4b_group_trends(arrays, limit_date_ns, g1_idx, g2_idx,
                                      percentiles)
        packed = np.asarray(packed_d)

        q, m1, q4, m4 = n_issues, max_iter1, qi.size, max_iter4
        nb = bounds["q_seg"].size
        o = 0

        def take(k):
            nonlocal o
            out = packed[o:o + k]
            o += k
            return out

        it, li = take(q), take(q)
        totals, detected = take(m1), take(m1)
        pos_f, pos_c, pos_d = take(q), take(q), take(q)
        take(q4)  # rq4a's per-issue iteration lane; unused downstream
        g1_tot, g1_det = take(m4).astype(np.int64), take(m4).astype(np.int64)
        g2_tot, g2_det = take(m4).astype(np.int64), take(m4).astype(np.int64)
        cp_pos = _unpack_cp_lane(take((nb + 1) // 2 if cp16 else nb),
                                 nb, cp16)
        tr = packed[o:].view(np.float32)
        return {
            "rq1": _rq1_post(it, li, totals, detected, min_projects),
            "rq2cp": self._rq2cp_post(arrays, cache, limit_date_ns, bounds,
                                      cp_pos),
            "rq2tr": self._rq2tr_post(prep2, tr),
            "rq3": self._rq3_post(arrays, cache, limit_date_ns,
                                  pos_f, pos_c, pos_d),
            "rq4a": _rq4a_post(g1_tot, g1_det, g2_tot, g2_det, min_projects),
            "rq4b": rq4b,
        }

    def rq2_trends(self, arrays: StudyArrays,
                   limit_date_ns: int) -> RQ2TrendsResult:
        P = arrays.n_projects
        cache = _study_cache(arrays)
        _touch_limit(cache, limit_date_ns)
        prep = _rq2tr_prep(arrays, cache, limit_date_ns)
        matrix, mask, S = prep["matrix"], prep["mask"], prep["S"]
        q = np.array(RQ2TrendsResult.PCTS, dtype=np.float32)
        if S == 0 or P == 0:
            # Empty study (e.g. no eligible projects): zero-width device
            # kernels are ill-formed, so emit the empty result directly.
            return RQ2TrendsResult(
                matrix=matrix, mask=mask,
                spearman=np.full(P, np.nan),
                percentiles=np.full((len(RQ2TrendsResult.PCTS), S), np.nan),
                mean=np.full(S, np.nan),
                counts=np.zeros(S, dtype=np.int64))
        if self._mesh is not None:
            # Mesh collectives (north star): percentile/mean shard the
            # session axis (each column reduces on one device — bit-exact),
            # Spearman shards the project axis, counts psum project shards.
            spear = rq_mesh.spearman_by_project_mesh(matrix, mask, self._mesh)
            pcts = rq_mesh.percentile_by_session_mesh(
                matrix.T, mask.T, q, self._mesh)
            mean = rq_mesh.mean_by_session_mesh(matrix.T, mask.T, self._mesh)
            counts = rq_mesh.counts_by_project_psum(mask, self._mesh)
            return RQ2TrendsResult(matrix=matrix, mask=mask, spearman=spear,
                                   percentiles=pcts, mean=mean, counts=counts)
        # One fused dispatch over the cached device copies.
        packed = np.asarray(_rq2_trends_kernel(
            *_rq2tr_dev(arrays, cache, limit_date_ns)))
        return self._rq2tr_post(prep, packed)

    def _rq2tr_post(self, prep: dict, packed: np.ndarray) -> RQ2TrendsResult:
        """Host tail of RQ2 trends: the float32 lerp replays with the exact
        op order of the eager masked_percentile kernel (same scheme as the
        mesh path), so single-device, mesh, and eager all agree
        bit-for-bit.  Shared by the per-RQ call and the fused suite."""
        matrix, mask = prep["matrix"], prep["mask"]
        P, S = matrix.shape
        K = len(RQ2TrendsResult.PCTS)
        n_valid, lo, frac = prep["n_valid"], prep["lo"], prep["frac"]
        spear = packed[:P].astype(np.float64)
        vlo = packed[P:P + K * S].reshape(K, S)
        vhi = packed[P + K * S:P + 2 * K * S].reshape(K, S)
        hi_valid = (lo + 1) <= (n_valid[None, :] - 1)
        pcts = vlo + np.where(hi_valid, frac * (vhi - vlo), np.float32(0.0))
        pcts = np.where(n_valid[None, :] > 0, pcts,
                        np.float32(np.nan)).astype(np.float64)
        mean = packed[P + 2 * K * S:].astype(np.float64)
        return RQ2TrendsResult(matrix=matrix, mask=mask, spearman=spear,
                               percentiles=pcts, mean=mean,
                               counts=n_valid.astype(np.int64))
