"""Device (jax_tpu) backend.

The reference's two RQ1 hot loops — 10m51s + 19m29s on the author's laptop
(rq1_detection_rate.py:361,367) — become one jitted kernel: a CSR binary
search for issue->iteration indexing and linkage, a bincount survival curve
for per-iteration populations, and a boolean scatter for unique detected
projects.  Timestamps ride as two int32 lanes (seconds, ns remainder) so
sub-second ordering matches the host backend exactly without enabling x64.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .base import (Backend, RQ1Result, RQ2ChangePointsResult, RQ2TrendsResult)
from .pandas_backend import floor_day_ns
from ..data.columnar import StudyArrays, ns_to_device_pair
from ..ops.segment import (counts_to_survival, masked_mean, masked_percentile,
                           masked_spearman, segment_searchsorted,
                           unique_pairs_count_per_iteration)


@partial(jax.jit, static_argnames=("n_projects", "max_iter"))
def _rq1_kernel(fuzz_s, fuzz_ns, fuzz_offsets, ok_s, ok_ns, ok_offsets, ok_orig_idx,
                issue_s, issue_ns, issue_seg, n_projects: int, max_iter: int):
    # Iteration of each issue: #builds (any result) strictly before rts.
    iteration_of_issue = segment_searchsorted(
        fuzz_s, fuzz_offsets, issue_s, issue_seg, side="left",
        values_lo=fuzz_ns, queries_lo=issue_ns)

    # Linkage: latest successful pre-cutoff build strictly before rts.
    pos = segment_searchsorted(ok_s, ok_offsets, issue_s, issue_seg, side="left",
                               values_lo=ok_ns, queries_lo=issue_ns)
    has_link = pos > 0
    if ok_orig_idx.shape[0]:
        gather = jnp.clip(ok_offsets[issue_seg] + pos - 1, 0, ok_orig_idx.shape[0] - 1)
        link_idx = jnp.where(has_link, ok_orig_idx[gather], -1)
    else:
        link_idx = jnp.full(issue_seg.shape, -1, dtype=jnp.int32)

    counts = fuzz_offsets[1:] - fuzz_offsets[:-1]
    totals = counts_to_survival(counts, max_iter)

    det_iter = jnp.where(has_link, iteration_of_issue, 0)
    detected = unique_pairs_count_per_iteration(issue_seg, det_iter,
                                                n_projects, max_iter)
    return iteration_of_issue, link_idx, totals, detected


class JaxBackend(Backend):
    name = "jax_tpu"

    def rq1_detection(self, arrays: StudyArrays, limit_date_ns: int,
                      min_projects: int) -> RQ1Result:
        P = arrays.n_projects
        n_issues = len(arrays.issues)
        n_builds = arrays.fuzz.counts()
        max_iter = int(n_builds.max()) if len(arrays.fuzz) else 0
        if max_iter == 0:
            return RQ1Result(np.empty(0, np.int64), np.empty(0, np.int64),
                             np.empty(0, np.int64),
                             np.zeros(n_issues, np.int64),
                             np.full(n_issues, -1, np.int64))

        btimes_ns = arrays.fuzz.columns["time_ns"]
        fs, fns = ns_to_device_pair(btimes_ns)
        ok_mask = arrays.fuzz.columns["ok"] & (btimes_ns < limit_date_ns)
        ok_pos = np.flatnonzero(ok_mask)
        # Per-segment successful-build offsets via boundary differences of
        # the running sum (robust to empty segments).
        running = np.concatenate([[0], np.cumsum(ok_mask.astype(np.int64))])
        ok_offsets = running[arrays.fuzz.offsets]

        issue_seg = np.repeat(np.arange(P), arrays.issues.counts())
        is_, ins = ns_to_device_pair(arrays.issues.columns["time_ns"])

        it, li, totals, detected = _rq1_kernel(
            jnp.asarray(fs), jnp.asarray(fns),
            jnp.asarray(arrays.fuzz.offsets, dtype=jnp.int32),
            jnp.asarray(fs[ok_pos]), jnp.asarray(fns[ok_pos]),
            jnp.asarray(ok_offsets, dtype=jnp.int32),
            jnp.asarray(ok_pos, dtype=jnp.int32),
            jnp.asarray(is_), jnp.asarray(ins),
            jnp.asarray(issue_seg, dtype=jnp.int32),
            n_projects=P,
            max_iter=max_iter,
        )
        totals = np.asarray(totals, dtype=np.int64)
        detected = np.asarray(detected, dtype=np.int64)
        keep = totals >= min_projects
        return RQ1Result(
            iterations=np.flatnonzero(keep) + 1,
            total_projects=totals[keep],
            detected_counts=detected[keep],
            iteration_of_issue=np.asarray(it, dtype=np.int64),
            link_idx=np.asarray(li, dtype=np.int64),
        )

    def rq2_change_points(self, arrays: StudyArrays,
                          limit_date_ns: int) -> RQ2ChangePointsResult:
        """Group-boundary detection is vectorised numpy (irregular/cheap);
        the date-equality join runs as one device searchsorted over the CSR
        coverage-date arrays, and the final float64 gathers stay on host so
        values are bit-exact vs the pandas backend."""
        covb_t = arrays.covb.columns["time_ns"]
        ghash = arrays.covb.columns["grouphash"]
        n_covb = len(arrays.covb)
        seg_all = np.repeat(np.arange(arrays.n_projects), arrays.covb.counts())
        has_cov = arrays.cov.counts() > 0
        keep = (covb_t < limit_date_ns) & has_cov[seg_all]
        rows = np.flatnonzero(keep)
        if rows.size == 0:
            e = np.empty(0, np.int64)
            f = np.empty(0, np.float64)
            return RQ2ChangePointsResult(e, e, e, f, f, f, f)
        seg = seg_all[rows]
        g = ghash[rows]
        new_group = np.concatenate(
            [[True], (g[1:] != g[:-1]) | (seg[1:] != seg[:-1])])
        start_pos = np.flatnonzero(new_group)
        starts = rows[start_pos]
        ends = rows[np.concatenate([start_pos[1:] - 1, [rows.size - 1]])]
        gseg = seg[start_pos]
        pair = np.flatnonzero(gseg[:-1] == gseg[1:])

        end_i = ends[pair]
        start_ip1 = starts[pair + 1]
        proj = gseg[pair]
        if end_i.size == 0:
            e = np.empty(0, np.int64)
            f = np.empty(0, np.float64)
            return RQ2ChangePointsResult(e, e, e, f, f, f, f)

        cov_days = arrays.cov.columns["date_ns"]
        q_days = np.concatenate([floor_day_ns(covb_t[end_i]),
                                 floor_day_ns(covb_t[start_ip1])])
        q_seg = np.concatenate([proj, proj])
        ds, dns = ns_to_device_pair(cov_days)
        qs, qns = ns_to_device_pair(q_days)
        pos = np.asarray(segment_searchsorted(
            ds, jnp.asarray(arrays.cov.offsets, dtype=jnp.int32),
            qs, q_seg.astype(np.int32), side="left",
            values_lo=dns, queries_lo=qns))
        gidx = arrays.cov.offsets[q_seg] + pos
        in_seg = gidx < arrays.cov.offsets[q_seg + 1]
        safe = np.clip(gidx, 0, max(len(arrays.cov) - 1, 0))
        matched = in_seg & (cov_days[safe] == q_days)
        covered = np.where(matched, arrays.cov.columns["covered"][safe], np.nan)
        total = np.where(matched, arrays.cov.columns["total"][safe], np.nan)
        n = end_i.size
        return RQ2ChangePointsResult(
            project_idx=proj.astype(np.int64),
            end_i=end_i.astype(np.int64),
            start_ip1=start_ip1.astype(np.int64),
            covered_i=covered[:n], total_i=total[:n],
            covered_ip1=covered[n:], total_ip1=total[n:],
        )

    def rq2_trends(self, arrays: StudyArrays) -> RQ2TrendsResult:
        P = arrays.n_projects
        cov = arrays.cov
        coverage = cov.columns["coverage"]
        covered = cov.columns["covered"]
        total = cov.columns["total"]
        sel = (~np.isnan(coverage)) & (coverage != 0) & (total != 0)
        seg_all = np.repeat(np.arange(P), cov.counts())
        lens = np.bincount(seg_all[sel], minlength=P)
        S = int(lens.max()) if lens.size else 0
        matrix = np.full((P, S), np.nan)
        mask = np.zeros((P, S), dtype=bool)
        # dense re-index: position of each kept row within its project
        if S:
            kept_seg = seg_all[sel]
            pos_in_proj = np.arange(sel.sum()) - np.repeat(
                np.concatenate([[0], np.cumsum(lens)[:-1]]), lens)
            with np.errstate(invalid="ignore", divide="ignore"):
                matrix[kept_seg, pos_in_proj] = (
                    covered[sel] / total[sel] * 100.0)
            mask[kept_seg, pos_in_proj] = True

        mj = jnp.asarray(matrix, dtype=jnp.float32)
        kj = jnp.asarray(mask)
        spear = np.asarray(masked_spearman(mj, kj), dtype=np.float64)
        cols = mj.T  # [S, P]: percentile/mean per session index
        colmask = kj.T
        pcts = np.asarray(masked_percentile(
            cols, colmask, np.array(RQ2TrendsResult.PCTS, dtype=np.float32)),
            dtype=np.float64)
        mean = np.asarray(masked_mean(cols, colmask), dtype=np.float64)
        counts = mask.sum(axis=0)
        return RQ2TrendsResult(matrix=matrix, mask=mask, spearman=spear,
                               percentiles=pcts, mean=mean, counts=counts)
