"""Device (jax_tpu) backend.

The reference's two RQ1 hot loops — 10m51s + 19m29s on the author's laptop
(rq1_detection_rate.py:361,367) — become one jitted kernel: a CSR binary
search for issue->iteration indexing and linkage, a bincount survival curve
for per-iteration populations, and a boolean scatter for unique detected
projects.  Timestamps ride as two int32 lanes (seconds, ns remainder) so
sub-second ordering matches the host backend exactly without enabling x64.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .base import Backend, RQ1Result
from ..data.columnar import StudyArrays, ns_to_device_pair
from ..ops.segment import (counts_to_survival, segment_searchsorted,
                           unique_pairs_count_per_iteration)


@partial(jax.jit, static_argnames=("n_projects", "max_iter"))
def _rq1_kernel(fuzz_s, fuzz_ns, fuzz_offsets, ok_s, ok_ns, ok_offsets, ok_orig_idx,
                issue_s, issue_ns, issue_seg, n_projects: int, max_iter: int):
    # Iteration of each issue: #builds (any result) strictly before rts.
    iteration_of_issue = segment_searchsorted(
        fuzz_s, fuzz_offsets, issue_s, issue_seg, side="left",
        values_lo=fuzz_ns, queries_lo=issue_ns)

    # Linkage: latest successful pre-cutoff build strictly before rts.
    pos = segment_searchsorted(ok_s, ok_offsets, issue_s, issue_seg, side="left",
                               values_lo=ok_ns, queries_lo=issue_ns)
    has_link = pos > 0
    if ok_orig_idx.shape[0]:
        gather = jnp.clip(ok_offsets[issue_seg] + pos - 1, 0, ok_orig_idx.shape[0] - 1)
        link_idx = jnp.where(has_link, ok_orig_idx[gather], -1)
    else:
        link_idx = jnp.full(issue_seg.shape, -1, dtype=jnp.int32)

    counts = fuzz_offsets[1:] - fuzz_offsets[:-1]
    totals = counts_to_survival(counts, max_iter)

    det_iter = jnp.where(has_link, iteration_of_issue, 0)
    detected = unique_pairs_count_per_iteration(issue_seg, det_iter,
                                                n_projects, max_iter)
    return iteration_of_issue, link_idx, totals, detected


class JaxBackend(Backend):
    name = "jax_tpu"

    def rq1_detection(self, arrays: StudyArrays, limit_date_ns: int,
                      min_projects: int) -> RQ1Result:
        P = arrays.n_projects
        n_issues = len(arrays.issues)
        n_builds = arrays.fuzz.counts()
        max_iter = int(n_builds.max()) if len(arrays.fuzz) else 0
        if max_iter == 0:
            return RQ1Result(np.empty(0, np.int64), np.empty(0, np.int64),
                             np.empty(0, np.int64),
                             np.zeros(n_issues, np.int64),
                             np.full(n_issues, -1, np.int64))

        btimes_ns = arrays.fuzz.columns["time_ns"]
        fs, fns = ns_to_device_pair(btimes_ns)
        ok_mask = arrays.fuzz.columns["ok"] & (btimes_ns < limit_date_ns)
        ok_pos = np.flatnonzero(ok_mask)
        # Per-segment successful-build offsets via boundary differences of
        # the running sum (robust to empty segments).
        running = np.concatenate([[0], np.cumsum(ok_mask.astype(np.int64))])
        ok_offsets = running[arrays.fuzz.offsets]

        issue_seg = np.repeat(np.arange(P), arrays.issues.counts())
        is_, ins = ns_to_device_pair(arrays.issues.columns["time_ns"])

        it, li, totals, detected = _rq1_kernel(
            jnp.asarray(fs), jnp.asarray(fns),
            jnp.asarray(arrays.fuzz.offsets, dtype=jnp.int32),
            jnp.asarray(fs[ok_pos]), jnp.asarray(fns[ok_pos]),
            jnp.asarray(ok_offsets, dtype=jnp.int32),
            jnp.asarray(ok_pos, dtype=jnp.int32),
            jnp.asarray(is_), jnp.asarray(ins),
            jnp.asarray(issue_seg, dtype=jnp.int32),
            n_projects=P,
            max_iter=max_iter,
        )
        totals = np.asarray(totals, dtype=np.int64)
        detected = np.asarray(detected, dtype=np.int64)
        keep = totals >= min_projects
        return RQ1Result(
            iterations=np.flatnonzero(keep) + 1,
            total_projects=totals[keep],
            detected_counts=detected[keep],
            iteration_of_issue=np.asarray(it, dtype=np.int64),
            link_idx=np.asarray(li, dtype=np.int64),
        )
