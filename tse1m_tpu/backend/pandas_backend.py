"""Host (pandas/numpy) backend — the semantic reference implementation.

Mirrors the reference's Python-loop logic (rq1_detection_rate.py:189-268)
project-by-project, but over the columnar arrays instead of N+1 SQL, so it is
already orders of magnitude faster than the original while remaining the
exact-semantics oracle the jax_tpu backend is parity-tested against.
"""

from __future__ import annotations

import numpy as np

from .base import (Backend, RQ1Result, RQ2ChangePointsResult, RQ2TrendsResult)
from ..data.columnar import StudyArrays

DAY_NS = 86_400_000_000_000


def floor_day_ns(ns: np.ndarray) -> np.ndarray:
    """Timestamp -> midnight of its day (the reference's .dt.date join key,
    rq2_coverage_and_added.py:124)."""
    return (np.asarray(ns) // DAY_NS) * DAY_NS


class PandasBackend(Backend):
    name = "pandas"

    def rq1_detection(self, arrays: StudyArrays, limit_date_ns: int,
                      min_projects: int) -> RQ1Result:
        P = arrays.n_projects
        n_builds = arrays.fuzz.counts()
        max_iter = int(n_builds.max()) if P else 0

        # Phase 1 — per-iteration project population
        # (rq1_detection_rate.py:192-201): iteration k has one slot per
        # project with >= k builds.
        totals = np.zeros(max_iter, dtype=np.int64)
        for c in n_builds:
            totals[: int(c)] += 1

        # Phase 2 — map each fixed issue to its iteration and its matched
        # successful build (rq1_detection_rate.py:215-230 + the
        # SAME_DATE_BUILD_ISSUE join).
        n_issues = len(arrays.issues)
        iteration_of_issue = np.zeros(n_issues, dtype=np.int64)
        link_idx = np.full(n_issues, -1, dtype=np.int64)
        detected = [set() for _ in range(max_iter + 1)]  # 1-based

        for p in range(P):
            ilo, ihi = arrays.issues.offsets[p], arrays.issues.offsets[p + 1]
            if ihi == ilo:
                continue
            flo = arrays.fuzz.offsets[p]
            seg = arrays.fuzz.segment(p)
            btimes = seg["time_ns"]
            ok = seg["ok"] & (btimes < limit_date_ns)
            ok_pos = np.flatnonzero(ok)
            ok_times = btimes[ok_pos]
            itimes = arrays.issues.columns["time_ns"][ilo:ihi]

            # iteration = #builds strictly before rts (strict '>' in the
            # reference, rq1:226) -> searchsorted side='left'.
            iters = np.searchsorted(btimes, itimes, side="left")
            iteration_of_issue[ilo:ihi] = iters

            # linkage: latest successful pre-cutoff build strictly before rts.
            pos = np.searchsorted(ok_times, itimes, side="left")
            has_link = pos > 0
            link_idx[ilo:ihi][has_link] = flo + ok_pos[pos[has_link] - 1]

            for it, lnk in zip(iters, has_link):
                if lnk and 0 < it <= max_iter:
                    detected[int(it)].add(p)

        detected_counts = np.array([len(detected[k]) for k in range(1, max_iter + 1)],
                                   dtype=np.int64)

        keep = totals >= min_projects
        iterations = np.flatnonzero(keep) + 1
        return RQ1Result(
            iterations=iterations,
            total_projects=totals[keep],
            detected_counts=detected_counts[keep],
            iteration_of_issue=iteration_of_issue,
            link_idx=link_idx,
        )

    def rq2_change_points(self, arrays: StudyArrays,
                          limit_date_ns: int) -> RQ2ChangePointsResult:
        # Mirrors the reference's per-project loop: collapse consecutive
        # identical (modules, revisions) coverage builds into groups
        # (rq2_coverage_and_added.py:129-149), pair each group's last build
        # with the next group's first (rq2:152-166), join both sides to the
        # same-day total_coverage row (rq2:170-184).
        out = {k: [] for k in ("project_idx", "end_i", "start_ip1",
                               "covered_i", "total_i", "covered_ip1",
                               "total_ip1")}
        covb_t = arrays.covb.columns["time_ns"]
        ghash = arrays.covb.columns["grouphash"]
        for p in range(arrays.n_projects):
            lo, hi = arrays.covb.offsets[p], arrays.covb.offsets[p + 1]
            rows = np.arange(lo, hi)[covb_t[lo:hi] < limit_date_ns]
            clo, chi = arrays.cov.offsets[p], arrays.cov.offsets[p + 1]
            if rows.size == 0 or chi == clo:
                continue  # reference skips projects missing either input
            cov_days = arrays.cov.columns["date_ns"][clo:chi]
            cov_covered = arrays.cov.columns["covered"][clo:chi]
            cov_total = arrays.cov.columns["total"][clo:chi]

            g = ghash[rows]
            new_group = np.concatenate([[True], g[1:] != g[:-1]])
            starts = rows[new_group]
            ends = np.concatenate([rows[np.flatnonzero(new_group)[1:] - 1],
                                   rows[-1:]])

            def day_row(day_ns):
                j = np.searchsorted(cov_days, day_ns, side="left")
                if j < cov_days.size and cov_days[j] == day_ns:
                    return cov_covered[j], cov_total[j]
                return np.nan, np.nan

            for i in range(len(starts) - 1):
                e, s1 = ends[i], starts[i + 1]
                ci, ti = day_row(floor_day_ns(covb_t[e]))
                cp, tp = day_row(floor_day_ns(covb_t[s1]))
                out["project_idx"].append(p)
                out["end_i"].append(e)
                out["start_ip1"].append(s1)
                out["covered_i"].append(ci)
                out["total_i"].append(ti)
                out["covered_ip1"].append(cp)
                out["total_ip1"].append(tp)
        return RQ2ChangePointsResult(
            project_idx=np.array(out["project_idx"], dtype=np.int64),
            end_i=np.array(out["end_i"], dtype=np.int64),
            start_ip1=np.array(out["start_ip1"], dtype=np.int64),
            covered_i=np.array(out["covered_i"], dtype=np.float64),
            total_i=np.array(out["total_i"], dtype=np.float64),
            covered_ip1=np.array(out["covered_ip1"], dtype=np.float64),
            total_ip1=np.array(out["total_ip1"], dtype=np.float64),
        )

    def rq2_trends(self, arrays: StudyArrays) -> RQ2TrendsResult:
        from scipy.stats import spearmanr

        P = arrays.n_projects
        trends = []
        for p in range(P):
            seg = arrays.cov.segment(p)
            sel = (~np.isnan(seg["coverage"])) & (seg["coverage"] != 0)
            covered, total = seg["covered"][sel], seg["total"][sel]
            keep = total != 0  # reference drops zero-total sessions (rq2:302)
            trends.append(covered[keep] / total[keep] * 100.0)

        S = max((len(t) for t in trends), default=0)
        matrix = np.full((P, S), np.nan)
        mask = np.zeros((P, S), dtype=bool)
        spear = np.full(P, np.nan)
        for p, t in enumerate(trends):
            matrix[p, :len(t)] = t
            mask[p, :len(t)] = True
            if len(t) >= 2:
                corr, _ = spearmanr(range(len(t)), t)
                spear[p] = corr

        counts = mask.sum(axis=0)
        pcts = np.full((len(RQ2TrendsResult.PCTS), S), np.nan)
        mean = np.full(S, np.nan)
        for s in range(S):
            col = matrix[mask[:, s], s]
            if col.size:
                pcts[:, s] = np.percentile(col, RQ2TrendsResult.PCTS)
                mean[s] = col.mean()
        return RQ2TrendsResult(matrix=matrix, mask=mask, spearman=spear,
                               percentiles=pcts, mean=mean, counts=counts)
