"""Host (pandas/numpy) backend — the semantic reference implementation.

Mirrors the reference's Python-loop logic (rq1_detection_rate.py:189-268)
project-by-project, but over the columnar arrays instead of N+1 SQL, so it is
already orders of magnitude faster than the original while remaining the
exact-semantics oracle the jax_tpu backend is parity-tested against.
"""

from __future__ import annotations

import numpy as np

from .base import (Backend, RQ1Result, RQ2ChangePointsResult, RQ2TrendsResult,
                   RQ3Result, RQ4aTrendResult, RQ4bTrendsResult)
from ..data.columnar import StudyArrays

DAY_NS = 86_400_000_000_000
HOUR_NS = 3_600_000_000_000


def floor_day_ns(ns: np.ndarray) -> np.ndarray:
    """Timestamp -> midnight of its day (the reference's .dt.date join key,
    rq2_coverage_and_added.py:124)."""
    return (np.asarray(ns) // DAY_NS) * DAY_NS


class PandasBackend(Backend):
    name = "pandas"

    def rq1_detection(self, arrays: StudyArrays, limit_date_ns: int,
                      min_projects: int) -> RQ1Result:
        P = arrays.n_projects
        n_builds = arrays.fuzz.counts()
        max_iter = int(n_builds.max()) if P else 0

        # Phase 1 — per-iteration project population
        # (rq1_detection_rate.py:192-201): iteration k has one slot per
        # project with >= k builds.
        totals = np.zeros(max_iter, dtype=np.int64)
        for c in n_builds:
            totals[: int(c)] += 1

        # Phase 2 — map each fixed issue to its iteration and its matched
        # successful build (rq1_detection_rate.py:215-230 + the
        # SAME_DATE_BUILD_ISSUE join).
        n_issues = len(arrays.issues)
        iteration_of_issue = np.zeros(n_issues, dtype=np.int64)
        link_idx = np.full(n_issues, -1, dtype=np.int64)
        detected = [set() for _ in range(max_iter + 1)]  # 1-based

        for p in range(P):
            ilo, ihi = arrays.issues.offsets[p], arrays.issues.offsets[p + 1]
            if ihi == ilo:
                continue
            flo = arrays.fuzz.offsets[p]
            seg = arrays.fuzz.segment(p)
            btimes = seg["time_ns"]
            ok = seg["ok"] & (btimes < limit_date_ns)
            ok_pos = np.flatnonzero(ok)
            ok_times = btimes[ok_pos]
            itimes = arrays.issues.columns["time_ns"][ilo:ihi]

            # iteration = #builds strictly before rts (strict '>' in the
            # reference, rq1:226) -> searchsorted side='left'.
            iters = np.searchsorted(btimes, itimes, side="left")
            iteration_of_issue[ilo:ihi] = iters

            # linkage: latest successful pre-cutoff build strictly before rts.
            pos = np.searchsorted(ok_times, itimes, side="left")
            has_link = pos > 0
            link_idx[ilo:ihi][has_link] = flo + ok_pos[pos[has_link] - 1]

            for it, lnk in zip(iters, has_link):
                if lnk and 0 < it <= max_iter:
                    detected[int(it)].add(p)

        detected_counts = np.array([len(detected[k]) for k in range(1, max_iter + 1)],
                                   dtype=np.int64)

        keep = totals >= min_projects
        iterations = np.flatnonzero(keep) + 1
        return RQ1Result(
            iterations=iterations,
            total_projects=totals[keep],
            detected_counts=detected_counts[keep],
            iteration_of_issue=iteration_of_issue,
            link_idx=link_idx,
        )

    def rq2_change_points(self, arrays: StudyArrays,
                          limit_date_ns: int) -> RQ2ChangePointsResult:
        # Mirrors the reference's per-project loop: collapse consecutive
        # identical (modules, revisions) coverage builds into groups
        # (rq2_coverage_and_added.py:129-149), pair each group's last build
        # with the next group's first (rq2:152-166), join both sides to the
        # same-day total_coverage row (rq2:170-184).
        out = {k: [] for k in ("project_idx", "end_i", "start_ip1",
                               "covered_i", "total_i", "covered_ip1",
                               "total_ip1")}
        covb_t = arrays.covb.columns["time_ns"]
        covb_ok = arrays.covb.columns["ok"]
        ghash = arrays.covb.columns["grouphash"]
        for p in range(arrays.n_projects):
            lo, hi = arrays.covb.offsets[p], arrays.covb.offsets[p + 1]
            # Successful pre-cutoff coverage builds only (the reference's
            # GET_BUILD_LOGS filter, rq2_coverage_and_added.py:60-68).
            rows = np.arange(lo, hi)[(covb_t[lo:hi] < limit_date_ns)
                                     & covb_ok[lo:hi]]
            clo, chi = arrays.cov.offsets[p], arrays.cov.offsets[p + 1]
            # cov rows are fetched to limit+1 day; this RQ joins against
            # pre-cutoff rows only (reference rq2:44 fetches date < limit).
            cov_in = arrays.cov.columns["date_ns"][clo:chi] < limit_date_ns
            if rows.size == 0 or not cov_in.any():
                continue  # reference skips projects missing either input
            cov_days = arrays.cov.columns["date_ns"][clo:chi][cov_in]
            cov_covered = arrays.cov.columns["covered"][clo:chi][cov_in]
            cov_total = arrays.cov.columns["total"][clo:chi][cov_in]

            g = ghash[rows]
            new_group = np.concatenate([[True], g[1:] != g[:-1]])
            starts = rows[new_group]
            ends = np.concatenate([rows[np.flatnonzero(new_group)[1:] - 1],
                                   rows[-1:]])

            def day_row(day_ns):
                j = np.searchsorted(cov_days, day_ns, side="left")
                if j < cov_days.size and cov_days[j] == day_ns:
                    return cov_covered[j], cov_total[j]
                return np.nan, np.nan

            for i in range(len(starts) - 1):
                e, s1 = ends[i], starts[i + 1]
                ci, ti = day_row(floor_day_ns(covb_t[e]))
                cp, tp = day_row(floor_day_ns(covb_t[s1]))
                out["project_idx"].append(p)
                out["end_i"].append(e)
                out["start_ip1"].append(s1)
                out["covered_i"].append(ci)
                out["total_i"].append(ti)
                out["covered_ip1"].append(cp)
                out["total_ip1"].append(tp)
        return RQ2ChangePointsResult(
            project_idx=np.array(out["project_idx"], dtype=np.int64),
            end_i=np.array(out["end_i"], dtype=np.int64),
            start_ip1=np.array(out["start_ip1"], dtype=np.int64),
            covered_i=np.array(out["covered_i"], dtype=np.float64),
            total_i=np.array(out["total_i"], dtype=np.float64),
            covered_ip1=np.array(out["covered_ip1"], dtype=np.float64),
            total_ip1=np.array(out["total_ip1"], dtype=np.float64),
        )

    def rq3_coverage_at_detection(self, arrays: StudyArrays,
                                  limit_date_ns: int) -> RQ3Result:
        """Oracle mirror of the reference's per-issue loop
        (rq3_diff_coverage_at_detection.py:241-302), with three documented
        deviations: (a) result filters use the canonical RESULT_OK enum (the
        reference's 'HalfWay' spelling matched only 'Finish' rows, rq3:261,
        274); (b) revision-set equality is exact over parsed arrays (the
        reference's ``[1:-2].split(',')`` truncates the final element's last
        character, rq3:280); (c) the final project's non-detected pairs are
        included (the reference only flushes them on project *change*,
        rq3:246-257, silently dropping the last project)."""
        det = {k: [] for k in ("pct", "cov", "tot", "proj", "issue", "rts")}
        nondet = {k: [] for k in ("pct", "cov", "tot", "proj")}
        fuzz_t = arrays.fuzz.columns["time_ns"]
        fuzz_ok = arrays.fuzz.columns["ok"]
        covb_t = arrays.covb.columns["time_ns"]
        covb_ok = arrays.covb.columns["ok"]
        issue_t = arrays.issues.columns["time_ns"]
        cutoff_plus1 = limit_date_ns + DAY_NS

        for p in range(arrays.n_projects):
            ilo, ihi = arrays.issues.offsets[p], arrays.issues.offsets[p + 1]
            if ihi == ilo:
                continue  # projects without fixed issues never enter rq3:241
            flo, fhi = arrays.fuzz.offsets[p], arrays.fuzz.offsets[p + 1]
            fsel = np.flatnonzero(fuzz_ok[flo:fhi]
                                  & (fuzz_t[flo:fhi] < limit_date_ns)) + flo
            ftimes = fuzz_t[fsel]
            clo, chi = arrays.covb.offsets[p], arrays.covb.offsets[p + 1]
            csel = np.flatnonzero(covb_t[clo:chi] < cutoff_plus1) + clo
            ctimes = covb_t[csel]
            vseg = arrays.cov.segment(p)
            vsel = ~np.isnan(vseg["covered"])
            days = vseg["date_ns"][vsel]
            covered = vseg["covered"][vsel]
            total = vseg["total"][vsel]
            detected_days = set()
            # Empty inputs skip issue *processing* only (rq3:266); the
            # non-detected flush still runs for the project (rq3:246-257).
            can_detect = ftimes.size and ctimes.size and days.size
            for j in range(ilo, ihi) if can_detect else ():
                rts = issue_t[j]
                k = np.searchsorted(ftimes, rts, side="left") - 1
                if k < 0:
                    continue  # no fuzzing build strictly before rts (rq3:269)
                m = np.searchsorted(ctimes, rts, side="right")
                if m >= ctimes.size or not covb_ok[csel[m]]:
                    continue  # rq3:273-274
                if ctimes[m] - ftimes[k] > 24 * HOUR_NS:
                    continue  # rq3:277
                if (arrays.fuzz_revhash_at([fsel[k]])[0]
                        != arrays.covb_revhash_at([csel[m]])[0]):
                    continue  # rq3:280
                target = floor_day_ns(rts) + DAY_NS
                i = int(np.searchsorted(days, target, side="left"))
                if i == 0 or i >= days.size or days[i] != target:
                    continue  # day-after row absent (rq3:287-293)
                if covered[i] == 0:
                    continue  # rq3:289-290 breaks the search -> issue skipped
                if total[i - 1] > 0 and total[i] > 0:
                    det["pct"].append((covered[i] / total[i]
                                       - covered[i - 1] / total[i - 1]) * 100.0)
                    det["cov"].append(covered[i] - covered[i - 1])
                    det["tot"].append(total[i] - total[i - 1])
                    det["proj"].append(p)
                    det["issue"].append(j)
                    det["rts"].append(rts)
                    detected_days.add(floor_day_ns(rts))

            for i in range(1, days.size):
                if days[i] in detected_days:
                    continue  # exclusion key = issue report date (rq3:249-251)
                if total[i - 1] > 0 and total[i] > 0:
                    nondet["pct"].append((covered[i] / total[i]
                                          - covered[i - 1] / total[i - 1]) * 100.0)
                    nondet["cov"].append(covered[i] - covered[i - 1])
                    nondet["tot"].append(total[i] - total[i - 1])
                    nondet["proj"].append(p)

        return RQ3Result(
            det_diff_percent=np.array(det["pct"], dtype=np.float64),
            det_diff_covered=np.array(det["cov"], dtype=np.float64),
            det_diff_total=np.array(det["tot"], dtype=np.float64),
            det_project_idx=np.array(det["proj"], dtype=np.int64),
            det_issue_idx=np.array(det["issue"], dtype=np.int64),
            det_issue_time_ns=np.array(det["rts"], dtype=np.int64),
            nondet_diff_percent=np.array(nondet["pct"], dtype=np.float64),
            nondet_diff_covered=np.array(nondet["cov"], dtype=np.float64),
            nondet_diff_total=np.array(nondet["tot"], dtype=np.float64),
            nondet_project_idx=np.array(nondet["proj"], dtype=np.int64),
        )

    def rq4a_detection_trend(self, arrays: StudyArrays, limit_date_ns: int,
                             g1_idx: np.ndarray, g2_idx: np.ndarray,
                             min_projects: int) -> RQ4aTrendResult:
        """Oracle mirror of the reference's G1/G2 loop (rq4a_bug.py:324-346):
        ALL fuzzing builds before the cutoff define iterations; a fixed
        issue marks its project detected at k = #builds before rts."""
        fuzz_t = arrays.fuzz.columns["time_ns"]
        issue_t = arrays.issues.columns["time_ns"]
        per_group = {}
        max_iter = 0
        for key, idx in (("g1", g1_idx), ("g2", g2_idx)):
            counts = {}
            detected: dict[int, set] = {}
            for p in idx:
                flo, fhi = arrays.fuzz.offsets[p], arrays.fuzz.offsets[p + 1]
                btimes = fuzz_t[flo:fhi][fuzz_t[flo:fhi] < limit_date_ns]
                if btimes.size == 0:
                    continue  # rq4a:335-336
                counts[p] = btimes.size
                max_iter = max(max_iter, btimes.size)
                ilo, ihi = (arrays.issues.offsets[p],
                            arrays.issues.offsets[p + 1])
                ks = np.searchsorted(btimes, issue_t[ilo:ihi], side="left")
                for k in ks[ks > 0]:
                    detected.setdefault(int(k), set()).add(int(p))
            per_group[key] = (counts, detected)

        totals = {}
        dets = {}
        for key, (counts, detected) in per_group.items():
            tot = np.zeros(max_iter, dtype=np.int64)
            for c in counts.values():
                tot[:c] += 1
            det = np.array([len(detected.get(k, ())) for k in
                            range(1, max_iter + 1)], dtype=np.int64)
            totals[key], dets[key] = tot, det

        valid = ((totals["g1"] >= min_projects)
                 & (totals["g2"] >= min_projects)) if max_iter else \
            np.zeros(0, dtype=bool)
        keep = np.flatnonzero(valid)
        return RQ4aTrendResult(
            iterations=keep + 1,
            g1_total=totals["g1"][keep] if max_iter else np.empty(0, np.int64),
            g1_detected=dets["g1"][keep] if max_iter else np.empty(0, np.int64),
            g2_total=totals["g2"][keep] if max_iter else np.empty(0, np.int64),
            g2_detected=dets["g2"][keep] if max_iter else np.empty(0, np.int64),
        )

    def rq4b_group_trends(self, arrays: StudyArrays, limit_date_ns: int,
                          g1_idx: np.ndarray, g2_idx: np.ndarray,
                          percentiles: tuple = (25, 50, 75)
                          ) -> RQ4bTrendsResult:
        """Oracle mirror of the reference's ragged per-session aggregation
        (rq4b_coverage.py:914-976): trend = raw coverage column (non-null,
        > 0, pre-cutoff), session-indexed densely per project."""
        P = arrays.n_projects
        trends = []
        for p in range(P):
            seg = arrays.cov.segment(p)
            sel = ((~np.isnan(seg["coverage"])) & (seg["coverage"] > 0)
                   & (seg["date_ns"] < limit_date_ns))
            trends.append(seg["coverage"][sel])
        S = max((len(t) for t in trends), default=0)
        matrix = np.full((P, S), np.nan)
        mask = np.zeros((P, S), dtype=bool)
        for p, t in enumerate(trends):
            matrix[p, :len(t)] = t
            mask[p, :len(t)] = True

        out = {}
        for key, idx in (("g1", np.asarray(g1_idx, dtype=np.int64)),
                         ("g2", np.asarray(g2_idx, dtype=np.int64))):
            pcts = np.full((len(percentiles), S), np.nan)
            counts = np.zeros(S, dtype=np.int64)
            for s in range(S):
                col = matrix[idx, s][mask[idx, s]]
                counts[s] = col.size
                if col.size:
                    pcts[:, s] = np.percentile(col, percentiles)
            out[key] = (pcts, counts)
        return RQ4bTrendsResult(
            percentiles=tuple(percentiles), matrix=matrix, mask=mask,
            g1_percentiles=out["g1"][0], g1_counts=out["g1"][1],
            g2_percentiles=out["g2"][0], g2_counts=out["g2"][1],
        )

    def rq2_trends(self, arrays: StudyArrays,
                   limit_date_ns: int) -> RQ2TrendsResult:
        from scipy.stats import spearmanr

        P = arrays.n_projects
        trends = []
        for p in range(P):
            seg = arrays.cov.segment(p)
            sel = ((~np.isnan(seg["coverage"])) & (seg["coverage"] != 0)
                   & (seg["date_ns"] < limit_date_ns))
            covered, total = seg["covered"][sel], seg["total"][sel]
            # Reference drops zero-total sessions (rq2:302); rows with
            # non-null coverage but NULL covered/total lines must drop too
            # (NaN passes a bare != 0).
            keep = (total != 0) & ~np.isnan(total) & ~np.isnan(covered)
            trends.append(covered[keep] / total[keep] * 100.0)

        S = max((len(t) for t in trends), default=0)
        matrix = np.full((P, S), np.nan)
        mask = np.zeros((P, S), dtype=bool)
        spear = np.full(P, np.nan)
        for p, t in enumerate(trends):
            matrix[p, :len(t)] = t
            mask[p, :len(t)] = True
            if len(t) >= 2:
                corr, _ = spearmanr(range(len(t)), t)
                spear[p] = corr

        counts = mask.sum(axis=0)
        pcts = np.full((len(RQ2TrendsResult.PCTS), S), np.nan)
        mean = np.full(S, np.nan)
        for s in range(S):
            col = matrix[mask[:, s], s]
            if col.size:
                pcts[:, s] = np.percentile(col, RQ2TrendsResult.PCTS)
                mean[s] = col.mean()
        return RQ2TrendsResult(matrix=matrix, mask=mask, spearman=spear,
                               percentiles=pcts, mean=mean, counts=counts)
