"""Host (pandas/numpy) backend — the semantic reference implementation.

Mirrors the reference's Python-loop logic (rq1_detection_rate.py:189-268)
project-by-project, but over the columnar arrays instead of N+1 SQL, so it is
already orders of magnitude faster than the original while remaining the
exact-semantics oracle the jax_tpu backend is parity-tested against.
"""

from __future__ import annotations

import numpy as np

from .base import Backend, RQ1Result
from ..data.columnar import StudyArrays


class PandasBackend(Backend):
    name = "pandas"

    def rq1_detection(self, arrays: StudyArrays, limit_date_ns: int,
                      min_projects: int) -> RQ1Result:
        P = arrays.n_projects
        n_builds = arrays.fuzz.counts()
        max_iter = int(n_builds.max()) if P else 0

        # Phase 1 — per-iteration project population
        # (rq1_detection_rate.py:192-201): iteration k has one slot per
        # project with >= k builds.
        totals = np.zeros(max_iter, dtype=np.int64)
        for c in n_builds:
            totals[: int(c)] += 1

        # Phase 2 — map each fixed issue to its iteration and its matched
        # successful build (rq1_detection_rate.py:215-230 + the
        # SAME_DATE_BUILD_ISSUE join).
        n_issues = len(arrays.issues)
        iteration_of_issue = np.zeros(n_issues, dtype=np.int64)
        link_idx = np.full(n_issues, -1, dtype=np.int64)
        detected = [set() for _ in range(max_iter + 1)]  # 1-based

        for p in range(P):
            ilo, ihi = arrays.issues.offsets[p], arrays.issues.offsets[p + 1]
            if ihi == ilo:
                continue
            flo = arrays.fuzz.offsets[p]
            seg = arrays.fuzz.segment(p)
            btimes = seg["time_ns"]
            ok = seg["ok"] & (btimes < limit_date_ns)
            ok_pos = np.flatnonzero(ok)
            ok_times = btimes[ok_pos]
            itimes = arrays.issues.columns["time_ns"][ilo:ihi]

            # iteration = #builds strictly before rts (strict '>' in the
            # reference, rq1:226) -> searchsorted side='left'.
            iters = np.searchsorted(btimes, itimes, side="left")
            iteration_of_issue[ilo:ihi] = iters

            # linkage: latest successful pre-cutoff build strictly before rts.
            pos = np.searchsorted(ok_times, itimes, side="left")
            has_link = pos > 0
            link_idx[ilo:ihi][has_link] = flo + ok_pos[pos[has_link] - 1]

            for it, lnk in zip(iters, has_link):
                if lnk and 0 < it <= max_iter:
                    detected[int(it)].add(p)

        detected_counts = np.array([len(detected[k]) for k in range(1, max_iter + 1)],
                                   dtype=np.int64)

        keep = totals >= min_projects
        iterations = np.flatnonzero(keep) + 1
        return RQ1Result(
            iterations=iterations,
            total_projects=totals[keep],
            detected_counts=detected_counts[keep],
            iteration_of_issue=iteration_of_issue,
            link_idx=link_idx,
        )
