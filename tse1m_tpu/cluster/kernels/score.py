"""Batched signature-agreement top-k scoring over the mmap'd store.

The serve plane's query path answers membership host-side; this module
is the raw-speed lever behind the ``topk`` verb's full-scan mode and
the ``backfill`` re-label driver: score a batch of query signatures
against EVERY stored signature by exact agreement count (the same
``(sig_u == sig_v).sum(axis=1)`` rule ``verify_edges``/``query_labels``
use), keeping only each query's top-k rows.

Three implementations, bit-identical by construction (the schemes.py
idiom):

- :func:`score_topk_host` — the numpy mirror (the oracle the bench's
  ``topk_recall`` key is pinned at 1.0 against);
- a jitted jnp ``fori_loop`` reference (`_topk_chunk_jnp`) — runs
  everywhere, is the CPU path;
- a pallas VMEM-blocked kernel (`_score_topk_kernel`): per grid step
  one [H, BN] store tile is scored against the resident [Qp, H] query
  block (static unroll over the H hash lanes — H broadcast compares on
  the VPU, no [Qp, BN, H] intermediate), and the running per-query
  top-k state is merged IN the kernel (fused partial reduction), so
  only [Qp, K_PAD] state ever leaves VMEM per chunk.

Determinism contract shared by all three: rank by (-agreement count,
ascending global row); slots past the valid row count hold
``(-1, -1)``.  Merging exact per-chunk top-k states is therefore
associative across the store scan and the result is elementwise-equal
to a single-shot host scan.

Streaming (:func:`bulk_topk_store`): store shards are walked in sorted
shard-id order as fixed-size row chunks (the LAST chunk of a shard is
padded, never reshaped), each chunk transposed host-side and shipped
through an explicit double-buffered ``device_put`` (the
``pipeline._iter_streamed`` shape: chunk k+1 stages on a producer
thread while chunk k computes).  Fixed chunk shapes + pow2-padded query
batches (the ``minhash_novel_rows`` compile-cache pattern) make the
steady state zero-recompile — the bench's topk round runs the loop
under ``lint.runtime.sanitized(0)``.

This module is a blessed ``wire-layer`` seat (graftlint): its
device_puts ARE the scoring plane's transfers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Top-k state width: one VPU lane tile.  ``k`` beyond this would need a
# second state tile per query; the serve verb clamps to it.
K_PAD = 128

# Sentinel row for empty/padded slots: loses every (count desc, row
# asc) tie to a real row, and survives int32 round-trips.
ROW_INF = np.int32(2**31 - 1)

_SCORE_PALLAS_OK = True


def _require_k(k: int) -> int:
    k = int(k)
    if not 0 <= k <= K_PAD:
        raise ValueError(f"topk k={k} outside [0, {K_PAD}] (one VPU "
                         "state tile per query)")
    return k


# -- numpy host mirror (the oracle) ------------------------------------------

def score_topk_host(query_sigs: np.ndarray, store_sigs: np.ndarray,
                    k: int, block_rows: int = 4096
                    ) -> tuple[np.ndarray, np.ndarray]:
    """[Q, H] x [N, H] uint32 -> (counts [Q, k] int32, rows [Q, k]
    int32), ranked by (-agreement, ascending row); ``-1`` pads both
    past ``min(k, N)``.  Exact and allocation-bounded (the [Q, N]
    count matrix is filled ``block_rows`` store rows at a time)."""
    k = _require_k(k)
    q = np.ascontiguousarray(query_sigs, np.uint32)
    s = np.ascontiguousarray(store_sigs, np.uint32)
    nq, n = int(q.shape[0]), int(s.shape[0])
    counts_out = np.full((nq, k), -1, np.int32)
    rows_out = np.full((nq, k), -1, np.int32)
    if nq == 0 or n == 0 or k == 0:
        return counts_out, rows_out
    counts = np.empty((nq, n), np.int32)
    for lo in range(0, n, block_rows):
        blk = s[lo:lo + block_rows]
        counts[:, lo:lo + blk.shape[0]] = (
            q[:, None, :] == blk[None, :, :]).sum(axis=2, dtype=np.int32)
    # Stable argsort on negated counts: ties resolve to the ascending
    # original row — exactly the device selection order.
    order = np.argsort(-counts, axis=1, kind="stable")[:, :k]
    m = min(k, n)
    rows_out[:, :m] = order[:, :m].astype(np.int32)
    counts_out[:, :m] = np.take_along_axis(counts, order, axis=1)[:, :m]
    return counts_out, rows_out


# -- shared device selection (jnp; runs on the VPU inside the kernel) --------

def _merge_topk(topc, topr, counts, rows, k: int):
    """Merge a [Qp, BN] tile of (count, row) candidates into the
    running [Qp, K_PAD] top-k state.  ``k`` static selection steps,
    each: max count over both sources, min row among the maxima, write
    slot t, retire the winner.  Rows are globally unique across state
    and tile, so the selection is deterministic; exhausted sources
    surface negative counts which the finalize step maps to (-1, -1)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, topc.shape, 1)
    newc = jnp.full_like(topc, -1)
    newr = jnp.full_like(topr, ROW_INF)
    for t in range(k):
        best = jnp.maximum(jnp.max(counts, axis=1, keepdims=True),
                           jnp.max(topc, axis=1, keepdims=True))
        brow = jnp.minimum(
            jnp.min(jnp.where(counts == best, rows, ROW_INF),
                    axis=1, keepdims=True),
            jnp.min(jnp.where(topc == best, topr, ROW_INF),
                    axis=1, keepdims=True))
        newc = jnp.where(lane == t, best, newc)
        newr = jnp.where(lane == t, brow, newr)
        counts = jnp.where((counts == best) & (rows == brow),
                           jnp.int32(-2), counts)
        topc = jnp.where((topc == best) & (topr == brow),
                         jnp.int32(-2), topc)
    return newc, newr


# -- jnp fori_loop reference -------------------------------------------------

@functools.partial(jax.jit, static_argnames=("k", "block_n"))
def _topk_chunk_jnp(q, s_t, rowids, topc, topr, k: int, block_n: int):
    """One chunk of the scan, jnp reference: q [Qp, H] uint32, s_t
    [H, Np] uint32 (transposed chunk), rowids [1, Np] int32 (global
    rows; ROW_INF on padding), state [Qp, K_PAD] int32 pair."""
    n_tiles = s_t.shape[1] // block_n

    def body(t, state):
        tc, tr = state
        tile = jax.lax.dynamic_slice_in_dim(s_t, t * block_n, block_n, 1)
        rid = jax.lax.dynamic_slice_in_dim(rowids, t * block_n, block_n, 1)
        counts = jnp.sum((q[:, :, None] == tile[None, :, :])
                         .astype(jnp.int32), axis=1)
        rows = jnp.broadcast_to(rid, counts.shape)
        counts = jnp.where(rows < ROW_INF, counts, jnp.int32(-1))
        return _merge_topk(tc, tr, counts, rows, k)

    return jax.lax.fori_loop(0, n_tiles, body, (topc, topr))


# -- pallas VMEM-blocked kernel ----------------------------------------------

def _score_topk_kernel(q_ref, s_ref, rid_ref, inc_ref, inr_ref,
                       outc_ref, outr_ref, *, k: int):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        outc_ref[...] = inc_ref[...]
        outr_ref[...] = inr_ref[...]

    q = q_ref[...]                       # [Qp, H] uint32, VMEM-resident
    qp, h = q.shape
    bn = s_ref.shape[1]
    counts = jnp.zeros((qp, bn), jnp.int32)
    for j in range(h):                   # static unroll over hash lanes
        counts = counts + (q[:, j:j + 1] == s_ref[j:j + 1, :]
                           ).astype(jnp.int32)
    rows = jnp.broadcast_to(rid_ref[...], (qp, bn))
    counts = jnp.where(rows < ROW_INF, counts, jnp.int32(-1))
    newc, newr = _merge_topk(outc_ref[...], outr_ref[...], counts, rows, k)
    outc_ref[...] = newc
    outr_ref[...] = newr


@functools.partial(jax.jit, static_argnames=("k", "block_n", "interpret"))
def _topk_chunk_pallas(q, s_t, rowids, topc, topr, k: int, block_n: int,
                       interpret: bool):
    from jax.experimental import pallas as pl

    qp, h = q.shape
    n = s_t.shape[1]
    assert n % block_n == 0, (n, block_n)
    kernel = functools.partial(_score_topk_kernel, k=k)
    return pl.pallas_call(
        kernel,
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec((qp, h), lambda i: (0, 0)),
            pl.BlockSpec((h, block_n), lambda i: (0, i)),
            pl.BlockSpec((1, block_n), lambda i: (0, i)),
            pl.BlockSpec((qp, K_PAD), lambda i: (0, 0)),
            pl.BlockSpec((qp, K_PAD), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((qp, K_PAD), lambda i: (0, 0)),
            pl.BlockSpec((qp, K_PAD), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((qp, K_PAD), jnp.int32),
            jax.ShapeDtypeStruct((qp, K_PAD), jnp.int32),
        ],
        interpret=interpret,
    )(q, s_t, rowids, topc, topr)


# -- dispatch ----------------------------------------------------------------

def _resolve_mode(use_pallas: str) -> str:
    if use_pallas == "auto":
        return "force" if jax.default_backend() == "tpu" else "never"
    return use_pallas


def _score_chunk(q_d, s_t_d, rid_d, topc, topr, k: int, block_n: int,
                 mode: str):
    """One chunk through the resolved backend, with the one-shot pallas
    breaker (the minhash_pallas idiom: a Mosaic lowering gap downgrades
    to the bit-identical jnp reference for the process lifetime)."""
    global _SCORE_PALLAS_OK
    if mode in ("force", "interpret") and _SCORE_PALLAS_OK:
        try:
            return _topk_chunk_pallas(q_d, s_t_d, rid_d, topc, topr, k,
                                      block_n, mode == "interpret")
        except Exception as e:  # graftlint: disable=broad-except -- compiler rejections are arbitrary; fallback is bit-identical
            _SCORE_PALLAS_OK = False
            from ...utils.logging import get_logger

            get_logger("cluster.pallas").warning(
                "topk scoring pallas kernel unavailable (%s: %s); "
                "falling back to the jnp reference", type(e).__name__, e)
    return _topk_chunk_jnp(q_d, s_t_d, rid_d, topc, topr, k, block_n)


def _pad_queries(query_sigs: np.ndarray) -> np.ndarray:
    """pow2 row padding (min 8 — the f32/i32 sublane tile): a serving
    process compiles O(log max-batch) query shapes, not one per k."""
    nq = int(query_sigs.shape[0])
    padded = max(8, 1 << max(0, nq - 1).bit_length())
    if padded == nq:
        return query_sigs
    out = np.zeros((padded, query_sigs.shape[1]), np.uint32)
    out[:nq] = query_sigs
    return out


def _init_state(qp: int):
    topc = jax.device_put(np.full((qp, K_PAD), -1, np.int32))
    topr = jax.device_put(np.full((qp, K_PAD), ROW_INF, np.int32))
    return topc, topr


def _stage_chunk(sig_rows: np.ndarray, base_row: int, chunk_rows: int):
    """Host half of one scan chunk: transpose to the kernel's [H, Np]
    layout, pad to the fixed chunk width (padding rows carry ROW_INF
    ids, so they score -1 and lose every selection), then an explicit
    device_put with a completion wait — the producer-thread half of the
    double buffer, exactly `pipeline._produce_chunk`'s shape."""
    c = int(sig_rows.shape[0])
    h = int(sig_rows.shape[1])
    s_t = np.zeros((h, chunk_rows), np.uint32)
    s_t[:, :c] = np.ascontiguousarray(sig_rows, np.uint32).T
    rid = np.full((1, chunk_rows), ROW_INF, np.int32)
    rid[0, :c] = np.arange(base_row, base_row + c, dtype=np.int32)
    s_d = jax.device_put(s_t)
    rid_d = jax.device_put(rid)
    jax.block_until_ready(rid_d)
    return s_d, rid_d


def _finalize(topc, topr, nq: int, k: int
              ) -> tuple[np.ndarray, np.ndarray]:
    counts = np.asarray(topc)[:nq, :k].astype(np.int32, copy=True)
    rows = np.asarray(topr)[:nq, :k].astype(np.int32, copy=True)
    empty = counts < 0
    counts[empty] = -1
    rows[empty] = -1
    return counts, rows


def topk_agreement(query_sigs: np.ndarray, store_sigs: np.ndarray,
                   k: int, *, use_pallas: str = "auto",
                   block_n: int = 512) -> tuple[np.ndarray, np.ndarray]:
    """Single-shot device top-k over an in-memory [N, H] signature
    block (rows are 0..N-1).  Same contract as :func:`score_topk_host`;
    the store-streaming variant is :func:`bulk_topk_store`."""
    k = _require_k(k)
    q = np.ascontiguousarray(query_sigs, np.uint32)
    nq = int(q.shape[0])
    counts_out = np.full((nq, k), -1, np.int32)
    rows_out = np.full((nq, k), -1, np.int32)
    s = np.ascontiguousarray(store_sigs, np.uint32)
    if nq == 0 or k == 0 or s.shape[0] == 0:
        return counts_out, rows_out
    mode = _resolve_mode(use_pallas)
    qp = _pad_queries(q)
    q_d = jax.device_put(qp)
    n = int(s.shape[0])
    chunk_rows = -(-n // block_n) * block_n
    s_d, rid_d = _stage_chunk(s, 0, chunk_rows)
    topc, topr = _init_state(qp.shape[0])
    topc, topr = _score_chunk(q_d, s_d, rid_d, topc, topr, k, block_n,
                              mode)
    return _finalize(topc, topr, nq, k)


def _scan_chunks(store, chunk_rows: int):
    """Yield (sig rows [c, H] np view, global base row) over the
    store's shards in sorted-id order — the scan's global row space
    (see :func:`store_scan_locator`)."""
    base = 0
    for entry in sorted(store.shards, key=lambda e: int(e["id"])):
        sid, rows = int(entry["id"]), int(entry["rows"])
        mm = store._sig_mmap(sid)
        for lo in range(0, rows, chunk_rows):
            blk = np.asarray(mm[lo:min(lo + chunk_rows, rows)])
            yield blk, base + lo
        base += rows


def store_scan_locator(store, rows: np.ndarray) -> np.ndarray:
    """Scan-global row ids -> [K, 2] int32 (shard, row) locators under
    the sorted-shard-id scan order; ``-1`` rows map to ``(-1, -1)``."""
    rows = np.asarray(rows, np.int64)
    loc = np.full((rows.shape[0], 2), -1, np.int32)
    base = 0
    for entry in sorted(store.shards, key=lambda e: int(e["id"])):
        sid, n = int(entry["id"]), int(entry["rows"])
        sel = (rows >= base) & (rows < base + n)
        loc[sel, 0] = sid
        loc[sel, 1] = (rows[sel] - base).astype(np.int32)
        base += n
    return loc


def bulk_topk_store(store, query_sigs: np.ndarray, k: int, *,
                    use_pallas: str = "auto", block_n: int = 512,
                    chunk_rows: int = 16384, overlap: bool = True
                    ) -> tuple[np.ndarray, np.ndarray]:
    """Device-scan EVERY committed store row against [Q, H] query
    signatures; returns (counts [Q, k], rows [Q, k]) int32 over the
    scan-global row space (:func:`store_scan_locator` maps back to
    (shard, row)).  Exact — recall 1.0 vs :func:`score_topk_host` over
    the concatenated shards by construction.

    The hot loop is shape-stable: every chunk ships as exactly
    ``chunk_rows`` columns (tails padded), queries pad to pow2, and
    chunk k+1 stages on one producer thread while chunk k computes —
    steady state is zero recompiles and only explicit wire-layer
    transfers."""
    k = _require_k(k)
    q = np.ascontiguousarray(query_sigs, np.uint32)
    nq = int(q.shape[0])
    if nq == 0 or k == 0 or int(store.n_rows) == 0:
        return (np.full((nq, k), -1, np.int32),
                np.full((nq, k), -1, np.int32))
    mode = _resolve_mode(use_pallas)
    chunk_rows = max(block_n, -(-int(chunk_rows) // block_n) * block_n)
    qp = _pad_queries(q)
    q_d = jax.device_put(qp)
    topc, topr = _init_state(qp.shape[0])
    chunks = _scan_chunks(store, chunk_rows)
    if not overlap:
        for blk, base in chunks:
            s_d, rid_d = _stage_chunk(blk, base, chunk_rows)
            topc, topr = _score_chunk(q_d, s_d, rid_d, topc, topr, k,
                                      block_n, mode)
        return _finalize(topc, topr, nq, k)
    from concurrent.futures import ThreadPoolExecutor

    ex = ThreadPoolExecutor(max_workers=1, thread_name_prefix="tse1m-score")
    try:
        fut = None
        for blk, base in chunks:
            nxt = ex.submit(_stage_chunk, blk, base, chunk_rows)
            if fut is not None:
                s_d, rid_d = fut.result()
                topc, topr = _score_chunk(q_d, s_d, rid_d, topc, topr,
                                          k, block_n, mode)
            fut = nxt
        if fut is not None:
            s_d, rid_d = fut.result()
            topc, topr = _score_chunk(q_d, s_d, rid_d, topc, topr, k,
                                      block_n, mode)
    finally:
        ex.shutdown(wait=False, cancel_futures=True)
    return _finalize(topc, topr, nq, k)


__all__ = ["K_PAD", "ROW_INF", "bulk_topk_store", "score_topk_host",
           "store_scan_locator", "topk_agreement"]
