"""On-device interleaved-rANS decode (wire v3's entropy-coded lanes).

The host codec (cluster/entropy.py) deals symbols round-robin across
``N_STREAMS`` independent rANS states and interleaves the
renormalization words in exact decode order, so the device decoder is a
data-parallel loop: every step advances all streams with one table
gather, and the variable word consumption collapses to a cumsum over the
stream axis (each stream consumes 0 or 1 sixteen-bit word per step — the
12-bit-frequency / 16-bit-renorm invariant).

Two implementations, dispatched like minhash_pallas: a jnp ``fori_loop``
(the reference — runs everywhere, is the CPU path) and a pallas kernel
that keeps the state vector, tables, and word stream VMEM-resident for
the whole lane.  The pallas variant uses dynamic row stores that not
every Mosaic generation lowers; the one-shot breaker falls back to the
bit-identical jnp decoder, mirroring minhash_pallas._FUSED_UNPACK_OK.

Decode tables (slot->symbol, frequency, cumulative) are BUILT ON DEVICE
from the shipped frequency array inside the jit — the wire carries only
the 2-byte-per-entry freqs, not the 2^12-entry slot table.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..entropy import _M, N_STREAMS, PROB_BITS, RANS_L, EntropyLane, \
    _DIRECT_BITS_MAX


def _decode_tables(freqs):
    """freqs [A] -> (slot_sym [2^12] int32, cum_excl [A] uint32)."""
    cumi = jnp.cumsum(freqs.astype(jnp.uint32))
    cume = jnp.concatenate([jnp.zeros(1, jnp.uint32), cumi[:-1]])
    slot_sym = jnp.searchsorted(cumi, jnp.arange(_M, dtype=jnp.uint32),
                                side="right").astype(jnp.int32)
    return slot_sym, cume


@functools.partial(jax.jit, static_argnames=("n",))
def _rans_decode_jnp(words, x0, freqs, n: int):
    """[W] uint16 words + [K] uint32 states + [A] uint16 freqs -> [n]
    uint32 symbols.  Oracle: entropy.rans_decode_host."""
    k = N_STREAMS
    steps = -(-n // k)
    slot_sym, cume = _decode_tables(freqs)
    fr = freqs.astype(jnp.uint32)
    ks = jnp.arange(k, dtype=jnp.int32)
    # One pad word so the clamped gather of an exhausted pointer stays
    # in-bounds (those lanes' reads are masked out by `need`).
    wpad = jnp.concatenate([words.astype(jnp.uint32),
                            jnp.zeros(1, jnp.uint32)])
    wlim = wpad.shape[0] - 1

    def body(t, carry):
        x, ptr, out = carry
        act = (t * k + ks) < n
        slot = (x & jnp.uint32(_M - 1)).astype(jnp.int32)
        s = slot_sym[slot]
        xn = fr[s] * (x >> jnp.uint32(PROB_BITS)) \
            + slot.astype(jnp.uint32) - cume[s]
        x = jnp.where(act, xn, x)
        need = act & (x < jnp.uint32(RANS_L))
        off = jnp.cumsum(need.astype(jnp.int32)) - need.astype(jnp.int32)
        w = wpad[jnp.clip(ptr + off, 0, wlim)]
        x = jnp.where(need, (x << jnp.uint32(16)) | w, x)
        ptr = ptr + jnp.sum(need.astype(jnp.int32))
        out = out.at[t].set(s.astype(jnp.uint32))
        return x, ptr, out

    out = jnp.zeros((steps, k), jnp.uint32)
    _, _, out = jax.lax.fori_loop(
        0, steps, body, (x0.astype(jnp.uint32), jnp.int32(0), out))
    return out.reshape(-1)[:n]


def _rans_kernel(words_ref, x0_ref, slot_ref, fr_ref, cume_ref, out_ref, *,
                 n: int):
    """Pallas body: the same loop with every operand VMEM-resident."""
    k = N_STREAMS
    steps = -(-n // k)
    wpad = words_ref[...].astype(jnp.uint32)
    wlim = wpad.shape[0] - 1
    slot_sym = slot_ref[...]
    fr = fr_ref[...]
    cume = cume_ref[...]
    ks = jax.lax.broadcasted_iota(jnp.int32, (k,), 0)

    def body(t, carry):
        x, ptr = carry
        act = (t * k + ks) < n
        slot = (x & jnp.uint32(_M - 1)).astype(jnp.int32)
        s = slot_sym[slot]
        xn = fr[s] * (x >> jnp.uint32(PROB_BITS)) \
            + slot.astype(jnp.uint32) - cume[s]
        x = jnp.where(act, xn, x)
        need = act & (x < jnp.uint32(RANS_L))
        off = jnp.cumsum(need.astype(jnp.int32)) - need.astype(jnp.int32)
        w = wpad[jnp.clip(ptr + off, 0, wlim)]
        x = jnp.where(need, (x << jnp.uint32(16)) | w, x)
        ptr = ptr + jnp.sum(need.astype(jnp.int32))
        out_ref[t, :] = s.astype(jnp.uint32)
        return x, ptr

    jax.lax.fori_loop(0, steps, body,
                      (x0_ref[...].astype(jnp.uint32), jnp.int32(0)))


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def _rans_decode_pallas(words, x0, freqs, n: int, interpret: bool):
    from jax.experimental import pallas as pl

    k = N_STREAMS
    steps = -(-n // k)
    slot_sym, cume = _decode_tables(freqs)
    fr = freqs.astype(jnp.uint32)
    wpad = jnp.concatenate([words.astype(jnp.uint16),
                            jnp.zeros(1, jnp.uint16)])
    out = pl.pallas_call(
        functools.partial(_rans_kernel, n=n),
        out_shape=jax.ShapeDtypeStruct((steps, k), jnp.uint32),
        interpret=interpret,
    )(wpad, x0.astype(jnp.uint32), slot_sym, fr, cume)
    return out.reshape(-1)[:n]


# One-shot breaker (minhash_pallas._FUSED_UNPACK_OK idiom): a Mosaic
# generation that rejects the dynamic-store loop falls back to the
# bit-identical jnp decoder for the rest of the process.
_RANS_PALLAS_OK = True


def _decode_plane(words_d, x0_d, freqs_d, n: int, use_pallas: str):
    global _RANS_PALLAS_OK
    if n == 0:
        # fori_loop traces its body even for a zero trip count, and the
        # body scatters into a zero-row output — short-circuit instead.
        return jnp.zeros(0, jnp.uint32)
    if use_pallas == "auto":
        use_pallas = "force" if jax.default_backend() == "tpu" else "never"
    if use_pallas in ("force", "interpret") and n and _RANS_PALLAS_OK:
        try:
            return _rans_decode_pallas(words_d, x0_d, freqs_d, n,
                                       use_pallas == "interpret")
        except Exception as e:  # Mosaic lowering gap: unfuse, don't fail  # graftlint: disable=broad-except -- compiler rejections are arbitrary; fallback is bit-identical
            _RANS_PALLAS_OK = False
            from ...utils.logging import get_logger

            get_logger("cluster.rans").warning(
                "pallas rANS decoder unavailable (%s: %s); falling back "
                "to the jnp decoder", type(e).__name__, e)
    return _rans_decode_jnp(words_d, x0_d, freqs_d, n)


@functools.partial(jax.jit, static_argnames=("shift",))
def _combine_plane(out, plane, shift: int):
    """Fold one byte plane in; jitted so the shift embeds as a
    compile-time constant instead of staging eagerly per call (the
    runtime sanitizer's no-implicit-transfers class)."""
    return out | (plane << jnp.uint32(shift))


def decode_lane_device(lane: EntropyLane, arrays_d, *,
                       use_pallas: str = "auto"):
    """Decode an entropy-coded lane on device -> [n] uint32.

    ``arrays_d``: the device-resident counterparts of
    ``lane.wire_arrays()`` (same order — (words, x0, freqs) per plane),
    device_put by the pipeline's wire layer."""
    arrays_d = list(arrays_d)
    assert len(arrays_d) == 3 * len(lane.planes), \
        (len(arrays_d), len(lane.planes))
    out = None
    for p in range(len(lane.planes)):
        words_d, x0_d, freqs_d = arrays_d[3 * p:3 * p + 3]
        plane = _decode_plane(words_d, x0_d, freqs_d, lane.n, use_pallas)
        if out is None:  # plane 0 always sits at shift 0
            out = plane
        else:
            out = _combine_plane(out, plane,
                                 8 * p if lane.bits > _DIRECT_BITS_MAX
                                 else 0)
    return out
