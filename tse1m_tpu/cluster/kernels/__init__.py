"""Device kernels for the cluster pipeline's wire plane.

The fused MinHash/band-key kernels live in ``cluster/minhash_pallas.py``
(re-exported here so callers can treat this package as the kernel
namespace); ``rans.py`` adds the wire-v3 entropy decoders — a jnp
``fori_loop`` reference and a pallas variant — fused into the pipeline's
packed-unpack path.  Kernels never open their own transfers: every
device_put stays in the blessed wire layer (cluster/encode.py,
cluster/entropy.py, cluster/prefilter.py, cluster/pipeline.py — the
graftlint ``wire-layer`` rule).
"""

from ..minhash_pallas import (minhash_and_keys, minhash_and_keys_packed,
                              minhash_and_keys_pallas)
from .rans import decode_lane_device

__all__ = [
    "minhash_and_keys",
    "minhash_and_keys_packed",
    "minhash_and_keys_pallas",
    "decode_lane_device",
]
