"""Device kernels for the cluster pipeline's wire plane.

The fused MinHash/band-key kernels live in ``cluster/minhash_pallas.py``
(re-exported here so callers can treat this package as the kernel
namespace); ``rans.py`` adds the wire-v3 entropy decoders — a jnp
``fori_loop`` reference and a pallas variant — fused into the pipeline's
packed-unpack path; ``score.py`` is the batched scoring plane — exact
top-k signature agreement with the same three-implementation parity
contract, streaming the mmap'd store through the device.

Transfer discipline: the encode/decode kernels never open their own
transfers (every device_put stays in the blessed wire layer —
cluster/encode.py, cluster/entropy.py, cluster/prefilter.py,
cluster/pipeline.py; the graftlint ``wire-layer`` rule).  ``score.py``
is the ONE kernel module with its own wire-layer seat: its streaming
store scan IS a transfer plane (double-buffered h2d chunk staging), so
it stages explicitly instead of routing through the pipeline.
"""

from ..minhash_pallas import (minhash_and_keys, minhash_and_keys_packed,
                              minhash_and_keys_pallas)
from .rans import decode_lane_device
from .score import (bulk_topk_store, score_topk_host, store_scan_locator,
                    topk_agreement)

__all__ = [
    "minhash_and_keys",
    "minhash_and_keys_packed",
    "minhash_and_keys_pallas",
    "decode_lane_device",
    "score_topk_host",
    "topk_agreement",
    "bulk_topk_store",
    "store_scan_locator",
]
