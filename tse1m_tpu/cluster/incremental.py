"""Warm-path label merge for the signature store (host side).

A continuous-fuzzing re-run is the previous run's rows plus a short
appended tail.  The banded-LSH edge structure makes that tail cheap to
absorb EXACTLY:

- Bucket hubs are elected by *minimum original index*
  (`lsh.bucket_representatives`), and appended rows only ever have
  larger indices — so adding rows never changes the hub of any bucket
  that already had members.  Every old row's verified edge set is
  therefore untouched, and the old labels (each the min index of its
  component) summarise them losslessly.
- A new row's hub per band is either the stored bucket table's rep (the
  band key already existed) or the minimum-index *new* row sharing the
  key (the key is novel).  Verifying those candidate edges with the
  exact signature-agreement rule the device uses, then running a host
  union-find over {old component labels} ∪ {new row indices} with
  union-by-min, reproduces the cold batch run's label vector
  elementwise — including the case where one new row bridges two
  previously separate old components.

So a ≤1%-novel warm run never rebuilds full band tables: it probes the
stored per-band (key -> rep) tables, unions, and appends only the novel
keys.  All arrays here are host numpy; `cluster/pipeline.py` owns every
device transfer.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

# LSM delta layer for the live band tables: past this many delta runs
# an absorb consolidates them into the base arrays.  Mirrors the store's
# probe-index delta layer (store._ProbeIndex): the BENCH_r08 GIL convoy
# was the O(Kb) sorted-insert into every band's full table on the ingest
# thread — with runs, an absorb touches O(batch log batch) per band and
# the rare consolidation pays the big memcpy, bounding the query tail.
_DELTA_RUNS_DEFAULT = 8


def _delta_max_runs() -> int:
    try:
        return max(1, int(os.environ.get("TSE1M_LIVE_DELTA_RUNS",
                                         _DELTA_RUNS_DEFAULT)))
    except ValueError:
        return _DELTA_RUNS_DEFAULT


@dataclass
class LshState:
    """The last completed run's LSH state, as persisted by
    `store.SignatureStore.save_state`."""

    n_rows: int
    labels: np.ndarray              # [n_rows] int32 min-orig-index labels
    locator: np.ndarray             # [n_rows, 2] int32 (shard, row) in store
    band_keys_sorted: list          # per band: [Kb] uint32 distinct keys
    band_reps: list                 # per band: [Kb] int32 min index per key
    prefix_digest: str              # digests_fingerprint of the run's rows

    def matches_prefix(self, digests: np.ndarray) -> bool:
        """True when this state's rows are exactly the first n_rows of
        the current input (the accretion pattern the merge requires)."""
        from .store import digests_fingerprint

        if digests.shape[0] < self.n_rows:
            return False
        return (digests_fingerprint(digests[:self.n_rows])
                == self.prefix_digest)


def build_band_tables(keys: np.ndarray) -> tuple[list, list]:
    """[N, B] uint32 band keys (original row order) -> per-band sorted
    distinct keys + the min row index holding each ([Kb] uint32,
    [Kb] int32)."""
    n, n_bands = keys.shape
    ks_list, rep_list = [], []
    for b in range(n_bands):
        order = np.argsort(keys[:, b], kind="stable")
        ks = keys[order, b]
        first = np.empty(n, bool)
        if n:
            first[0] = True
            np.not_equal(ks[1:], ks[:-1], out=first[1:])
        ks_list.append(np.ascontiguousarray(ks[first]))
        rep_list.append(order[first].astype(np.int32))
    return ks_list, rep_list


def extend_band_tables(band_keys_sorted: list, band_reps: list,
                       new_keys: np.ndarray, base_index: int
                       ) -> tuple[list, list]:
    """Append the new rows' novel band keys (rep = min new row's global
    index, ``base_index`` + row position).  Existing keys keep their
    reps — new rows have larger indices by construction."""
    ks_out, rep_out = [], []
    k = new_keys.shape[0]
    for b, (ks, reps) in enumerate(zip(band_keys_sorted, band_reps)):
        kb = new_keys[:, b]
        pos = np.searchsorted(ks, kb)
        inb = pos < ks.shape[0]
        hit = np.zeros(k, bool)
        hit[inb] = ks[pos[inb]] == kb[inb]
        rest = np.flatnonzero(~hit)
        if rest.size == 0:
            ks_out.append(ks)
            rep_out.append(reps)
            continue
        order = rest[np.argsort(kb[rest], kind="stable")]
        ks2 = kb[order]
        first = np.empty(order.size, bool)
        first[0] = True
        np.not_equal(ks2[1:], ks2[:-1], out=first[1:])
        add_k = ks2[first]
        add_r = (order[first] + base_index).astype(np.int32)
        # Sorted-insert merge (both sides sorted, no ties — novel keys
        # are by construction absent from ks): O(Kb) memcpy instead of a
        # full re-sort, which matters when this runs once per serving
        # ingest batch rather than once per warm run.
        ins = np.searchsorted(ks, add_k)
        ks_out.append(np.insert(ks, ins, add_k))
        rep_out.append(np.insert(reps, ins, add_r))
    return ks_out, rep_out


def candidate_edges(band_keys_sorted: list, band_reps: list,
                    new_keys: np.ndarray, base_index: int
                    ) -> tuple[np.ndarray, np.ndarray]:
    """Unverified candidate edges (u, v) for the appended rows, in global
    original indices — exactly the edges the cold run would add: per
    band, each new row points at its bucket hub (stored rep for an
    existing key, min-index new row for a novel key).  Self-edges are
    dropped, like the device verifier's caller does."""
    k, n_bands = new_keys.shape
    idx = np.arange(k, dtype=np.int64) + base_index
    us, vs = [], []
    for b in range(n_bands):
        kb = new_keys[:, b]
        ks, reps = band_keys_sorted[b], band_reps[b]
        pos = np.searchsorted(ks, kb)
        inb = pos < ks.shape[0]
        hit = np.zeros(k, bool)
        hit[inb] = ks[pos[inb]] == kb[inb]
        if hit.any():
            us.append(idx[hit])
            vs.append(reps[pos[hit]].astype(np.int64))
        rest = np.flatnonzero(~hit)
        if rest.size:
            order = rest[np.argsort(kb[rest], kind="stable")]
            ks2 = kb[order]
            first = np.empty(order.size, bool)
            first[0] = True
            np.not_equal(ks2[1:], ks2[:-1], out=first[1:])
            grp = np.cumsum(first) - 1
            us.append(idx[order])
            vs.append(idx[order[np.flatnonzero(first)][grp]])
    if not us:
        e = np.empty(0, np.int64)
        return e, e.copy()
    u = np.concatenate(us)
    v = np.concatenate(vs)
    keep = u != v
    return u[keep], v[keep]


def verify_edges(u: np.ndarray, v: np.ndarray, new_sigs: np.ndarray,
                 base_index: int, gather_old_sigs, n_hashes: int,
                 threshold: float) -> np.ndarray:
    """The device verifier's exact rule on host: accept an edge iff the
    fraction of agreeing MinHash rows (float32, like
    `lsh.estimated_jaccard`) reaches ``threshold``.  ``gather_old_sigs``
    maps unique old row indices to their stored [*, H] signatures."""
    if u.size == 0:
        return np.zeros(0, bool)
    sig_u = new_sigs[u - base_index]
    sig_v = np.empty_like(sig_u)
    old = v < base_index
    if old.any():
        uniq, inv = np.unique(v[old], return_inverse=True)
        sig_v[old] = gather_old_sigs(uniq)[inv]
    new = ~old
    if new.any():
        sig_v[new] = new_sigs[v[new] - base_index]
    agree = (sig_u == sig_v).sum(axis=1)
    est = agree.astype(np.float32) / np.float32(n_hashes)
    return est >= np.float32(threshold)


def merge_labels(old_labels: np.ndarray, u: np.ndarray, v: np.ndarray,
                 n_old: int, n_new: int) -> np.ndarray:
    """Union the verified new edges into the old labeling; returns
    [n_old + n_new] int32 labels equal elementwise to a cold batch run
    over the union.

    Nodes are old component labels (< n_old, each already the min index
    of its component) and new row indices (>= n_old); union-by-min keeps
    every root the minimum original index of its merged component, so a
    new row that bridges two old components relabels both to the smaller
    component's label — exactly what min-label propagation converges to.
    """
    parent: dict[int, int] = {}

    def find(x: int) -> int:
        root = x
        while parent.get(root, root) != root:
            root = parent[root]
        while parent.get(x, x) != root:
            parent[x], x = root, parent[x]
        return root

    for u_, v_ in zip(u.tolist(), v.tolist()):
        cu = find(u_)
        cv = find(int(old_labels[v_]) if v_ < n_old else v_)
        if cu == cv:
            continue
        if cu > cv:
            cu, cv = cv, cu
        parent[cv] = cu
        parent.setdefault(cu, cu)

    new_lab = np.arange(n_old, n_old + n_new, dtype=np.int64)
    for i in range(n_new):
        j = n_old + i
        if j in parent:
            new_lab[i] = find(j)
    out_old = old_labels.astype(np.int64, copy=True)
    remap = {lab: r for lab in parent if lab < n_old
             for r in (find(lab),) if r != lab}
    if remap:
        lk = np.fromiter(remap.keys(), np.int64, len(remap))
        lv = np.fromiter(remap.values(), np.int64, len(remap))
        order = np.argsort(lk)
        lk, lv = lk[order], lv[order]
        pos = np.searchsorted(lk, out_old)
        inb = pos < lk.size
        match = np.zeros(n_old, bool)
        match[inb] = lk[pos[inb]] == out_old[inb]
        out_old[match] = lv[pos[match]]
    return np.concatenate([out_old, new_lab]).astype(np.int32)


# ---------------------------------------------------------------------------
# Live index: the serving-plane view of the same extend-never-rebuild
# machinery.  A LiveClusterIndex is an IMMUTABLE snapshot of one ingest
# generation — labels, band tables, store locator, and (optionally) a
# sorted digest -> row map for membership lookups.  `absorb` returns a
# NEW snapshot sharing every unchanged array with its parent (the band
# tables are copy-on-extend already), so a serving daemon can swap the
# snapshot reference atomically per ingest batch and concurrent queries
# never observe a half-updated table.  The batch warm path
# (cluster/pipeline._store_warm_merge) is a client of this same object:
# one merge implementation, proven once, serving both shapes.


@dataclass(frozen=True)
class LiveClusterIndex:
    """One ingest generation of the online cluster-membership index."""

    # graftlint snapshot-publish: published snapshots are never mutated —
    # frozen blocks attribute stores at runtime; the static pass also
    # proves no in-place array op (labels[i] = ..., band list .append)
    # ever targets a published instance.  (The marker is redundant with
    # frozen=True but keeps the discipline grep-able.)
    __immutable_after_publish__ = True

    generation: int
    n_rows: int
    labels: np.ndarray              # [n_rows] int32 min-orig-index labels
    locator: np.ndarray             # [n_rows, 2] int32 (shard, row) in store
    band_keys_sorted: list          # BASE per band: [Kb] uint32 distinct keys
    band_reps: list                 # BASE per band: [Kb] int32 min index
    # Sorted 128-bit digest map (membership lookups).  Optional: the
    # batch warm path never queries by digest and skips building it.
    digest_keys: np.ndarray | None = field(default=None, repr=False)
    digest_rows: np.ndarray | None = field(default=None, repr=False)
    # LSM delta runs over the band tables: each run is one absorbed
    # generation's novel keys, (ks_per_band, reps_per_band) with every
    # per-band array sorted; keys are distinct ACROSS runs and the base
    # (a key is added only when no earlier source holds it).  Probes
    # search base + runs; absorb appends a run instead of re-writing
    # the base arrays, and consolidates past _delta_max_runs().
    band_deltas: tuple = field(default=(), repr=False)

    # -- constructors --------------------------------------------------------

    @classmethod
    def empty(cls, n_bands: int) -> "LiveClusterIndex":
        e32 = np.empty(0, np.uint32)
        return cls(generation=0, n_rows=0,
                   labels=np.empty(0, np.int32),
                   locator=np.empty((0, 2), np.int32),
                   band_keys_sorted=[e32.copy() for _ in range(n_bands)],
                   band_reps=[np.empty(0, np.int32) for _ in range(n_bands)],
                   digest_keys=_empty_digest_struct(),
                   digest_rows=np.empty(0, np.int32))

    @classmethod
    def from_state(cls, state: LshState,
                   digests: np.ndarray | None = None) -> "LiveClusterIndex":
        """Adopt a persisted LSH state (store.SignatureStore.load_state)
        as generation 0.  ``digests`` ([n_rows, 2] uint64, row order)
        enables the digest-membership map; None skips it (batch path)."""
        dk = dr = None
        if digests is not None:
            dk, dr = _sorted_digest_map(digests)
        return cls(generation=0, n_rows=state.n_rows,
                   labels=state.labels.astype(np.int32, copy=True),
                   locator=state.locator, digest_keys=dk, digest_rows=dr,
                   band_keys_sorted=list(state.band_keys_sorted),
                   band_reps=list(state.band_reps))

    # -- band-table probing (base + LSM delta runs) --------------------------

    def _band_sources(self, b: int):
        yield self.band_keys_sorted[b], self.band_reps[b]
        for run_ks, run_reps in self.band_deltas:
            yield run_ks[b], run_reps[b]

    def _probe_band(self, b: int, kb: np.ndarray
                    ) -> tuple[np.ndarray, np.ndarray]:
        """(hit [K] bool, rep [K] int32): binary-search the base table,
        then each delta run — a key lives in exactly one source."""
        k = kb.shape[0]
        hit = np.zeros(k, bool)
        rep = np.zeros(k, np.int32)
        for ks, reps in self._band_sources(b):
            if ks.shape[0] == 0:
                continue
            todo = np.flatnonzero(~hit)
            if todo.size == 0:
                break
            q = kb[todo]
            pos = np.searchsorted(ks, q)
            inb = pos < ks.shape[0]
            m = np.zeros(todo.size, bool)
            m[inb] = ks[pos[inb]] == q[inb]
            if m.any():
                sel = todo[m]
                hit[sel] = True
                rep[sel] = reps[pos[m]]
        return hit, rep

    def _probe_new_keys(self, new_keys: np.ndarray, base_index: int):
        """One pass per band over an appended batch: the candidate edge
        list (exactly candidate_edges' semantics, against base+deltas)
        AND the batch's novel-key delta run."""
        k, n_bands = new_keys.shape
        idx = np.arange(k, dtype=np.int64) + base_index
        us, vs = [], []
        run_ks, run_reps = [], []
        for b in range(n_bands):
            kb = new_keys[:, b]
            hit, rep = self._probe_band(b, kb)
            if hit.any():
                us.append(idx[hit])
                vs.append(rep[hit].astype(np.int64))
            rest = np.flatnonzero(~hit)
            if rest.size:
                order = rest[np.argsort(kb[rest], kind="stable")]
                ks2 = kb[order]
                first = np.empty(order.size, bool)
                first[0] = True
                np.not_equal(ks2[1:], ks2[:-1], out=first[1:])
                grp = np.cumsum(first) - 1
                us.append(idx[order])
                vs.append(idx[order[np.flatnonzero(first)][grp]])
                run_ks.append(np.ascontiguousarray(ks2[first]))
                run_reps.append((order[np.flatnonzero(first)]
                                 + base_index).astype(np.int32))
            else:
                run_ks.append(np.empty(0, np.uint32))
                run_reps.append(np.empty(0, np.int32))
        if not us:
            e = np.empty(0, np.int64)
            u, v = e, e.copy()
        else:
            u = np.concatenate(us)
            v = np.concatenate(vs)
            keep = u != v
            u, v = u[keep], v[keep]
        return u, v, run_ks, run_reps

    def band_tables(self) -> tuple[list, list]:
        """Fully consolidated (band_keys_sorted, band_reps) — what the
        persistence layer commits (store.save_state's format predates
        the delta runs and stays one sorted array per band).  Pure; the
        snapshot keeps its runs."""
        if not self.band_deltas:
            return list(self.band_keys_sorted), list(self.band_reps)
        return self._consolidated()

    def _consolidated(self) -> tuple[list, list]:
        bk, br = [], []
        for b in range(len(self.band_keys_sorted)):
            parts = list(self._band_sources(b))
            ks = np.concatenate([p[0] for p in parts])
            reps = np.concatenate([p[1] for p in parts])
            order = np.argsort(ks, kind="stable")
            bk.append(np.ascontiguousarray(ks[order]))
            br.append(np.ascontiguousarray(reps[order]))
        return bk, br

    # -- ingest --------------------------------------------------------------

    def absorb(self, new_keys: np.ndarray, new_sigs: np.ndarray,
               gather_old_sigs, n_hashes: int, threshold: float,
               new_locator: np.ndarray | None = None,
               new_digests: np.ndarray | None = None
               ) -> "LiveClusterIndex":
        """Absorb an appended tail of rows into a NEW snapshot.

        Exactly the batch warm merge: candidate edges from the stored
        band tables, verified with the device's signature-agreement
        rule, merged with union-by-min — labels elementwise-equal to a
        cold batch run over the union (see module docstring).  The
        parent snapshot is untouched; the base band arrays are SHARED
        with the parent (the batch's novel keys land in a new LSM delta
        run) until the run count crosses the consolidation threshold.
        """
        n_old = self.n_rows
        k = int(new_keys.shape[0])
        if k == 0:
            return self
        u, v, run_ks, run_reps = self._probe_new_keys(new_keys, n_old)
        ok = verify_edges(u, v, new_sigs, n_old, gather_old_sigs,
                          n_hashes, threshold)
        labels = merge_labels(self.labels, u[ok], v[ok], n_old, k)
        deltas = self.band_deltas
        if any(a.size for a in run_ks):
            deltas = deltas + ((run_ks, run_reps),)
        locator = self.locator
        if new_locator is not None:
            locator = np.concatenate(
                [locator, np.ascontiguousarray(new_locator, np.int32)])
        dk, dr = self.digest_keys, self.digest_rows
        if dk is not None and new_digests is not None:
            dk, dr = _merge_digest_map(dk, dr, new_digests, n_old)
        out = LiveClusterIndex(
            generation=self.generation + 1, n_rows=n_old + k,
            labels=labels, locator=locator,
            band_keys_sorted=self.band_keys_sorted,
            band_reps=self.band_reps, digest_keys=dk, digest_rows=dr,
            band_deltas=deltas)
        if len(deltas) >= _delta_max_runs():
            bk, br = out._consolidated()
            out = LiveClusterIndex(
                generation=out.generation, n_rows=out.n_rows,
                labels=out.labels, locator=out.locator,
                band_keys_sorted=bk, band_reps=br, digest_keys=dk,
                digest_rows=dr, band_deltas=())
        return out

    # -- queries (read-only; safe from any thread on one snapshot) ----------

    def lookup_digests(self, digests: np.ndarray
                       ) -> tuple[np.ndarray, np.ndarray]:
        """[N, 2] uint64 digests -> (hit [N] bool, row [N] int32; -1 on
        miss).  Requires the digest map (built with ``new_digests``)."""
        if self.digest_keys is None:
            raise RuntimeError("this LiveClusterIndex was built without a "
                               "digest map (batch merge shape); membership "
                               "lookups need from_state(digests=...)")
        n = digests.shape[0]
        row = np.full(n, -1, np.int32)
        if n == 0 or self.digest_keys.shape[0] == 0:
            return np.zeros(n, bool), row
        q = _digest_struct(digests)
        pos = np.searchsorted(self.digest_keys, q)
        inb = pos < self.digest_keys.shape[0]
        hit = np.zeros(n, bool)
        hit[inb] = self.digest_keys[pos[inb]] == q[inb]
        row[hit] = self.digest_rows[pos[hit]]
        return hit, row

    def candidate_hubs(self, keys: np.ndarray
                       ) -> tuple[np.ndarray, np.ndarray]:
        """Per-band bucket hubs for query vectors that are NOT index rows:
        [K, B] band keys -> (q [E], hub_row [E]) pairs — the rows a cold
        run would test these vectors' signatures against.  Probes the
        base tables AND every LSM delta run (a key lives in exactly one
        source, so the union of hits is the consolidated answer)."""
        k, n_bands = keys.shape
        qs, hubs = [], []
        for b in range(n_bands):
            hit, rep = self._probe_band(b, keys[:, b])
            if hit.any():
                qs.append(np.flatnonzero(hit))
                hubs.append(rep[hit].astype(np.int64))
        if not qs:
            e = np.empty(0, np.int64)
            return e, e.copy()
        return np.concatenate(qs), np.concatenate(hubs)

    def query_labels(self, sigs: np.ndarray, keys: np.ndarray,
                     gather_sigs, n_hashes: int, threshold: float
                     ) -> np.ndarray:
        """Cluster membership for novel vectors (no mutation): each
        vector's candidate hubs are verified with the exact signature-
        agreement rule; the answer is the minimum label over verified
        hubs — the component a cold run would union this vector into —
        or -1 (a new singleton cluster).  ``gather_sigs`` maps unique
        index row ids -> their stored [*, H] signatures."""
        k = int(sigs.shape[0])
        out = np.full(k, -1, np.int64)
        q, hub = self.candidate_hubs(keys)
        if q.size == 0:
            return out
        uniq, inv = np.unique(hub, return_inverse=True)
        hub_sigs = gather_sigs(uniq)
        if hub_sigs is None:          # store raced (eviction): all miss
            return out
        agree = (sigs[q] == hub_sigs[inv]).sum(axis=1)
        ok = agree.astype(np.float32) / np.float32(n_hashes) \
            >= np.float32(threshold)
        if not ok.any():
            return out
        hub_lab = self.labels[hub[ok]].astype(np.int64)
        sentinel = np.int64(2**62)
        acc = np.full(k, sentinel, np.int64)
        np.minimum.at(acc, q[ok], hub_lab)
        return np.where(acc == sentinel, np.int64(-1), acc)

    def topk(self, sigs: np.ndarray, keys: np.ndarray, gather_sigs,
             k: int) -> tuple[np.ndarray, np.ndarray]:
        """Per-query top-k index rows by exact signature agreement over
        the band-candidate set (the serve ``topk`` verb's low-latency
        host path): probe every band's bucket for hub rows, gather their
        stored signatures, rank by (-agreement count, ascending index
        row).  Returns (counts [Q, k] int32, rows [Q, k] int32), both
        ``-1``-padded past the candidate count.

        Candidates are bucket REPRESENTATIVES (one hub per distinct
        band key), so recall is bounded by the hub structure — the
        exact-recall surface is the full store scan
        (`cluster.kernels.score.bulk_topk_store`)."""
        nq = int(sigs.shape[0])
        k = int(k)
        counts_out = np.full((nq, k), -1, np.int32)
        rows_out = np.full((nq, k), -1, np.int32)
        if nq == 0 or k == 0:
            return counts_out, rows_out
        q, hub = self.candidate_hubs(keys)
        if q.size == 0:
            return counts_out, rows_out
        # One hub can hit a query in several bands: dedupe the pairs so
        # a row is ranked once per query.
        pair = q * np.int64(self.n_rows + 1) + hub
        sel = np.unique(pair, return_index=True)[1]
        q, hub = q[sel], hub[sel]
        uniq, inv = np.unique(hub, return_inverse=True)
        hub_sigs = gather_sigs(uniq)
        if hub_sigs is None:          # store raced (eviction): all miss
            return counts_out, rows_out
        agree = (sigs[q] == hub_sigs[inv]).sum(axis=1).astype(np.int32)
        # (-agreement, ascending row) within each query — the scorer
        # kernels' selection order exactly.
        order = np.lexsort((hub, -agree, q))
        qs, ag, hb = q[order], agree[order], hub[order]
        first = np.flatnonzero(np.r_[True, qs[1:] != qs[:-1]])
        runs = np.diff(np.r_[first, qs.size])
        rank = np.arange(qs.size) - np.repeat(first, runs)
        keep = rank < k
        counts_out[qs[keep], rank[keep]] = ag[keep]
        rows_out[qs[keep], rank[keep]] = hb[keep].astype(np.int32)
        return counts_out, rows_out


def _empty_digest_struct() -> np.ndarray:
    return np.empty(0, np.dtype([("a", "<u8"), ("b", "<u8")]))


def _digest_struct(digests: np.ndarray) -> np.ndarray:
    from .store import _as_struct

    return _as_struct(digests)


def _sorted_digest_map(digests: np.ndarray
                       ) -> tuple[np.ndarray, np.ndarray]:
    d = _digest_struct(digests)
    order = np.argsort(d, kind="stable").astype(np.int32)
    return d[order].copy(), order


def _merge_digest_map(keys: np.ndarray, rows: np.ndarray,
                      new_digests: np.ndarray, base_index: int
                      ) -> tuple[np.ndarray, np.ndarray]:
    nd = _digest_struct(new_digests)
    norder = np.argsort(nd, kind="stable")
    nk = nd[norder]
    nr = (norder + base_index).astype(np.int32)
    pos = np.searchsorted(nk, keys)
    merged_k = np.insert(nk, pos, keys)
    merged_r = np.insert(nr, pos, rows)
    return merged_k, merged_r


__all__ = ["LiveClusterIndex", "LshState", "build_band_tables",
           "candidate_edges", "extend_band_tables", "merge_labels",
           "verify_edges"]
