"""Warm-path label merge for the signature store (host side).

A continuous-fuzzing re-run is the previous run's rows plus a short
appended tail.  The banded-LSH edge structure makes that tail cheap to
absorb EXACTLY:

- Bucket hubs are elected by *minimum original index*
  (`lsh.bucket_representatives`), and appended rows only ever have
  larger indices — so adding rows never changes the hub of any bucket
  that already had members.  Every old row's verified edge set is
  therefore untouched, and the old labels (each the min index of its
  component) summarise them losslessly.
- A new row's hub per band is either the stored bucket table's rep (the
  band key already existed) or the minimum-index *new* row sharing the
  key (the key is novel).  Verifying those candidate edges with the
  exact signature-agreement rule the device uses, then running a host
  union-find over {old component labels} ∪ {new row indices} with
  union-by-min, reproduces the cold batch run's label vector
  elementwise — including the case where one new row bridges two
  previously separate old components.

So a ≤1%-novel warm run never rebuilds full band tables: it probes the
stored per-band (key -> rep) tables, unions, and appends only the novel
keys.  All arrays here are host numpy; `cluster/pipeline.py` owns every
device transfer.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class LshState:
    """The last completed run's LSH state, as persisted by
    `store.SignatureStore.save_state`."""

    n_rows: int
    labels: np.ndarray              # [n_rows] int32 min-orig-index labels
    locator: np.ndarray             # [n_rows, 2] int32 (shard, row) in store
    band_keys_sorted: list          # per band: [Kb] uint32 distinct keys
    band_reps: list                 # per band: [Kb] int32 min index per key
    prefix_digest: str              # digests_fingerprint of the run's rows

    def matches_prefix(self, digests: np.ndarray) -> bool:
        """True when this state's rows are exactly the first n_rows of
        the current input (the accretion pattern the merge requires)."""
        from .store import digests_fingerprint

        if digests.shape[0] < self.n_rows:
            return False
        return (digests_fingerprint(digests[:self.n_rows])
                == self.prefix_digest)


def build_band_tables(keys: np.ndarray) -> tuple[list, list]:
    """[N, B] uint32 band keys (original row order) -> per-band sorted
    distinct keys + the min row index holding each ([Kb] uint32,
    [Kb] int32)."""
    n, n_bands = keys.shape
    ks_list, rep_list = [], []
    for b in range(n_bands):
        order = np.argsort(keys[:, b], kind="stable")
        ks = keys[order, b]
        first = np.empty(n, bool)
        if n:
            first[0] = True
            np.not_equal(ks[1:], ks[:-1], out=first[1:])
        ks_list.append(np.ascontiguousarray(ks[first]))
        rep_list.append(order[first].astype(np.int32))
    return ks_list, rep_list


def extend_band_tables(band_keys_sorted: list, band_reps: list,
                       new_keys: np.ndarray, base_index: int
                       ) -> tuple[list, list]:
    """Append the new rows' novel band keys (rep = min new row's global
    index, ``base_index`` + row position).  Existing keys keep their
    reps — new rows have larger indices by construction."""
    ks_out, rep_out = [], []
    k = new_keys.shape[0]
    for b, (ks, reps) in enumerate(zip(band_keys_sorted, band_reps)):
        kb = new_keys[:, b]
        pos = np.searchsorted(ks, kb)
        inb = pos < ks.shape[0]
        hit = np.zeros(k, bool)
        hit[inb] = ks[pos[inb]] == kb[inb]
        rest = np.flatnonzero(~hit)
        if rest.size == 0:
            ks_out.append(ks)
            rep_out.append(reps)
            continue
        order = rest[np.argsort(kb[rest], kind="stable")]
        ks2 = kb[order]
        first = np.empty(order.size, bool)
        first[0] = True
        np.not_equal(ks2[1:], ks2[:-1], out=first[1:])
        merged_k = np.concatenate([ks, ks2[first]])
        merged_r = np.concatenate(
            [reps, (order[first] + base_index).astype(np.int32)])
        resort = np.argsort(merged_k, kind="stable")
        ks_out.append(merged_k[resort])
        rep_out.append(merged_r[resort])
    return ks_out, rep_out


def candidate_edges(band_keys_sorted: list, band_reps: list,
                    new_keys: np.ndarray, base_index: int
                    ) -> tuple[np.ndarray, np.ndarray]:
    """Unverified candidate edges (u, v) for the appended rows, in global
    original indices — exactly the edges the cold run would add: per
    band, each new row points at its bucket hub (stored rep for an
    existing key, min-index new row for a novel key).  Self-edges are
    dropped, like the device verifier's caller does."""
    k, n_bands = new_keys.shape
    idx = np.arange(k, dtype=np.int64) + base_index
    us, vs = [], []
    for b in range(n_bands):
        kb = new_keys[:, b]
        ks, reps = band_keys_sorted[b], band_reps[b]
        pos = np.searchsorted(ks, kb)
        inb = pos < ks.shape[0]
        hit = np.zeros(k, bool)
        hit[inb] = ks[pos[inb]] == kb[inb]
        if hit.any():
            us.append(idx[hit])
            vs.append(reps[pos[hit]].astype(np.int64))
        rest = np.flatnonzero(~hit)
        if rest.size:
            order = rest[np.argsort(kb[rest], kind="stable")]
            ks2 = kb[order]
            first = np.empty(order.size, bool)
            first[0] = True
            np.not_equal(ks2[1:], ks2[:-1], out=first[1:])
            grp = np.cumsum(first) - 1
            us.append(idx[order])
            vs.append(idx[order[np.flatnonzero(first)][grp]])
    if not us:
        e = np.empty(0, np.int64)
        return e, e.copy()
    u = np.concatenate(us)
    v = np.concatenate(vs)
    keep = u != v
    return u[keep], v[keep]


def verify_edges(u: np.ndarray, v: np.ndarray, new_sigs: np.ndarray,
                 base_index: int, gather_old_sigs, n_hashes: int,
                 threshold: float) -> np.ndarray:
    """The device verifier's exact rule on host: accept an edge iff the
    fraction of agreeing MinHash rows (float32, like
    `lsh.estimated_jaccard`) reaches ``threshold``.  ``gather_old_sigs``
    maps unique old row indices to their stored [*, H] signatures."""
    if u.size == 0:
        return np.zeros(0, bool)
    sig_u = new_sigs[u - base_index]
    sig_v = np.empty_like(sig_u)
    old = v < base_index
    if old.any():
        uniq, inv = np.unique(v[old], return_inverse=True)
        sig_v[old] = gather_old_sigs(uniq)[inv]
    new = ~old
    if new.any():
        sig_v[new] = new_sigs[v[new] - base_index]
    agree = (sig_u == sig_v).sum(axis=1)
    est = agree.astype(np.float32) / np.float32(n_hashes)
    return est >= np.float32(threshold)


def merge_labels(old_labels: np.ndarray, u: np.ndarray, v: np.ndarray,
                 n_old: int, n_new: int) -> np.ndarray:
    """Union the verified new edges into the old labeling; returns
    [n_old + n_new] int32 labels equal elementwise to a cold batch run
    over the union.

    Nodes are old component labels (< n_old, each already the min index
    of its component) and new row indices (>= n_old); union-by-min keeps
    every root the minimum original index of its merged component, so a
    new row that bridges two old components relabels both to the smaller
    component's label — exactly what min-label propagation converges to.
    """
    parent: dict[int, int] = {}

    def find(x: int) -> int:
        root = x
        while parent.get(root, root) != root:
            root = parent[root]
        while parent.get(x, x) != root:
            parent[x], x = root, parent[x]
        return root

    for u_, v_ in zip(u.tolist(), v.tolist()):
        cu = find(u_)
        cv = find(int(old_labels[v_]) if v_ < n_old else v_)
        if cu == cv:
            continue
        if cu > cv:
            cu, cv = cv, cu
        parent[cv] = cu
        parent.setdefault(cu, cu)

    new_lab = np.arange(n_old, n_old + n_new, dtype=np.int64)
    for i in range(n_new):
        j = n_old + i
        if j in parent:
            new_lab[i] = find(j)
    out_old = old_labels.astype(np.int64, copy=True)
    remap = {lab: r for lab in parent if lab < n_old
             for r in (find(lab),) if r != lab}
    if remap:
        lk = np.fromiter(remap.keys(), np.int64, len(remap))
        lv = np.fromiter(remap.values(), np.int64, len(remap))
        order = np.argsort(lk)
        lk, lv = lk[order], lv[order]
        pos = np.searchsorted(lk, out_old)
        inb = pos < lk.size
        match = np.zeros(n_old, bool)
        match[inb] = lk[pos[inb]] == out_old[inb]
        out_old[match] = lv[pos[match]]
    return np.concatenate([out_old, new_lab]).astype(np.int32)


__all__ = ["LshState", "build_band_tables", "candidate_edges",
           "extend_band_tables", "merge_labels", "verify_edges"]
