"""Cross-row base-delta H2D encoding for the cluster pipeline.

The north-star transfer (BASELINE.json: ~1M session feature sets to the
device) is link-bound: round 4 measured 7.2 s of a 9.5 s wall moving
183 MB of 24-bit-packed features over a ~25 MB/s tunneled PJRT link.
Within-row compression cannot help — uniform 64-element sets over a 2^24
universe carry ~19.4 bits/element of entropy, and a measured round-4
attempt at sorted-gap packing lost more to the one-core host sort than it
saved on the wire.  The redundancy that IS there is *cross-row*: fuzzing
sessions of the same target hit near-identical coverage sets (the planted
synth workload mirrors this — ~60% of rows differ from a shared base row
in only ~6 of 64 positions, and rows of one cluster share positional
layout, so no sort is needed).

Scheme: a cheap host MinHash sketch groups probable near-duplicate rows;
each group's first row stays in the **full lane** (24-bit packed, as
before) and every other member travels in the **delta lane** as (base row
id, changed positions, new values) — ~30 bytes instead of 192.  A
membership bitmask (1 bit/row) lets the device reassemble original order.
Grouping is only a *compression heuristic*: every candidate pair is
verified by exact element comparison (diff count ≤ ``max_diffs``) before
it is encoded, so decode reproduces the input bit-exactly regardless of
sketch quality, and labels match the un-encoded pipeline elementwise.

Measured at 1M x 64 synth (round 5): 98% of true near-duplicates
captured, wire 183 MB -> ~103 MB; numpy encode ~2.3 s, native (C++)
encode ~0.3 s (``native/encode.cc``, used automatically when it loads).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# One (multiply-add) hash pass per probe; (min, max) of the hashed row is
# the group key.  Two order statistics from one pass give ~J^2 ~ 0.8
# capture per probe with negligible cross-cluster key collisions (each
# statistic concentrates in a ~2^26 band; their pair spans ~2^52).
_PROBES = ((0x9E3779B1, 0x85EBCA77), (0xC2B2AE3D, 0x27D4EB2F),
           (0x165667B1, 0x9E3779B9), (0x85EBCA6B, 0xC2B2AE35))

# Encoding only pays when the transfer is seconds long; below this raw
# size a single put is already cheap and the sketch pass would dominate.
_AUTO_MIN_BYTES = 64 * 1024 * 1024
# ...and only when enough rows actually compress (wire win ~= delta
# fraction * 160 B/row; under 5% the bookkeeping lanes eat the win).
_AUTO_MIN_DELTA_FRACTION = 0.05


@dataclass(frozen=True)
class DeltaEncoding:
    """Host-side product of :func:`encode_delta` — the exact wire layout.

    Lanes preserve original row order within themselves; ``mask_bits``
    (little-endian packbits of the 1=delta membership bit per row) is all
    the device needs to map lane ranks back to original indices.
    """

    n: int                  # original row count
    set_size: int
    mask_bits: np.ndarray   # [ceil(n/8)] uint8, little bit order
    full_rows: np.ndarray   # [F, S] uint32 — rows that travel whole
    rep_in_full: np.ndarray  # [D] int32 — full-lane rank of each delta row's base
    counts: np.ndarray      # [D] uint8 — changed positions per delta row
    pos_flat: np.ndarray    # [T] uint8 — changed positions, row-major
    val_flat: np.ndarray    # [T] uint32 — replacement values

    @property
    def n_delta(self) -> int:
        return int(self.rep_in_full.shape[0])

    @property
    def n_full(self) -> int:
        return int(self.full_rows.shape[0])

    def wire_bytes(self, packed24: bool) -> int:
        """Bytes this encoding puts on the H2D link (3 B/value when the
        24-bit pack applies, else 4)."""
        vb = 3 if packed24 else 4
        return (self.mask_bits.nbytes + self.full_rows.shape[0]
                * self.set_size * vb + self.rep_in_full.nbytes
                + self.counts.nbytes + self.pos_flat.nbytes
                + self.val_flat.shape[0] * vb)


def sketch_keys(rows: np.ndarray, probe: int) -> np.ndarray:
    """[K, S] uint32 rows -> [K] uint64 group keys ((min, max) of one
    multiply-add hash pass).  Shared by the numpy and native encoders so
    their groupings agree."""
    a, b = _PROBES[probe]
    h = rows * np.uint32(a) + np.uint32(b)
    return ((h.min(axis=1).astype(np.uint64) << np.uint64(32))
            | h.max(axis=1).astype(np.uint64))


def _group_rows(items: np.ndarray, max_diffs: int, n_probes: int,
                ) -> np.ndarray:
    """[N] int64 rep_of: original index of each row's verified base row,
    -1 for full-lane rows.  Invariant: rep_of[rep_of[i]] == -1 (no
    chains) — a row with children is pinned to the full lane, and later
    probes keep pinned rows in the pool as grouping targets only."""
    n = items.shape[0]
    rep_of = np.full(n, -1, np.int64)
    pinned = np.zeros(n, bool)
    pool = np.arange(n)
    for p in range(min(n_probes, len(_PROBES))):
        if pool.size < 2:
            break
        keys = sketch_keys(items[pool], p)
        # Stable sort by (key, pinned-first): a pinned row heads its group
        # whenever one is present, so stragglers attach to existing bases
        # instead of spawning a second base for the same cluster.
        order = np.lexsort((~pinned[pool], keys))
        ks = keys[order]
        first = np.empty(ks.shape, bool)
        first[0] = True
        np.not_equal(ks[1:], ks[:-1], out=first[1:])
        rep_sorted = order[np.flatnonzero(first)][np.cumsum(first) - 1]
        cand = (rep_sorted != order) & ~pinned[pool[order]]
        cand_rows = pool[order[cand]]
        cand_reps = pool[rep_sorted[cand]]
        if cand_rows.size == 0:
            continue
        # Exact verification — the sketch only proposes; rows whose diff
        # exceeds the cap stay in the pool for the next probe.
        nd = (items[cand_rows] != items[cand_reps]).sum(axis=1)
        good = nd <= max_diffs
        rep_of[cand_rows[good]] = cand_reps[good]
        pinned[cand_reps[good]] = True
        pool = pool[rep_of[pool] < 0]
    return rep_of


def encode_delta(items: np.ndarray, *, max_diffs: int = 16,
                 n_probes: int = 3,
                 min_delta_fraction: float = 0.0,
                 use_native: bool = True) -> DeltaEncoding | None:
    """Encode [N, S] uint32 rows, or None when not worthwhile.

    ``min_delta_fraction``: bail out (None) unless at least this fraction
    of rows lands in the delta lane — the caller then ships the plain
    packed lane with zero overhead.
    """
    items = np.ascontiguousarray(items, dtype=np.uint32)
    n, s = items.shape if items.ndim == 2 else (0, 0)
    if n < 2 or s == 0 or s > 255 or max_diffs > 255:
        return None
    # Break-even clamp: a delta row must beat a full row on the wire even
    # in the 24-bit-pack case (4 B base ref + 1 B count + nd*(1 B pos +
    # 3 B value) < 3*s B full row).  Without it, small set sizes make the
    # exact-diff verification vacuous and chance sketch collisions would
    # *grow* the transfer.  Sets of <= 3 elements can never break even.
    break_even = (3 * s - 6) // 4
    if break_even < 1:
        return None
    max_diffs = min(max_diffs, break_even)
    rep_of = None
    if use_native:
        from ..native import group_delta_native

        rep_of = group_delta_native(items, max_diffs, n_probes)
    if rep_of is None:
        rep_of = _group_rows(items, max_diffs, n_probes)
    is_delta = rep_of >= 0
    d = int(is_delta.sum())
    if d < max(1, int(min_delta_fraction * n)):
        return None
    delta_idx = np.flatnonzero(is_delta)
    full_rank = np.cumsum(~is_delta) - 1
    delta_rows = items[delta_idx]
    neq = delta_rows != items[rep_of[delta_idx]]
    counts = neq.sum(axis=1, dtype=np.int64)
    _, pos = np.nonzero(neq)
    return DeltaEncoding(
        n=n, set_size=s,
        mask_bits=np.packbits(is_delta, bitorder="little"),
        full_rows=np.ascontiguousarray(items[~is_delta]),
        rep_in_full=full_rank[rep_of[delta_idx]].astype(np.int32),
        counts=counts.astype(np.uint8),
        pos_flat=pos.astype(np.uint8),
        val_flat=delta_rows[neq],
    )


def decode_host(enc: DeltaEncoding) -> np.ndarray:
    """Reference decoder (numpy) — the device decoder's test oracle."""
    is_delta = np.unpackbits(enc.mask_bits, bitorder="little")[:enc.n]
    out = np.empty((enc.n, enc.set_size), np.uint32)
    out[~is_delta.astype(bool)] = enc.full_rows
    base = enc.full_rows[enc.rep_in_full].copy()
    rows = np.repeat(np.arange(enc.n_delta), enc.counts)
    base[rows, enc.pos_flat] = enc.val_flat
    out[is_delta.astype(bool)] = base
    return out
