"""Cross-row base-delta H2D encoding for the cluster pipeline.

The north-star transfer (BASELINE.json: ~1M session feature sets to the
device) is link-bound: round 4 measured 7.2 s of a 9.5 s wall moving
183 MB of 24-bit-packed features over a ~25 MB/s tunneled PJRT link.
Within-row compression cannot help — uniform 64-element sets over a 2^24
universe carry ~19.4 bits/element of entropy, and a measured round-4
attempt at sorted-gap packing lost more to the one-core host sort than it
saved on the wire.  The redundancy that IS there is *cross-row*: fuzzing
sessions of the same target hit near-identical coverage sets (the planted
synth workload mirrors this — ~60% of rows differ from a shared base row
in only ~6 of 64 positions, and rows of one cluster share positional
layout, so no sort is needed).

Scheme: a cheap host MinHash sketch groups probable near-duplicate rows;
each group's first row stays in the **full lane** (24-bit packed, as
before) and every other member travels in the **delta lane** as (base row
id, changed positions, new values) — ~30 bytes instead of 192.  A
membership bitmask (1 bit/row) lets the device reassemble original order.
Grouping is only a *compression heuristic*: every candidate pair is
verified by exact element comparison (diff count ≤ ``max_diffs``) before
it is encoded, so decode reproduces the input bit-exactly regardless of
sketch quality, and labels match the un-encoded pipeline elementwise.

Measured at 1M x 64 synth (round 5): 98% of true near-duplicates
captured, wire 183 MB -> ~103 MB; numpy encode ~2.3 s, native (C++)
encode ~0.3 s (``native/encode.cc``, used automatically when it loads).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# One (multiply-add) hash pass per probe; (min, max) of the hashed row is
# the group key.  Two order statistics from one pass give ~J^2 ~ 0.8
# capture per probe with negligible cross-cluster key collisions (each
# statistic concentrates in a ~2^26 band; their pair spans ~2^52).
_PROBES = ((0x9E3779B1, 0x85EBCA77), (0xC2B2AE3D, 0x27D4EB2F),
           (0x165667B1, 0x9E3779B9), (0x85EBCA6B, 0xC2B2AE35))

# Encoding only pays when the transfer is seconds long; below this raw
# size a single put is already cheap and the sketch pass would dominate.
_AUTO_MIN_BYTES = 64 * 1024 * 1024
# ...and only when enough rows actually compress (wire win ~= delta
# fraction * 160 B/row; under 5% the bookkeeping lanes eat the win).
_AUTO_MIN_DELTA_FRACTION = 0.05


@dataclass(frozen=True)
class DeltaEncoding:
    """Host-side product of :func:`encode_delta` — the exact wire layout.

    Lanes preserve original row order within themselves; ``mask_bits``
    (little-endian packbits of the 1=delta membership bit per row) is all
    the device needs to map lane ranks back to original indices.
    """

    n: int                  # original row count
    set_size: int
    mask_bits: np.ndarray   # [ceil(n/8)] uint8, little bit order
    full_rows: np.ndarray   # [F, S] uint32 — rows that travel whole
    rep_in_full: np.ndarray  # [D] int32 — full-lane rank of each delta row's base
    counts: np.ndarray      # [D] uint8 — changed positions per delta row
    pos_flat: np.ndarray    # [T] uint8 — changed positions, row-major
    val_flat: np.ndarray    # [T] uint32 — replacement values

    @property
    def n_delta(self) -> int:
        return int(self.rep_in_full.shape[0])

    @property
    def n_full(self) -> int:
        return int(self.full_rows.shape[0])

    def wire_bytes(self, packed24: bool) -> int:
        """Bytes this encoding puts on the H2D link (3 B/value when the
        24-bit pack applies, else 4)."""
        vb = 3 if packed24 else 4
        return (self.mask_bits.nbytes + self.full_rows.shape[0]
                * self.set_size * vb + self.rep_in_full.nbytes
                + self.counts.nbytes + self.pos_flat.nbytes
                + self.val_flat.shape[0] * vb)


def sketch_keys(rows: np.ndarray, probe: int) -> np.ndarray:
    """[K, S] uint32 rows -> [K] uint64 group keys ((min, max) of one
    multiply-add hash pass).  Shared by the numpy and native encoders so
    their groupings agree."""
    a, b = _PROBES[probe]
    h = rows * np.uint32(a) + np.uint32(b)
    return ((h.min(axis=1).astype(np.uint64) << np.uint64(32))
            | h.max(axis=1).astype(np.uint64))


def _group_rows(items: np.ndarray, max_diffs: int, n_probes: int,
                ) -> np.ndarray:
    """[N] int64 rep_of: original index of each row's verified base row,
    -1 for full-lane rows.  Invariant: rep_of[rep_of[i]] == -1 (no
    chains) — a row with children is pinned to the full lane, and later
    probes keep pinned rows in the pool as grouping targets only."""
    n = items.shape[0]
    rep_of = np.full(n, -1, np.int64)
    pinned = np.zeros(n, bool)
    pool = np.arange(n)
    for p in range(min(n_probes, len(_PROBES))):
        if pool.size < 2:
            break
        keys = sketch_keys(items[pool], p)
        # Stable sort by (key, pinned-first): a pinned row heads its group
        # whenever one is present, so stragglers attach to existing bases
        # instead of spawning a second base for the same cluster.
        order = np.lexsort((~pinned[pool], keys))
        ks = keys[order]
        first = np.empty(ks.shape, bool)
        first[0] = True
        np.not_equal(ks[1:], ks[:-1], out=first[1:])
        rep_sorted = order[np.flatnonzero(first)][np.cumsum(first) - 1]
        cand = (rep_sorted != order) & ~pinned[pool[order]]
        cand_rows = pool[order[cand]]
        cand_reps = pool[rep_sorted[cand]]
        if cand_rows.size == 0:
            continue
        # Exact verification — the sketch only proposes; rows whose diff
        # exceeds the cap stay in the pool for the next probe.
        nd = (items[cand_rows] != items[cand_reps]).sum(axis=1)
        good = nd <= max_diffs
        rep_of[cand_rows[good]] = cand_reps[good]
        pinned[cand_reps[good]] = True
        pool = pool[rep_of[pool] < 0]
    return rep_of


def encode_delta(items: np.ndarray, *, max_diffs: int = 16,
                 n_probes: int = 3,
                 min_delta_fraction: float = 0.0,
                 use_native: bool = True) -> DeltaEncoding | None:
    """Encode [N, S] uint32 rows, or None when not worthwhile.

    ``min_delta_fraction``: bail out (None) unless at least this fraction
    of rows lands in the delta lane — the caller then ships the plain
    packed lane with zero overhead.
    """
    items = np.ascontiguousarray(items, dtype=np.uint32)
    n, s = items.shape if items.ndim == 2 else (0, 0)
    if n < 2 or s == 0 or s > 255 or max_diffs > 255:
        return None
    # Break-even clamp: a delta row must beat a full row on the wire even
    # in the 24-bit-pack case (4 B base ref + 1 B count + nd*(1 B pos +
    # 3 B value) < 3*s B full row).  Without it, small set sizes make the
    # exact-diff verification vacuous and chance sketch collisions would
    # *grow* the transfer.  Sets of <= 3 elements can never break even.
    break_even = (3 * s - 6) // 4
    if break_even < 1:
        return None
    max_diffs = min(max_diffs, break_even)
    rep_of = None
    if use_native:
        from ..native import group_delta_native

        rep_of = group_delta_native(items, max_diffs, n_probes)
    if rep_of is None:
        rep_of = _group_rows(items, max_diffs, n_probes)
    is_delta = rep_of >= 0
    d = int(is_delta.sum())
    if d < max(1, int(min_delta_fraction * n)):
        return None
    delta_idx = np.flatnonzero(is_delta)
    full_rank = np.cumsum(~is_delta) - 1
    delta_rows = items[delta_idx]
    neq = delta_rows != items[rep_of[delta_idx]]
    counts = neq.sum(axis=1, dtype=np.int64)
    _, pos = np.nonzero(neq)
    return DeltaEncoding(
        n=n, set_size=s,
        mask_bits=np.packbits(is_delta, bitorder="little"),
        full_rows=np.ascontiguousarray(items[~is_delta]),
        rep_in_full=full_rank[rep_of[delta_idx]].astype(np.int32),
        counts=counts.astype(np.uint8),
        pos_flat=pos.astype(np.uint8),
        val_flat=delta_rows[neq],
    )


def decode_host(enc: DeltaEncoding) -> np.ndarray:
    """Reference decoder (numpy) — the device decoder's test oracle."""
    is_delta = np.unpackbits(enc.mask_bits, bitorder="little")[:enc.n]
    out = np.empty((enc.n, enc.set_size), np.uint32)
    out[~is_delta.astype(bool)] = enc.full_rows
    base = enc.full_rows[enc.rep_in_full].copy()
    rows = np.repeat(np.arange(enc.n_delta), enc.counts)
    base[rows, enc.pos_flat] = enc.val_flat
    out[is_delta.astype(bool)] = base
    return out


# ---------------------------------------------------------------------------
# Adaptive bit-width wire packing.
#
# The fixed 24-bit pack left bytes on the table in both directions: chunks
# whose value range fits 16 (or, quantized, 10) bits still shipped 3 bytes
# per value, and chunks with ids >= 2^24 fell all the way back to raw
# uint32.  Here every chunk picks its own width from its actual value
# range (min subtracted, so a narrow band high in the id space still packs
# tight): byte-multiple widths (8/16/24/32) travel as cheap byte views,
# sub-byte/odd widths as a little-endian bit stream.  The same machinery
# bit-packs the delta lanes' positions (6 bits for 64-element sets),
# counts, and base references, which the fixed scheme shipped at full
# uint8/int32 width.  Devices decode with pipeline._unpack_bits /
# minhash_pallas' fused byte unpack (or, for wire-v3 entropy-coded
# lanes, cluster/kernels/rans.py), so decoded bytes never cross the
# link; `unpack_bits_host` below is the decoders' numpy oracle.

# Lossy id quantization (b-bit minwise hashing, arXiv:1205.2958: MinHash
# pipelines tolerate aggressive universe reduction): ids hashed into a
# 2^b universe via Fibonacci multiply-shift.  Set resemblance — the only
# thing MinHash reads — survives because identical ids collide
# identically and cross-id collisions are ~set_size/2^b per pair;
# measured at 200k planted sessions, ari_vs_planted is unchanged to the
# third decimal down to b=8.  Applied identically to every lane (and to
# both the encoded and plain paths), so label parity between encodings is
# preserved; labels differ from an unquantized run only through the
# quantized universe, gated by the bench's ari_vs_planted >= 0.98.
_QUANT_MULT = np.uint32(0x9E3779B1)  # Fibonacci hashing: top bits well-mixed
_AUTO_QUANT_BITS = 10


def quantize_ids(items: np.ndarray, bits: int) -> np.ndarray:
    """Hash uint32 ids into a 2^bits universe (top `bits` of a
    multiply-shift).  Deterministic per value: equal sets stay equal."""
    if not 1 <= bits <= 32:
        raise ValueError(f"quantization bits must be in [1, 32], got {bits}")
    if bits == 32:
        return items
    return ((items * _QUANT_MULT) >> np.uint32(32 - bits)).astype(np.uint32)


def width_bits(max_value: int) -> int:
    """Minimal bit width holding max_value (>= 1 so empty/zero lanes still
    have a well-formed stream)."""
    return max(1, int(max_value).bit_length())


def snap_byte_width(bits: int) -> int:
    """Round a bit width up to the nearest byte multiple (8/16/24/32)."""
    return min(32, ((bits + 7) // 8) * 8)


def pack_bits_host(vals: np.ndarray, bits: int) -> np.ndarray:
    """Pack `vals` (any shape, values < 2^bits after uint32 cast) into a
    little-endian uint8 bit stream of ceil(size*bits/8) bytes; value i
    occupies stream bits [i*bits, (i+1)*bits).  Byte-multiple widths take
    a zero-copy-ish byte-view path; other widths go through packbits."""
    v = np.ascontiguousarray(vals, dtype="<u4").reshape(-1)
    if bits % 8 == 0:
        k = bits // 8
        return np.ascontiguousarray(
            v[:, None].view(np.uint8)[:, :k]).reshape(-1)
    # Sub-byte/odd widths: expand to a bit matrix and packbits.  Sliced
    # (cache-resident pieces, 8-value-aligned so every slice emits whole
    # bytes) and shifted in the narrowest dtype — 4-8x faster than one
    # huge uint32 bit matrix at 1M x 64 scale, which matters because this
    # runs on the producer thread the compute stage hides behind.
    dt = np.uint16 if bits <= 16 else np.uint32
    vv = v.astype(dt, copy=False)
    shifts = np.arange(bits, dtype=dt)
    step = 1 << 20
    out = []
    for i in range(0, v.size, step):
        bitmat = ((vv[i:i + step, None] >> shifts) & 1).astype(np.uint8)
        out.append(np.packbits(bitmat.reshape(-1), bitorder="little"))
    if not out:
        return np.zeros(0, np.uint8)
    return out[0] if len(out) == 1 else np.concatenate(out)


def unpack_bits_host(packed: np.ndarray, n: int, bits: int) -> np.ndarray:
    """Inverse of :func:`pack_bits_host` — the device unpack kernels'
    numpy oracle.  Returns [n] uint32."""
    if n == 0:
        return np.empty(0, np.uint32)
    if bits % 8 == 0:
        k = bits // 8
        b = packed[:n * k].reshape(n, k).astype(np.uint32)
        out = b[:, 0]
        for j in range(1, k):
            out = out | (b[:, j] << np.uint32(8 * j))
        return out
    bitmat = np.unpackbits(packed, bitorder="little")[:n * bits]
    weights = (np.uint32(1) << np.arange(bits, dtype=np.uint32))
    return (bitmat.reshape(n, bits).astype(np.uint32) * weights).sum(
        axis=1, dtype=np.uint32)


@dataclass(frozen=True)
class ChunkWire:
    """One chunk's wire form: a packed uint8 payload + the header the
    device needs to decode it (bits, offset bias, logical shape).  The
    header never rides the link per-value — it travels as static decode
    arguments / one batched metadata transfer.

    Wire v3: when the chunk's values are skewed enough that a static
    entropy table beats the fixed width (cluster/entropy.py's measured
    win threshold), ``ent`` holds the rANS frame and ``payload`` is
    empty — the chunk ships the frame's arrays instead.  ``bits`` and
    ``offset`` keep their meaning (the coded symbols are the
    offset-subtracted values), so decode is entropy-decode + offset."""

    payload: np.ndarray      # uint8 bit/byte stream (empty when ent)
    n_values: int            # logical value count (rows * set_size)
    bits: int                # wire width per value
    offset: int              # subtracted min; device adds it back
    shape: tuple             # logical decoded shape
    ent: "object | None" = None  # entropy.EntropyLane when rANS-coded

    @property
    def nbytes(self) -> int:
        if self.ent is not None:
            return int(self.ent.nbytes)
        return int(self.payload.nbytes)

    def wire_arrays(self) -> list:
        """The exact host arrays this chunk puts on the wire (the
        transfer-probe / drift-guard inventory)."""
        if self.ent is not None:
            return self.ent.wire_arrays()
        return [self.payload]

    def device_payload(self):
        """What the pipeline device_puts for this chunk: the packed
        stream, or the entropy frame's array tuple."""
        if self.ent is not None:
            return tuple(self.ent.wire_arrays())
        return self.payload


def chunk_wire_bits(chunk: np.ndarray, pack_limit: int = 1 << 24,
                    ) -> tuple[int, int]:
    """(bits, offset) for one chunk under the adaptive rule: subtract the
    chunk min, take the minimal width of the remaining range, and snap
    widths > 16 up to a byte multiple (the sub-byte bit stream's host
    cost only pays off below ~2 B/value).  ``pack_limit`` keeps the
    historical kill switch: chunks containing ids >= the limit ship raw
    uint32, exactly like the old 24-bit pack's fallback."""
    if chunk.size == 0:
        return 8, 0
    mx = int(chunk.max())
    if mx >= pack_limit:
        return 32, 0
    offset = int(chunk.min())
    bits = width_bits(mx - offset)
    if bits > 16:
        bits = snap_byte_width(bits)
    if bits >= 32:
        offset = 0
        bits = 32
    return bits, offset


def pack_chunk(chunk: np.ndarray, pack_limit: int = 1 << 24,
               entropy: str = "off",
               stats: dict | None = None) -> ChunkWire:
    """Adaptive-width wire form of a uint32 chunk (any shape).

    ``entropy``: 'off' ships the bit-packed stream (the v2 format);
    'auto' additionally offers the chunk to the rANS codec and ships
    whichever is smaller (the per-chunk win threshold — quantized/uniform
    chunks always fall back to the plain pack); 'force' entropy-codes
    regardless (tests/CI).  ``stats`` (mutable dict) accrues the codec's
    encode seconds / bytes saved for StageRecorder."""
    bits, offset = chunk_wire_bits(chunk, pack_limit)
    vals = chunk if offset == 0 else chunk - np.uint32(offset)
    ent = _try_entropy(vals, bits, entropy, stats)
    if ent is not None:
        return ChunkWire(payload=np.zeros(0, np.uint8),
                         n_values=int(chunk.size), bits=bits,
                         offset=offset, shape=tuple(chunk.shape), ent=ent)
    return ChunkWire(payload=pack_bits_host(vals, bits),
                     n_values=int(chunk.size), bits=bits, offset=offset,
                     shape=tuple(chunk.shape))


def _try_entropy(vals: np.ndarray, bits: int, entropy: str,
                 stats: dict | None):
    """The per-lane codec gate: an EntropyLane when it wins (or is
    forced), else None; accounting lands in ``stats``."""
    if entropy not in ("off", "auto", "force"):
        raise ValueError(f"unknown entropy mode {entropy!r}; "
                         "expected off | auto | force")
    if entropy == "off":
        return None
    import time

    from . import entropy as ent_mod

    t0 = time.perf_counter()
    lane = ent_mod.encode_lane(vals, bits, force=(entropy == "force"))
    if stats is not None:
        stats["entropy_s"] = (stats.get("entropy_s", 0.0)
                              + time.perf_counter() - t0)
        if lane is not None:
            stats["entropy_lanes"] = stats.get("entropy_lanes", 0) + 1
            stats["entropy_coded_bytes"] = (
                stats.get("entropy_coded_bytes", 0) + lane.nbytes)
            stats["entropy_saved_bytes"] = (
                stats.get("entropy_saved_bytes", 0)
                + ent_mod.packed_nbytes(int(vals.size), bits)
                - lane.nbytes)
    return lane


def unpack_chunk_host(wire: ChunkWire) -> np.ndarray:
    """Reference decoder for :func:`pack_chunk`."""
    vals = unpack_bits_host(wire.payload, wire.n_values, wire.bits)
    if wire.offset:
        vals = vals + np.uint32(wire.offset)
    return vals.reshape(wire.shape)


@dataclass(frozen=True)
class LaneWire:
    """One metadata lane's wire form: a minimal-width bit stream, or —
    wire v3 — a rANS frame when the lane's skew beats the fixed width
    (cluster/entropy.py's measured win threshold)."""

    n: int                   # value count
    bits: int                # logical value width
    packed: np.ndarray | None = None   # uint8 bit stream
    ent: "object | None" = None        # entropy.EntropyLane

    @property
    def nbytes(self) -> int:
        if self.ent is not None:
            return int(self.ent.nbytes)
        return int(self.packed.nbytes)

    def wire_arrays(self) -> list:
        if self.ent is not None:
            return self.ent.wire_arrays()
        return [self.packed]

    def device_payload(self):
        if self.ent is not None:
            return tuple(self.ent.wire_arrays())
        return self.packed


def pack_lane(vals: np.ndarray, bits: int, entropy: str = "off",
              stats: dict | None = None) -> LaneWire:
    """Wire form of one metadata lane under the v3 per-lane choice."""
    ent = _try_entropy(vals, bits, entropy, stats)
    if ent is not None:
        return LaneWire(n=int(vals.size), bits=bits, ent=ent)
    return LaneWire(n=int(vals.size), bits=bits,
                    packed=pack_bits_host(vals, bits))


@dataclass(frozen=True)
class DeltaMetaWire:
    """Wire form of a DeltaEncoding's metadata lanes.

    The fixed layout shipped rep at int32, counts at uint8 and positions
    at uint8 regardless of content; here each lane packs at its minimal
    width — 6-bit positions for 64-element sets, ~5-bit counts, ~19-bit
    base references at 1M rows — and, under wire v3, any lane whose skew
    beats its fixed width ships a static-table rANS frame instead
    (per-lane choice, plain pack fallback).  The value lane reuses the
    adaptive chunk packer.  The whole object ships as ONE pytree
    device_put (pipeline._put_delta_meta)."""

    rep: LaneWire
    counts: LaneWire
    pos: LaneWire
    val: ChunkWire

    @property
    def nbytes(self) -> int:
        return int(self.rep.nbytes + self.counts.nbytes + self.pos.nbytes
                   + self.val.nbytes)

    def lanes(self) -> tuple:
        return (self.rep, self.counts, self.pos)

    def wire_arrays(self) -> list:
        out: list = []
        for lane in self.lanes():
            out += lane.wire_arrays()
        out += self.val.wire_arrays()
        return out


def pack_delta_meta(enc: DeltaEncoding, pack_limit: int = 1 << 24,
                    entropy: str = "off",
                    stats: dict | None = None) -> DeltaMetaWire:
    """Pack a DeltaEncoding's rep/counts/pos/val lanes for the wire."""
    rep_bits = width_bits(max(enc.n_full - 1, 1))
    counts_bits = width_bits(int(enc.counts.max()) if enc.n_delta else 1)
    pos_bits = width_bits(max(enc.set_size - 1, 1))
    return DeltaMetaWire(
        rep=pack_lane(enc.rep_in_full, rep_bits, entropy, stats),
        counts=pack_lane(enc.counts, counts_bits, entropy, stats),
        pos=pack_lane(enc.pos_flat, pos_bits, entropy, stats),
        val=pack_chunk(enc.val_flat, pack_limit, entropy, stats))
