"""Checkpoint/resume for the device clustering pipeline (SURVEY §5 A4,
TPU-build note: "same pattern for signature/cluster shards").

The streamed pipeline already computes MinHash signatures chunk-by-chunk
(`pipeline._minhash_streamed`); this module persists each chunk's
(signatures, band keys) shard with a manifest, so an interrupted long run
— a 1M+ study over a slow link, or one host of a pod job — resumes at the
first unfinished chunk and goes straight to label propagation once all
shards exist.  Collection-side counterpart: `collect/checkpoint.py`
(batch files + merge, the reference's 2_get_buildlog_metadata.py:141-147
pattern); here the "batch" is a device-shard npz and the "merge" is the
device concatenation feeding label propagation.

Durability contract: a crash loses at most the chunk in flight (shards are
written tmp-then-rename, so a torn write is invisible to resume).  The
manifest fingerprints the inputs and every shape-affecting parameter; a
resume against different items or params refuses instead of silently
mixing shards.  Each shard is additionally CRC-framed (`store.file_crc`,
frame recorded in the manifest): a flipped byte anywhere in a committed
shard — bit rot, not just truncation — reads as 'not done' and the chunk
recomputes, mirroring the signature store's self-healing layer.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from ..resilience import fault_point, io_retry_policy, retry_call
from ..utils.logging import get_logger

log = get_logger("cluster.checkpoint")

_MANIFEST = "manifest.json"


def _items_fingerprint(items: np.ndarray) -> str:
    """Full-content fingerprint (shape + dtype + every byte).  blake2b
    streams ~1 GB/s, so even 1M x 64 costs ~0.25 s — cheap insurance next
    to a checkpointed long run, and a sampled hash would let a resume
    silently mix shards from a changed study (rows off the sample stride)
    into wrong labels."""
    h = hashlib.blake2b(digest_size=16)
    h.update(repr((items.shape, str(items.dtype))).encode())
    h.update(np.ascontiguousarray(items).tobytes())
    return h.hexdigest()


class ClusterCheckpoint:
    """Per-chunk signature/key shards + manifest under ``directory``.

    Multi-host: give each process its own directory (e.g. suffixed with
    ``jax.process_index()``) — shards are process-local row ranges.
    """

    def __init__(self, directory: str, items: np.ndarray, params,
                 step: int, extra: dict | None = None,
                 n_chunks: int | None = None) -> None:
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.meta = {
            "fingerprint": _items_fingerprint(items),
            "n": int(items.shape[0]),
            "set_size": int(items.shape[1]),
            "n_hashes": params.n_hashes,
            "n_bands": params.n_bands,
            "seed": params.seed,
            # Signature scheme (cluster/schemes.py): shards hold this
            # kernel family's signatures, so a resume under a different
            # scheme must refuse like any policy change.
            "scheme": getattr(params, "scheme", "kminhash"),
            "step": int(step),
            # Shape-affecting facts beyond (items, params) — e.g. the delta
            # encoder's lane split, which decides what each chunk contains.
            **(extra or {}),
        }
        if n_chunks is not None:
            self.meta["n_chunks"] = int(n_chunks)
        self._manifest_path = os.path.join(directory, _MANIFEST)
        prior = self._load_manifest()
        if prior is not None:
            # Symmetric comparison: a prior manifest carrying keys this run
            # doesn't (e.g. a delta-encoded run resumed without encoding)
            # means the shards hold different rows — refuse, don't load.
            prior_meta = {k: v for k, v in prior.items()
                          if k not in ("chunks_done", "chunk_crcs")}
            # Migration default: a manifest written before schemes
            # existed holds kminhash shards by definition — it must
            # RESUME under scheme="kminhash", not refuse on a key it
            # could not have known.
            prior_meta.setdefault("scheme", "kminhash")
            if prior_meta != self.meta:
                # The meta diff, not the raw dicts: a long chunks_done
                # list would bury the one key that actually differs
                # (e.g. wire_quant_bits — shards hold signatures of the
                # QUANTIZED universe, so a policy change means every
                # shard is wrong for this run).
                diff = {k: (prior_meta.get(k), self.meta.get(k))
                        for k in set(prior_meta) | set(self.meta)
                        if prior_meta.get(k) != self.meta.get(k)}
                raise ValueError(
                    f"checkpoint at {directory} belongs to a different "
                    "run (items or params changed); use a fresh directory "
                    f"or delete it. mismatched (have, want): {diff}")
            self.done = set(prior["chunks_done"])
            self.chunk_crcs = {str(k): int(v) for k, v in
                               (prior.get("chunk_crcs") or {}).items()}
            log.info("resuming cluster run: %d/%d chunks already done",
                     len(self.done), self.n_chunks)
        else:
            self.done = set()
            self.chunk_crcs = {}
            self._write_manifest()

    @property
    def n_chunks(self) -> int:
        if "n_chunks" in self.meta:
            return self.meta["n_chunks"]
        return -(-self.meta["n"] // self.meta["step"])

    @staticmethod
    def peek_meta(directory: str) -> dict | None:
        """The existing manifest's meta (or None) WITHOUT constructing a
        checkpoint — the resume path reads the surviving wire policy
        from here (e.g. a degraded wire_quant_bits) before planning, so
        an auto-policy resume clamps to what the shards actually hold
        instead of refusing."""
        path = os.path.join(directory, _MANIFEST)
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _load_manifest(self) -> dict | None:
        if not os.path.exists(self._manifest_path):
            return None
        with open(self._manifest_path) as f:
            return json.load(f)

    def _write_manifest(self) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({**self.meta, "chunks_done": sorted(self.done),
                       "chunk_crcs": self.chunk_crcs}, f)
        os.replace(tmp, self._manifest_path)

    def _shard_path(self, index: int) -> str:
        return os.path.join(self.directory, f"shard_{index:05d}.npz")

    def chunk_done(self, index: int) -> bool:
        return index in self.done and self._shard_ok(index)

    def _shard_ok(self, index: int) -> bool:
        """True when the shard file exists, passes its CRC frame (a
        flipped byte anywhere fails here) AND loads — a torn/truncated/
        bit-rotted npz on disk must read as 'not done' so resume
        recomputes it instead of crashing or silently clustering
        garbage."""
        path = self._shard_path(index)
        if not os.path.exists(path):
            return False
        want = self.chunk_crcs.get(str(index))
        if want is not None:
            from .store import file_crc

            try:
                got = file_crc(path)
            except OSError:
                return False
            if int(got) != int(want):
                log.warning("shard %s failed its CRC frame (stored %d, "
                            "computed %d); will recompute", path, want, got)
                return False
        try:
            with np.load(path) as z:
                return "sig" in z.files and "keys" in z.files
        except Exception as e:  # graftlint: disable=broad-except -- a torn shard must read as not-done whatever the failure mode
            log.warning("shard %s unreadable (%s); will recompute", path, e)
            return False

    def save_chunk(self, index: int, sig: np.ndarray,
                   keys: np.ndarray) -> None:
        """Persist one chunk's shard atomically (tmp + rename), then mark
        it done in the manifest — a crash mid-write leaves the chunk
        'not done' and it recomputes on resume.  The write itself runs
        under the shared retry engine: a transient I/O failure (or an
        injected torn write) rewrites the tmp file from scratch."""
        from .store import file_crc

        path = self._shard_path(index)
        tmp = path + ".tmp.npz"
        crc = {}

        def write_shard() -> None:
            np.savez(tmp, sig=sig, keys=keys)
            crc["v"] = file_crc(tmp)  # frame the exact published bytes
            fault_point("checkpoint.cluster.save", path=tmp)
            os.replace(tmp, path)

        retry_call(write_shard, policy=io_retry_policy(),
                   site="checkpoint.cluster.save")
        self.done.add(index)
        self.chunk_crcs[str(index)] = crc["v"]
        self._write_manifest()

    def load_chunk(self, index: int) -> tuple[np.ndarray, np.ndarray]:
        with np.load(self._shard_path(index)) as z:
            return z["sig"], z["keys"]

    def load_chunk_or_none(self, index: int):
        """(sig, keys) or None when the shard is missing/torn — the
        pipeline's resume path falls back to recomputing the chunk."""
        try:
            with np.load(self._shard_path(index)) as z:
                return z["sig"], z["keys"]
        except Exception as e:  # graftlint: disable=broad-except -- a torn shard must read as not-done whatever the failure mode
            log.warning("shard %d unreadable at load (%s); recomputing",
                        index, e)
            self.done.discard(index)
            return None

    def cleanup(self) -> None:
        """Remove shards + manifest after a completed run — including any
        orphaned ``.tmp.npz`` left by a crash mid-save (a torn write is
        invisible to resume, but its temp file still occupies disk)."""
        import glob

        for p in glob.glob(os.path.join(self.directory,
                                        "shard_*.npz.tmp.npz")):
            os.remove(p)
        for i in range(self.n_chunks):
            p = self._shard_path(i)
            if os.path.exists(p):
                os.remove(p)
        if os.path.exists(self._manifest_path):
            os.remove(self._manifest_path)
