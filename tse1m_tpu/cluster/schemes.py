"""Signature-scheme registry: THE dispatch point for signature kernels.

The pipeline's original assumption — "a signature is K independent
multiply-shift MinHashes" — is now one member of a kernel *family*,
selected per run by the ``scheme`` policy field (ClusterParams.scheme,
the store/checkpoint policy tuple, the serve daemon's ingest path):

- ``kminhash`` — the original K-permutation multiply-add family
  (minhash.minhash_signatures).  Bit-compatible with every store and
  checkpoint written before schemes existed: a manifest with no
  ``scheme`` key loads as kminhash.
- ``cminhash`` — one-permutation hashing with circulant-shift repair
  (C-MinHash, arXiv:2109.03337/2109.04595) and bounded optimal-style
  densification (arXiv:1703.04664) for sparse rows.  ONE element-hash
  pass instead of K: ~``n_hashes``× fewer hash evaluations per row,
  which is the whole device-compute story post-prefilter (the rows the
  host prefilter keeps are exactly the rows that pay kernel time).
- ``weighted`` — exact weighted minwise hashing over integer hit
  counts (arXiv:1602.08393 lineage): each (element, weight) pair
  expands host-side into ``weight`` replica ids (``expand_weighted``),
  and the cminhash kernel runs over the replica universe.  Weighted
  Jaccard of the (clipped-integer) weighted sets equals plain Jaccard
  of the replica sets, so every downstream stage — banding, LSH,
  verification, label propagation, the store, the serve plane — works
  unchanged on the expanded rows.

Every module that *computes* signatures must dispatch through this
registry (graftlint rule ``scheme-parity``); the raw kernels in
minhash.py / minhash_pallas.py / host.py are implementation detail.
That is what makes the bit-parity story auditable: host oracle, device
reference, pallas variant and serve-side host MinHash all draw their
constants from one ``make_params`` and are CI-asserted bit-identical
per scheme.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

SCHEMES = ("kminhash", "cminhash", "weighted")
DEFAULT_SCHEME = "kminhash"

# Densification schedule length (cminhash): chained donor rounds square
# the empty-bin fraction per round, so 12 rounds fill any row with at
# least one non-empty bin to ~1e-4 residual even at |S| = H/32; the
# circulant fallback covers the residual deterministically.
_T_DENSIFY = 12

# Weighted expansion: hit counts clip to [1, MAX_WEIGHT] (a count of 0
# still means "this edge was covered" — set membership is the floor the
# reference paper models; the weights refine it).  Replica ids embed as
# x * _REPLICA_MULT + r — an odd-multiplier hash embedding, injective in
# x per replica index; cross-pair collisions are birthday-rare (~(S*W)^2
# / 2^33 per row pair) and land below the verifier's threshold noise.
MAX_WEIGHT = 8
_REPLICA_MULT = np.uint32(0x85EBCA6B)


@dataclass(frozen=True)
class HashParams:
    """One scheme's resolved hash constants (host numpy arrays).

    ``arrays`` is the positional constant tuple the scheme's kernels
    take after ``items`` — (a, b) for kminhash, (a0, b0, jmap, offs)
    for cminhash/weighted.  Derived deterministically from (scheme,
    n_hashes, seed) so host and device share them bit-identically.
    """

    scheme: str
    n_hashes: int
    arrays: tuple

    def device(self) -> "HashParams":
        """The same params with device-resident arrays (one conversion
        per run, outside the hot loop — the runtime sanitizer rejects
        per-chunk implicit staging)."""
        import jax.numpy as jnp

        return HashParams(self.scheme, self.n_hashes,
                          tuple(jnp.asarray(a) for a in self.arrays))


def get_scheme(name: str) -> str:
    if name not in SCHEMES:
        raise ValueError(
            f"unknown signature scheme {name!r}; valid schemes: "
            f"{', '.join(SCHEMES)}")
    return name


def _one_perm_consts(n_hashes: int, seed: int, stream: int) -> tuple:
    """(a0, b0, jmap, offs) for the one-permutation kernel.  ``stream``
    separates the cminhash and weighted constant streams so the two
    schemes' signatures of identical rows differ (their stores must not
    be confusable even before the policy key refuses)."""
    rng = np.random.default_rng([int(seed) & 0xFFFFFFFF, stream])
    # Shape-(1,) rather than 0-d: jnp.asarray of a 0-d numpy scalar
    # converts via convert_element_type — an IMPLICIT transfer the
    # runtime sanitizer rejects; 1-element arrays ride device_put like
    # every other constant, and uint32 broadcasting is unchanged.
    a0 = np.array([int(rng.integers(1, 1 << 32)) | 1], np.uint32)
    b0 = np.array([int(rng.integers(0, 1 << 32))], np.uint32)
    # Donor maps must be PERMUTATIONS: a multiply-mod map whose
    # multiplier shares a factor with H collapses its image (observed:
    # 4 of 128 bins) and the densification walk starves — the estimator
    # bias the optimal-densification paper exists to kill.  A seeded
    # permutation per round keeps every bin reachable and the walk's
    # bin-priority sequence set-independent, which is the unbiasedness
    # argument (both rows stop at the first self-non-empty bin of one
    # shared sequence).
    jmap = np.stack([rng.permutation(n_hashes)
                     for _ in range(_T_DENSIFY)]).astype(np.int32)
    k = np.arange(n_hashes, dtype=np.uint64)
    cf = np.uint64(int(rng.integers(1, 1 << 32)) | 1)
    df = np.uint64(int(rng.integers(0, 1 << 32)))
    offs = ((cf * (k + np.uint64(1)) + df)
            & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return (a0, b0, jmap, offs)


def make_params(scheme: str, n_hashes: int, seed: int = 0) -> HashParams:
    """Resolve a scheme's hash constants.  kminhash keeps the exact
    pre-scheme constant stream (minhash.make_hash_params) — stores and
    checkpoints written before the registry existed stay valid."""
    get_scheme(scheme)
    if scheme == "kminhash":
        from .minhash import make_hash_params

        return HashParams(scheme, n_hashes,
                          tuple(make_hash_params(n_hashes, seed)))
    stream = 0xC31F if scheme == "cminhash" else 0x3E16
    return HashParams(scheme, n_hashes,
                      _one_perm_consts(n_hashes, seed, stream))


# -- device dispatch ---------------------------------------------------------


def scheme_signatures_traced(items, scheme: str, arrays):
    """Traced-level dispatch for shard_map/jit bodies: [N, S] items (+
    the scheme's positional constants) -> [N, H] signatures.  The
    caller owns staging ``arrays`` (e.g. shard_map in_specs)."""
    from .minhash import cminhash_signatures, minhash_signatures

    if scheme == "kminhash":
        return minhash_signatures(items, *arrays)
    return cminhash_signatures(items, *arrays)


def scheme_sig_and_keys(items, hp: HashParams, n_bands: int, *,
                        use_pallas: str = "auto", block_n: int = 512):
    """[N, S] device items -> ([N, H] signatures, [N, B] band keys),
    fused per scheme (pallas on TPU, jax elsewhere)."""
    from .minhash_pallas import cminhash_and_keys, minhash_and_keys

    if hp.scheme == "kminhash":
        return minhash_and_keys(items, *hp.arrays, n_bands,
                                use_pallas=use_pallas, block_n=block_n)
    return cminhash_and_keys(items, *hp.arrays, n_bands,
                             use_pallas=use_pallas, block_n=block_n)


def scheme_sig_and_keys_packed(payload_d, shape: tuple, k: int, offset,
                               hp: HashParams, n_bands: int, *,
                               use_pallas: str = "auto",
                               block_n: int = 512):
    """scheme_sig_and_keys over a byte-packed wire chunk.  kminhash
    keeps its fused-unpack pallas path (offset folds into the additive
    hash constant); the one-permutation schemes decode on device first
    (bit-identical by definition — decode-then-hash IS the contract the
    fused path is verified against)."""
    from .minhash_pallas import (_combine_bytes, cminhash_and_keys,
                                 minhash_and_keys_packed)

    if hp.scheme == "kminhash":
        return minhash_and_keys_packed(payload_d, shape, k, offset,
                                       *hp.arrays, n_bands,
                                       use_pallas=use_pallas,
                                       block_n=block_n)
    items = _combine_bytes(payload_d, shape, k, offset)
    return cminhash_and_keys(items, *hp.arrays, n_bands,
                             use_pallas=use_pallas, block_n=block_n)


# -- host dispatch -----------------------------------------------------------


def scheme_host_signatures(items: np.ndarray, hp: HashParams) -> np.ndarray:
    """Numpy [N, S] -> [N, H], bit-identical to the device path for the
    same scheme (the host-oracle / prefilter / serve-query contract)."""
    from .host import host_cminhash_signatures, host_signatures

    if hp.scheme == "kminhash":
        return host_signatures(items, *hp.arrays)
    return host_cminhash_signatures(items, *hp.arrays)


# -- accounting --------------------------------------------------------------


def scheme_hash_evals(scheme: str, n_rows: int, set_size: int,
                      n_hashes: int) -> int:
    """Element-hash evaluations (multiply-add over an element id) a
    signature pass executes — the honest FLOP-side comparison bench
    emits (BENCH_r09): kminhash hashes every element once per hash
    function; the one-permutation schemes hash every element once,
    period (densification/banding touch [N, H] state, never re-hash an
    element).  For ``weighted``, ``set_size`` is the expanded replica
    width."""
    get_scheme(scheme)
    if scheme == "kminhash":
        return int(n_rows) * int(set_size) * int(n_hashes)
    return int(n_rows) * int(set_size)


# -- weighted expansion ------------------------------------------------------


def expand_weighted(items: np.ndarray, weights: np.ndarray,
                    max_weight: int = MAX_WEIGHT) -> np.ndarray:
    """[N, S] ids + [N, S] integer hit counts -> [N, S'] replica ids.

    Element x with (clipped) weight w contributes replicas
    ``x * _REPLICA_MULT + r`` for r in [0, w): plain Jaccard over the
    replica sets equals weighted Jaccard over the clipped integer
    weights — the exact reduction the weighted-minwise literature
    builds on.  Rows pad to the batch's widest expansion with a
    duplicate of their own first replica (weight >= 1 everywhere, so
    the pad is always a real member and duplicates never move a min).
    The expanded matrix is what enters the pipeline: wire, store
    digests, prefilter and signatures all see the replica universe, so
    content addressing distinguishes same-support/different-counts
    rows for free."""
    items = np.ascontiguousarray(items, dtype=np.uint32)
    n, s = items.shape
    if n == 0:
        return np.empty((0, s), np.uint32)
    w = np.clip(weights, 1, int(max_weight)).astype(np.int64)
    totals = w.sum(axis=1)
    width = int(totals.max())
    reps = w.ravel()
    with np.errstate(over="ignore"):
        flat_ids = np.repeat(items.ravel(), reps)
        idx = np.arange(int(reps.sum()), dtype=np.int64)
        starts = np.repeat(np.cumsum(reps) - reps, reps)
        r = (idx - starts).astype(np.uint32)
        rep_ids = flat_ids * _REPLICA_MULT + r
        out = np.empty((n, width), np.uint32)
        out[:] = items[:, :1] * _REPLICA_MULT  # pad: own first replica
    row_starts = np.repeat(np.cumsum(totals) - totals, totals)
    row_of = np.repeat(np.arange(n, dtype=np.int64), totals)
    out[row_of, idx - row_starts] = rep_ids
    return out


__all__ = ["DEFAULT_SCHEME", "HashParams", "MAX_WEIGHT", "SCHEMES",
           "expand_weighted", "get_scheme", "make_params",
           "scheme_hash_evals", "scheme_host_signatures",
           "scheme_sig_and_keys", "scheme_sig_and_keys_packed",
           "scheme_signatures_traced"]
