"""Fused MinHash + band-key pallas kernel for TPU.

One HBM->VMEM pass per item block: load [BN, S] uint32 features, produce
both the [BN, H] signature block and the [BN, B] band keys without ever
re-reading the signatures from HBM — the band fold happens while the
signature block is still resident in VMEM.  This is the memory-bound hot
op of the north star (BASELINE.json): arithmetic intensity is low
(S multiply-add-mins per signature element), so fusing the second pass
roughly halves HBM traffic vs the two-step jax path.

Falls back transparently to the jax implementation off-TPU; tests run the
kernel in interpreter mode (minhash_pallas interpret=True) for semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .minhash import (_FNV_OFFSET, _FNV_PRIME, UMAX, band_keys,
                      cminhash_signatures, minhash_signatures)


def _kernel(items_ref, a_ref, b_ref, sig_ref, keys_ref, *, n_bands: int):
    # Loops over the set and band-row dims are statically unrolled python
    # loops (S, H/B are small compile-time constants): Mosaic has no
    # dynamic_slice lowering, and unrolling lets it software-pipeline the
    # multiply-add-min chain on the VPU.
    items = items_ref[...]  # [BN, S] uint32
    a = a_ref[...]          # [H]
    b = b_ref[...]
    bn, s = items.shape
    h = a.shape[0]

    # Mosaic has no unsigned vector min (arith.minui); bias by 2^31 and
    # min in the signed domain — order-isomorphic, bit-exact after unbias.
    bias = jnp.uint32(0x80000000)
    acc = jnp.full((bn, h), 0x7FFFFFFF, dtype=jnp.int32)  # biased UMAX
    for i in range(s):
        col = items[:, i:i + 1]  # static slice
        hashed = col * a[None, :] + b[None, :]
        acc = jnp.minimum(acc, jax.lax.bitcast_convert_type(
            hashed ^ bias, jnp.int32))
    sig = jax.lax.bitcast_convert_type(acc, jnp.uint32) ^ bias
    sig_ref[...] = sig

    r = h // n_bands
    salt = _FNV_OFFSET + jax.lax.broadcasted_iota(jnp.uint32, (bn, n_bands), 1)
    keys = salt
    for j in range(r):
        # Interleaved banding (minhash.band_keys): row j of every band is
        # the contiguous slice sig[:, j*B:(j+1)*B] — the one extract
        # shape Mosaic lowers (no strided/3-D vector casts needed).
        x = sig[:, j * n_bands:(j + 1) * n_bands]
        keys = (keys ^ x) * _FNV_PRIME
    keys_ref[...] = keys


@functools.partial(jax.jit, static_argnames=("n_bands", "block_n", "interpret"))
def minhash_and_keys_pallas(items, a, b, n_bands: int, block_n: int = 512,
                            interpret: bool = False):
    """[N, S] items -> ([N, H] signatures, [N, B] band keys), fused.

    N must be a multiple of block_n (pipeline pads and strips).
    """
    from jax.experimental import pallas as pl

    n, s = items.shape
    h = a.shape[0]
    assert n % block_n == 0, (n, block_n)
    grid = (n // block_n,)
    return pl.pallas_call(
        functools.partial(_kernel, n_bands=n_bands),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, s), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, h), lambda i: (i, 0)),
            pl.BlockSpec((block_n, n_bands), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), jnp.uint32),
            jax.ShapeDtypeStruct((n, n_bands), jnp.uint32),
        ],
        interpret=interpret,
    )(items.astype(jnp.uint32), a.astype(jnp.uint32), b.astype(jnp.uint32))


def minhash_and_keys(items, a, b, n_bands: int, *, use_pallas: str = "auto",
                     block_n: int = 512):
    """Dispatch: pallas on TPU (or forced), fused-jax elsewhere.

    use_pallas: 'auto' | 'never' | 'force' | 'interpret'.
    """
    if use_pallas == "auto":
        use_pallas = "force" if jax.default_backend() == "tpu" else "never"
    if use_pallas in ("force", "interpret"):
        n = items.shape[0]
        pad = (-n) % block_n
        if pad:
            items = jnp.concatenate(
                [jnp.asarray(items),
                 jnp.zeros((pad, items.shape[1]), dtype=jnp.uint32)], axis=0)
        sig, keys = minhash_and_keys_pallas(
            jnp.asarray(items), jnp.asarray(a), jnp.asarray(b), n_bands,
            block_n=block_n, interpret=(use_pallas == "interpret"))
        return sig[:n], keys[:n]
    sig = minhash_signatures(jnp.asarray(items), jnp.asarray(a), jnp.asarray(b))
    return sig, band_keys(sig, n_bands)


# ---------------------------------------------------------------------------
# VMEM-blocked C-MinHash (one-permutation) bin-min kernel.  The scheme's
# expensive pass is O(N*S): permute every element once and fold it into
# its bin's minimum — that is what runs here, one HBM->VMEM load per
# item block, as a one-hot compare against a broadcasted bin iota
# (Mosaic has no scatter).  The O(N*H) tail — densification rounds,
# circulant fallback, band fold — runs OUTSIDE the kernel as the SAME
# jitted jnp the reference path uses (minhash._cminhash_densify +
# band_keys): its donor gathers don't lower to anything Mosaic-shaped,
# it is bandwidth-trivial next to the bin-min pass, and sharing one
# implementation is half the bit-parity argument.  The sentinel algebra
# matches the reference exactly: a biased 0x7FFFFFFF is UMAX, so a
# never-touched bin and a bin holding a genuine UMAX element are
# indistinguishable in BOTH implementations.

def _cminhash_binmin_kernel(items_ref, c_ref, binmin_ref, rowmin_ref, *,
                            n_hashes: int):
    items = items_ref[...]          # [BN, S] uint32
    c = c_ref[...]                  # [2] uint32: (a0, b0)
    bn, s = items.shape
    h = n_hashes

    bias = jnp.uint32(0x80000000)
    u = items * c[0] + c[1]                        # the one permutation
    bins = (u % jnp.uint32(h)).astype(jnp.int32)
    ub = jax.lax.bitcast_convert_type(u ^ bias, jnp.int32)
    iota_h = jax.lax.broadcasted_iota(jnp.int32, (bn, h), 1)
    acc = jnp.full((bn, h), 0x7FFFFFFF, dtype=jnp.int32)
    for i in range(s):  # static unroll: one-hot segment min per column
        acc = jnp.minimum(acc, jnp.where(iota_h == bins[:, i:i + 1],
                                         ub[:, i:i + 1], 0x7FFFFFFF))
    binmin_ref[...] = jax.lax.bitcast_convert_type(acc, jnp.uint32) ^ bias
    rowmin_ref[...] = jax.lax.bitcast_convert_type(
        jnp.min(ub, axis=1, keepdims=True), jnp.uint32) ^ bias


@functools.partial(jax.jit,
                   static_argnames=("n_hashes", "block_n", "interpret"))
def _cminhash_binmin_pallas(items, consts, n_hashes: int, block_n: int,
                            interpret: bool):
    from jax.experimental import pallas as pl

    n, s = items.shape
    assert n % block_n == 0, (n, block_n)
    grid = (n // block_n,)
    return pl.pallas_call(
        functools.partial(_cminhash_binmin_kernel, n_hashes=n_hashes),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, s), lambda i: (i, 0)),
            pl.BlockSpec((2,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, n_hashes), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, n_hashes), jnp.uint32),
            jax.ShapeDtypeStruct((n, 1), jnp.uint32),
        ],
        interpret=interpret,
    )(items.astype(jnp.uint32), consts)


# One-shot breaker, same contract as the fused-unpack kernel below: the
# urem this kernel leans on is among the least-portable Mosaic ops, so a
# lowering rejection falls back to the bit-identical jax reference for
# the rest of the process instead of failing every chunk.
_CMINHASH_PALLAS_OK = True


def cminhash_and_keys(items, a0, b0, jmap, offs, n_bands: int, *,
                      use_pallas: str = "auto", block_n: int = 512):
    """[N, S] items -> ([N, H] signatures, [N, B] band keys) under the
    cminhash scheme.  Dispatch mirrors minhash_and_keys: pallas bin-min
    on TPU (or forced/interpret), jax reference elsewhere; pad rows are
    zeros and sliced off (the kernel is row-independent)."""
    global _CMINHASH_PALLAS_OK
    from .minhash import _cminhash_densify

    if use_pallas == "auto":
        use_pallas = "force" if jax.default_backend() == "tpu" else "never"
    a0 = jnp.asarray(a0, jnp.uint32).reshape(1)
    b0 = jnp.asarray(b0, jnp.uint32).reshape(1)
    jmap = jnp.asarray(jmap, jnp.int32)
    offs = jnp.asarray(offs, jnp.uint32)
    if use_pallas in ("force", "interpret") and _CMINHASH_PALLAS_OK:
        n = items.shape[0]
        padded = jnp.asarray(items)
        pad = (-n) % block_n
        if pad:
            padded = jnp.concatenate(
                [padded,
                 jnp.zeros((pad, items.shape[1]), dtype=jnp.uint32)], axis=0)
        try:
            binmin, rowmin = _cminhash_binmin_pallas(
                padded, jnp.concatenate([a0, b0]), int(offs.shape[0]),
                block_n, use_pallas == "interpret")
            sig = _cminhash_densify(binmin[:n], rowmin[:n, 0], jmap, offs)
            return sig, band_keys(sig, n_bands)
        except Exception as e:  # Mosaic lowering gap: unfuse, don't fail  # graftlint: disable=broad-except -- compiler rejections are arbitrary; fallback is bit-identical
            _CMINHASH_PALLAS_OK = False
            from ..utils.logging import get_logger

            get_logger("cluster.pallas").warning(
                "cminhash pallas kernel unavailable (%s: %s); falling "
                "back to the jax reference", type(e).__name__, e)
    sig = cminhash_signatures(jnp.asarray(items), a0, b0, jmap, offs)
    return sig, band_keys(sig, n_bands)


# ---------------------------------------------------------------------------
# Fused byte-unpack MinHash: consume the wire's byte-packed payload
# directly, so decoded uint32 items never round-trip HBM (the decode is a
# VMEM-resident combine in the same pass that hashes).  Offsets fold into
# the hash's additive constant — h(x + off) = x*a + (off*a + b) — so the
# signatures are bit-identical to decode-then-hash.

def _kernel_packed(items_ref, a_ref, b_ref, sig_ref, keys_ref, *,
                   n_bands: int, k: int):
    """items_ref: [BN, S*k] uint8, element j's little-endian bytes at
    columns [j*k, (j+1)*k).  Same static-unroll structure as _kernel."""
    items = items_ref[...]
    a = a_ref[...]
    b = b_ref[...]
    bn, sk = items.shape
    s = sk // k
    h = a.shape[0]

    bias = jnp.uint32(0x80000000)
    acc = jnp.full((bn, h), 0x7FFFFFFF, dtype=jnp.int32)
    for j in range(s):
        col = items[:, j * k:(j + 1) * k].astype(jnp.uint32)  # static slice
        x = col[:, 0:1]
        for t in range(1, k):
            x = x | (col[:, t:t + 1] << jnp.uint32(8 * t))
        hashed = x * a[None, :] + b[None, :]
        acc = jnp.minimum(acc, jax.lax.bitcast_convert_type(
            hashed ^ bias, jnp.int32))
    sig = jax.lax.bitcast_convert_type(acc, jnp.uint32) ^ bias
    sig_ref[...] = sig

    r = h // n_bands
    salt = _FNV_OFFSET + jax.lax.broadcasted_iota(jnp.uint32, (bn, n_bands), 1)
    keys = salt
    for j in range(r):
        x = sig[:, j * n_bands:(j + 1) * n_bands]
        keys = (keys ^ x) * _FNV_PRIME
    keys_ref[...] = keys


@functools.partial(jax.jit,
                   static_argnames=("k", "n_bands", "block_n", "interpret"))
def _minhash_packed_pallas(payload2d, a, b, k: int, n_bands: int,
                           block_n: int, interpret: bool):
    from jax.experimental import pallas as pl

    n, sk = payload2d.shape
    h = a.shape[0]
    assert n % block_n == 0, (n, block_n)
    grid = (n // block_n,)
    return pl.pallas_call(
        functools.partial(_kernel_packed, n_bands=n_bands, k=k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, sk), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, h), lambda i: (i, 0)),
            pl.BlockSpec((block_n, n_bands), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), jnp.uint32),
            jax.ShapeDtypeStruct((n, n_bands), jnp.uint32),
        ],
        interpret=interpret,
    )(payload2d, a.astype(jnp.uint32), b.astype(jnp.uint32))


@functools.partial(jax.jit, static_argnames=("shape", "k"))
def _combine_bytes(payload, shape: tuple, k: int, offset):
    """Fallback device decode for the byte-packed wire (jnp, off-pallas):
    [rows*S*k] uint8 -> [rows, S] uint32 (+ offset)."""
    rows, s = shape
    p = payload.reshape(rows, s, k).astype(jnp.uint32)
    x = p[..., 0]
    for t in range(1, k):
        x = x | (p[..., t] << jnp.uint32(8 * t))
    return x + jnp.asarray(offset, jnp.uint32)


# One-shot breaker: if Mosaic rejects the uint8 fused kernel on some TPU
# generation, fall back to decode-then-hash for the rest of the process
# instead of failing every chunk (the unfused path is bit-identical).
_FUSED_UNPACK_OK = True


def minhash_and_keys_packed(payload_d, shape: tuple, k: int, offset, a, b,
                            n_bands: int, *, use_pallas: str = "auto",
                            block_n: int = 512):
    """minhash_and_keys over a byte-packed wire chunk.

    payload_d: flat uint8 device array, `shape` = (rows, S) decoded shape,
    `k` = bytes per value, `offset` = per-chunk bias (folded into b).
    Signatures/keys are bit-identical to decoding first — the pipeline
    relies on this for cross-encoding label parity.
    """
    global _FUSED_UNPACK_OK
    rows, s = shape
    # Explicit conversion BEFORE any jit boundary: a raw np scalar would
    # be staged implicitly per chunk (lint/runtime.no_implicit_transfers).
    # graftlint: disable=wire-layer -- 4-byte offset scalar of the wire's own decode path (fused unpack kernel)
    offset = jax.device_put(np.uint32(offset))
    if use_pallas == "auto":
        use_pallas = "force" if jax.default_backend() == "tpu" else "never"
    if use_pallas in ("force", "interpret") and rows and _FUSED_UNPACK_OK:
        a = jnp.asarray(a).astype(jnp.uint32)
        b = jnp.asarray(b).astype(jnp.uint32)
        # Fold the offset bias into the additive hash constant.
        b_eff = b + jnp.asarray(offset, jnp.uint32) * a
        payload2d = payload_d.reshape(rows, s * k)
        pad = (-rows) % block_n
        if pad:
            payload2d = jnp.concatenate(
                [payload2d, jnp.zeros((pad, s * k), dtype=jnp.uint8)], axis=0)
        try:
            sig, keys = _minhash_packed_pallas(
                payload2d, a, b_eff, k, n_bands, block_n,
                use_pallas == "interpret")
            return sig[:rows], keys[:rows]
        except Exception as e:  # Mosaic lowering gap: unfuse, don't fail  # graftlint: disable=broad-except -- compiler rejections are arbitrary; fallback is bit-identical
            _FUSED_UNPACK_OK = False
            from ..utils.logging import get_logger

            get_logger("cluster.pallas").warning(
                "fused byte-unpack kernel unavailable (%s: %s); "
                "falling back to decode-then-hash", type(e).__name__, e)
    items = _combine_bytes(payload_d, (rows, s), k, offset)
    return minhash_and_keys(items, a, b, n_bands, use_pallas=use_pallas,
                            block_n=block_n)
