"""Banded-LSH bucket structure + verified min-label propagation.

No union-find exists on device (SURVEY.md §7.3); instead each LSH bucket
elects its minimum item index as *representative* (sort by band key +
segment-min — all static-shape ops), candidate edges (item -> rep) are
verified by estimated Jaccard (fraction of agreeing MinHash rows), and
cluster labels converge by pointer-jumping min-label propagation over the
accepted star edges.  Buckets act as hubs, so the effective graph diameter
is tiny and a fixed trip count of ~12 jumps covers 1M-item instances
(2^12 chain length) — data-independent control flow, jit-compatible.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def bucket_representatives(keys: jax.Array, orig: jax.Array | None = None,
                           lane_of: jax.Array | None = None) -> jax.Array:
    """[N, B] band keys -> [N, B] reps: min item index sharing the key.

    Per band: argsort the keys, mark run boundaries, segment-min the item
    indices within runs, scatter back.  Items in singleton buckets get
    themselves as rep (self-edges are dropped by the verifier's caller).

    ``orig``/``lane_of`` (both [N] int32, inverse permutations) make the
    election permutation-independent when rows arrive in an encoder's lane
    order (pipeline._cluster_encoded): the bucket hub is the member with
    the minimum ORIGINAL index (``orig``: row order -> original index),
    mapped back into row order via ``lane_of``.  Without them the row
    order is the original order and the two maps are identity.  This is
    what makes the delta-encoded path's labels bit-identical to the
    unencoded path's — buckets are order-invariant sets, so electing by
    original index yields the same hub, hence the same verified edges.
    """
    n = keys.shape[0]
    vals = jnp.arange(n, dtype=jnp.int32) if orig is None else orig
    return jax.vmap(lambda k: band_hub_election(k, vals, lane_of),
                    in_axes=1, out_axes=1)(keys.astype(jnp.uint32))


def band_hub_election(k: jax.Array, vals: jax.Array,
                      lane_of: jax.Array | None = None) -> jax.Array:
    """One band's hub election: [N] keys -> [N] rep row index.

    argsort the keys, mark run boundaries, segment-min ``vals`` (the
    election value — original indices) within runs, scatter back.  Shared
    by the single-device vmap above and the band-sharded kernel
    (cluster/sharded.py), which feeds one owned band at a time — keeping
    the two paths' elections one implementation, hence bit-identical.
    """
    n = k.shape[0]
    order = jnp.argsort(k)  # [N]
    ks = k[order]
    new_run = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), ks[1:] != ks[:-1]])
    seg = jnp.cumsum(new_run.astype(jnp.int32)) - 1  # [N] run ids
    run_min = jax.ops.segment_min(vals[order], seg, num_segments=n)
    rep_sorted = run_min[seg]  # min election value in my bucket
    if lane_of is not None:
        rep_sorted = lane_of[rep_sorted]
    return jnp.zeros((n,), jnp.int32).at[order].set(rep_sorted)


def estimated_jaccard(sig: jax.Array, reps: jax.Array) -> jax.Array:
    """[N, H] signatures, [N, B] rep indices -> [N, B] float32 estimated
    Jaccard = fraction of MinHash rows agreeing with the rep's row.

    Looped over the (small) band axis: a broadcast gather would materialise
    [N, B, H] — 8 GB at the 1M/16-band/128-hash operating point — while one
    band at a time peaks at O(N*H)."""
    n, h = sig.shape
    n_bands = reps.shape[1]

    def body(b, out):
        rep_rows = sig[reps[:, b]]  # [N, H]
        agree = (rep_rows == sig).sum(axis=-1).astype(jnp.float32)
        return out.at[:, b].set(agree / jnp.float32(h))

    return jax.lax.fori_loop(
        0, n_bands, body, jnp.zeros((n, n_bands), jnp.float32))


@partial(jax.jit, static_argnames=("n_iters", "axis_name"))
def propagate_labels(reps: jax.Array, valid: jax.Array,
                     n_iters: int = 64,
                     axis_name: str | None = None) -> jax.Array:
    """Min-label propagation over verified star edges, to convergence.

    reps: [N, B] rep item index per band; valid: [N, B] accepted edges.
    Returns [N] int32 labels = min item index reachable in each component.

    ``axis_name``: when the band axis is sharded over a mesh (each device
    holds B/d bands of the same N rows — cluster/sharded.py), labels stay
    replicated and each pull/push reduces across devices with `pmin`.
    Since min is associative/commutative, every iterate equals the
    single-device trajectory exactly: bit-identical labels, same trip
    count.

    Labels are monotonically non-increasing and bounded, and the fixpoint
    (the true component minima) is unique and schedule-independent — so the
    loop is a `while_loop` that stops one iteration after labels stabilise.
    The pull/push gathers over [N, B] dominate the whole cluster stage
    (~0.14 s each per iteration at N=1M on a v5-lite), and real data
    converges in ~4 iterations where a defensive fixed trip count burned 12;
    `n_iters` is now only a safety cap, and a convergence check (one
    compare+reduce, cheap next to the gathers) replaces the guesswork —
    faster in the common case AND correct on adversarially deep chains.
    Data-dependent trip count is fine under jit: `lax.while_loop` keeps
    shapes static, and under SPMD the `changed` reduction becomes a
    replicated collective.
    """
    n = reps.shape[0]
    self_idx = jnp.arange(n, dtype=jnp.int32)
    reps = jnp.where(valid, reps, self_idx[:, None])

    def step(labels):
        # pull: my label can drop to my reps' labels
        pulled = jnp.min(labels[reps], axis=1)
        if axis_name is not None:
            pulled = jax.lax.pmin(pulled, axis_name)
        labels = jnp.minimum(labels, pulled)
        # push: my reps' labels can drop to mine (scatter-min)
        pushed = labels.at[reps.reshape(-1)].min(
            jnp.broadcast_to(labels[:, None], reps.shape).reshape(-1))
        if axis_name is not None:
            pushed = jax.lax.pmin(pushed, axis_name)
        labels = jnp.minimum(labels, pushed)
        # pointer jumping: compress chains label -> label[label]
        return jnp.minimum(labels, labels[labels])

    def cond(carry):
        i, changed, _ = carry
        return changed & (i < n_iters)

    def body(carry):
        i, _, labels = carry
        new = step(labels)
        return i + 1, jnp.any(new != labels), new

    _, _, labels = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jnp.bool_(True), self_idx))
    return labels
