"""Banded-LSH bucket structure + verified min-label propagation.

No union-find exists on device (SURVEY.md §7.3); instead each LSH bucket
elects its minimum item index as *representative* (sort by band key +
segment-min — all static-shape ops), candidate edges (item -> rep) are
verified by estimated Jaccard (fraction of agreeing MinHash rows), and
cluster labels converge by pointer-jumping min-label propagation over the
accepted star edges.  Buckets act as hubs, so the effective graph diameter
is tiny and a fixed trip count of ~12 jumps covers 1M-item instances
(2^12 chain length) — data-independent control flow, jit-compatible.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def bucket_representatives(keys: jax.Array) -> jax.Array:
    """[N, B] band keys -> [N, B] reps: min item index sharing the key.

    Per band: argsort the keys, mark run boundaries, segment-min the item
    indices within runs, scatter back.  Items in singleton buckets get
    themselves as rep (self-edges are dropped by the verifier's caller).
    """
    n, n_bands = keys.shape

    def one_band(k):
        order = jnp.argsort(k)  # [N]
        ks = k[order]
        new_run = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), ks[1:] != ks[:-1]])
        seg = jnp.cumsum(new_run.astype(jnp.int32)) - 1  # [N] run ids
        run_min = jax.ops.segment_min(order.astype(jnp.int32), seg,
                                      num_segments=n)
        rep_sorted = run_min[seg]
        return jnp.zeros((n,), jnp.int32).at[order].set(rep_sorted)

    return jax.vmap(one_band, in_axes=1, out_axes=1)(keys.astype(jnp.uint32))


def estimated_jaccard(sig: jax.Array, reps: jax.Array) -> jax.Array:
    """[N, H] signatures, [N, B] rep indices -> [N, B] float32 estimated
    Jaccard = fraction of MinHash rows agreeing with the rep's row.

    Looped over the (small) band axis: a broadcast gather would materialise
    [N, B, H] — 8 GB at the 1M/16-band/128-hash operating point — while one
    band at a time peaks at O(N*H)."""
    n, h = sig.shape
    n_bands = reps.shape[1]

    def body(b, out):
        rep_rows = sig[reps[:, b]]  # [N, H]
        agree = (rep_rows == sig).sum(axis=-1).astype(jnp.float32)
        return out.at[:, b].set(agree / jnp.float32(h))

    return jax.lax.fori_loop(
        0, n_bands, body, jnp.zeros((n, n_bands), jnp.float32))


@partial(jax.jit, static_argnames=("n_iters",))
def propagate_labels(reps: jax.Array, valid: jax.Array,
                     n_iters: int = 12) -> jax.Array:
    """Min-label propagation over verified star edges.

    reps: [N, B] rep item index per band; valid: [N, B] accepted edges.
    Returns [N] int32 labels = min item index reachable in each component.
    """
    n = reps.shape[0]
    self_idx = jnp.arange(n, dtype=jnp.int32)
    reps = jnp.where(valid, reps, self_idx[:, None])
    labels = self_idx

    def body(_, labels):
        # pull: my label can drop to my reps' labels
        pulled = jnp.min(labels[reps], axis=1)
        labels = jnp.minimum(labels, pulled)
        # push: my reps' labels can drop to mine (scatter-min)
        labels = labels.at[reps.reshape(-1)].min(
            jnp.broadcast_to(labels[:, None], reps.shape).reshape(-1))
        # pointer jumping: compress chains label -> label[label]
        labels = jnp.minimum(labels, labels[labels])
        return labels

    return jax.lax.fori_loop(0, n_iters, body, labels)
