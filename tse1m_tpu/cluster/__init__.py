"""Million-session MinHash/LSH dedup + crash clustering (the north star).

The reference has no clustering layer — `BASELINE.json`'s north star adds it:
cluster ~1M session coverage vectors on a TPU mesh in < 60 s at ARI >= 0.98
vs the host baseline.  Pipeline (SURVEY.md §7.2 step 5):

  items [N, S] uint32 feature sets
    -> MinHash signatures [N, H]          (pallas kernel / jax fallback)
    -> banded LSH keys [N, B]             (mixing hash over H/B rows per band)
    -> bucket representatives per band    (sort + segment-min)
    -> signature-verified edges           (est. Jaccard >= threshold)
    -> min-label propagation              (pointer jumping, fixed trip count)
    -> cluster labels [N]
"""

from .metrics import adjusted_rand_index
from .minhash import band_keys, make_hash_params, minhash_signatures
from .host import host_cluster
from .pipeline import (ClusterParams, cluster_sessions,
                       cluster_sessions_pod, cluster_sessions_resumable)
from .schemes import SCHEMES, expand_weighted, make_params

__all__ = [
    "adjusted_rand_index",
    "band_keys",
    "make_hash_params",
    "minhash_signatures",
    "host_cluster",
    "ClusterParams",
    "cluster_sessions",
    "cluster_sessions_pod",
    "cluster_sessions_resumable",
    "SCHEMES",
    "expand_weighted",
    "make_params",
]
