"""Persistent content-addressed MinHash signature store (warm-path cache).

The paper's workload is *continuous* fuzzing: sessions accrete daily and
the overwhelming majority of each run's coverage vectors were already
seen the run before — yet the cluster pipeline re-encoded, re-shipped
and re-hashed every row from scratch (BENCH_r05: 10.9 s of a 15.2 s wall
was host->device wire at ~10 MB/s; compute was 1.9 s).  Signatures are
tiny, stable summaries worth persisting (the online/batch split argued
by b-bit minwise hashing, arXiv:1205.2958): a session's MinHash
signature depends only on its raw coverage-id set and the hash policy,
so it can be computed once and reused forever.

This module is the host-side store; `cluster/incremental.py` plans the
warm run and merges labels; `cluster/pipeline.py` owns every actual
device transfer (the blessed wire layer).

Layout (all writes tmp + ``os.replace`` — a SIGKILL mid-write leaves a
torn temp file that the next open sweeps, never a half-shard):

- ``store_manifest.json``: the policy key ``(n_hashes, seed,
  quant_bits, scheme)`` plus the committed shard list.  A store opened
  under a different policy REFUSES (mirrors ``cluster/checkpoint.py``'s
  ``wire_quant_bits`` handling) — signatures of a different hash family
  or quantized universe are wrong for this run, every one of them.  A
  manifest with no ``scheme`` key predates the kernel family and loads
  as ``kminhash`` (see ``normalize_policy``).
- ``sig_NNNNN.npy`` / ``key_NNNNN.npy``: append-only shards —
  ``[M, n_hashes] uint32`` signatures, mmap-loaded so a warm probe reads
  only the rows it gathers, and ``[M, 2] uint64`` content digests
  (`row_digests`) keying them.  A shard is visible only once the
  manifest lists it.
- ``state.json`` + ``state_NNNNN.npz``: the last completed run's LSH
  state (labels, per-band bucket tables, per-row shard locator, prefix
  digest) — what lets a warm accreted run merge labels instead of
  rebuilding band tables.  The json is the commit point.
- ``index_<fp>.keys.npy`` / ``index_<fp>.loc.npy``: the sorted probe
  index, materialized and mmap'd past ``TSE1M_SIG_STORE_IDX_ROWS`` rows
  so a billion-row store probes in O(log n) page touches instead of an
  in-RAM copy of every key (``<fp>`` fingerprints the shard list; stale
  generations are swept).

Self-healing (this is a store that lives for thousands of runs, and a
b-bit-packed signature byte carries maximal information — one flipped
bit silently poisons every future warm merge):

- Every committed shard is **CRC-framed**: the manifest entry carries a
  checksum of each file's exact bytes (CRC32C/Castagnoli when the
  ``crc32c`` wheel is present, else zlib's CRC-32 — same burst-error
  detection, recorded per store so verification always uses the algo
  that wrote it).  Frames are verified on open, before any mmap gather.
- A shard that fails its frame (bit rot, torn write, filesystem loss) is
  **quarantined** — moved to ``quarantine/``, dropped from the manifest,
  its digests probe as misses and recompute: exactly the torn-write
  semantics, extended to silent corruption.  Each quarantine fires a
  degradation event (observability plane -> run manifest / bench keys).
- The LSH state npz is framed the same way; a corrupt state degrades the
  next run to the union path over cached signatures (labels unchanged).
- ``scrub()`` (CLI: ``tse1m scrub``) walks a store, reports frame
  health, and with ``repair`` re-frames legacy shards, sweeps orphans
  and compacts.

Hygiene: ``compact()`` folds many small append shards into one large
shard (the state's locator is remapped exactly, so warm merges survive
compaction); eviction under ``max_bytes`` (``TSE1M_SIG_STORE_MAX_MB``)
is **LRU by probe recency** — every ``bulk_probe`` advances a
generation counter and stamps the shards it hit, and the coldest shard
goes first.  Content addressing keeps eviction safe: an evicted row
probes as a miss and recomputes; an LSH state whose locator references
an evicted shard reads as unusable and the next run rebuilds it.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os

import numpy as np

from ..observability import record_degradation
from ..observability.tracing import span
from ..resilience import fault_point, io_retry_policy, retry_call
from ..trace.hooks import shared_access, trace_point
from ..utils.atomic import atomic_write
from ..utils.logging import get_logger

log = get_logger("cluster.store")

_MANIFEST = "store_manifest.json"
_STATE = "state.json"
_QUARANTINE_DIR = "quarantine"
# Serving idempotency journal bound: retries arrive within one client
# retry window, so a small LRU of recent request ids suffices — the
# oldest entries age out with each append's manifest commit.
_JOURNAL_MAX = 128

# The policy tuple: any of these changing invalidates every stored
# signature (different hash family / universe), so it is THE manifest key.
# ``scheme`` (cluster/schemes.py) joined the tuple after stores already
# existed in the wild: a manifest WITHOUT the key is a kminhash store by
# definition (the only family that existed when it was written), so
# normalization defaults absent -> "kminhash" on load and every newly
# written manifest carries the key explicitly.
POLICY_KEYS = ("n_hashes", "seed", "quant_bits", "scheme")


def normalize_policy(policy: dict) -> dict:
    """Canonical policy dict: ints for the numeric keys, the scheme
    string validated against the registry, absent scheme -> kminhash
    (pre-scheme stores must OPEN, not refuse — the migration contract)."""
    from .schemes import get_scheme

    out = {k: int(policy[k]) for k in POLICY_KEYS
           if k != "scheme" and k in policy}
    out["scheme"] = get_scheme(str(policy.get("scheme", "kminhash")))
    return out

# Past this many index rows the probe index is materialized + mmap'd
# instead of held in RAM (the bounded-memory story past ~10M rows).
_IDX_MMAP_ROWS_DEFAULT = 4_000_000
# Auto-compaction threshold: at open, this many committed shards fold
# into one (continuous fuzzing appends a small shard per day; without
# compaction a year is ~365 shards and every probe walks all of them).
_COMPACT_SHARDS_DEFAULT = 64


# -- CRC framing -------------------------------------------------------------
#
# CRC32C (Castagnoli) when the hardware-accelerated wheel is available;
# zlib's CRC-32 otherwise (ubiquitous, C-speed, equal burst-detection
# power — only the polynomial differs).  The algo that framed a store is
# recorded in its manifest, so verification never mixes polynomials; a
# store opened under the other algo is transparently re-framed.

try:  # pragma: no cover - depends on the environment's wheels
    from crc32c import crc32c as _crc_update

    _CRC_ALGO = "crc32c"
except ImportError:  # pragma: no cover
    from zlib import crc32 as _crc_update

    _CRC_ALGO = "crc32"


def file_crc(path: str, chunk_bytes: int = 1 << 20) -> int:
    """Frame checksum of a file's exact bytes, streamed (bounded RSS —
    verification must not page a multi-GB shard into memory)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                return int(crc)
            crc = _crc_update(block, crc)


# -- content digests ---------------------------------------------------------
#
# 128-bit per-row content hash, fully vectorised: two independent
# multilinear hashes over the row's uint32 ids (mod 2^64, random odd
# per-column coefficients from a FIXED seed — digests must be stable
# across processes and machines), finalised with a splitmix64 mix.
# Pairwise collision probability is ~2^-66; a collision would silently
# reuse another row's signature, so 64 bits alone would be too thin for
# a store that lives for thousands of runs.

_DIGEST_SEED = 0x74736531  # "tse1"
_coef_cache: dict[int, np.ndarray] = {}


def _digest_coeffs(set_size: int) -> np.ndarray:
    c = _coef_cache.get(set_size)
    if c is None:
        rng = np.random.default_rng(_DIGEST_SEED)
        c = (rng.integers(1, 1 << 63, size=(2, set_size), dtype=np.uint64)
             * np.uint64(2) + np.uint64(1))
        _coef_cache[set_size] = c
    return c


def _mix64(x: np.ndarray) -> np.ndarray:
    x = x.copy()
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return x


def row_digests(items: np.ndarray) -> np.ndarray:
    """[N, S] uint32 rows -> [N, 2] uint64 content digests.

    Hashes the RAW (pre-quantization) ids: the store policy carries the
    quantization width, so the same raw row under the same policy always
    maps to the same cached signature.
    """
    items = np.ascontiguousarray(items, dtype=np.uint32)
    if items.ndim != 2:
        raise ValueError(f"expected [N, S] items, got shape {items.shape}")
    n, s = items.shape
    coef = _digest_coeffs(s)
    out = np.empty((n, 2), np.uint64)
    step = 1 << 17  # bound the [step, S] uint64 temporary to ~64 MB
    for lo in range(0, n, step):
        v = items[lo:lo + step].astype(np.uint64)
        for lane in range(2):
            acc = (v * coef[lane][None, :]).sum(axis=1, dtype=np.uint64)
            acc ^= np.uint64(s)  # rows of different widths never collide
            out[lo:lo + step, lane] = _mix64(acc)
    return out


_DIG_DT = np.dtype([("a", "<u8"), ("b", "<u8")])


class _ProbeIndex:
    """One immutable generation of the sorted probe index: mode
    ('ram'|'mmap'), struct-view keys, raw [N, 2] keys, and the (shard,
    row) locator columns.  Published as a single attribute so concurrent
    readers snapshot it with one reference read."""

    __slots__ = ("mode", "keys", "keys2d", "shard", "row")
    __immutable_after_publish__ = True

    def __init__(self, mode, keys, keys2d, shard, row):
        self.mode = mode
        self.keys = keys
        self.keys2d = keys2d
        self.shard = shard
        self.row = row


class _IndexSnapshot:
    """The store's WHOLE probe view — base index plus the LSM delta
    runs — as one immutable object behind one reference
    (``SignatureStore._snap``).  Base and deltas used to live in two
    attributes; ``_build_index`` cleared the delta list *before*
    publishing the consolidated base, so a `bulk_probe` racing a
    `refresh()` consolidation could read the old base with the already-
    emptied deltas and miss every delta-resident row — the torn probe
    index graftrace's store scenario catches (tests/test_trace.py
    plants the old two-phase publication and the explorer flags it).
    Now every layout change constructs a fresh snapshot and swaps the
    one reference; graftlint's ``snapshot-publish`` / ``atomic-swap``
    passes prove nothing mutates it after the swap."""

    __slots__ = ("base", "deltas")
    __immutable_after_publish__ = True

    def __init__(self, base: "_ProbeIndex", deltas: tuple = ()) -> None:
        self.base = base
        self.deltas = tuple(deltas)

    @property
    def n_rows(self) -> int:
        return int(self.base.keys.shape[0]) + sum(
            int(d.keys.shape[0]) for d in self.deltas)


def _as_struct(digests: np.ndarray) -> np.ndarray:
    """[N, 2] uint64 -> [N] structured view (lexicographically sortable
    and searchsorted-able as one 128-bit key)."""
    d = np.ascontiguousarray(digests, dtype="<u8")
    return d.view(_DIG_DT).reshape(-1)


def digests_fingerprint(digests: np.ndarray) -> str:
    """Order-sensitive fingerprint of a digest sequence — the state's
    accretion-prefix check (`LshState.prefix_digest`)."""
    return hashlib.blake2b(
        np.ascontiguousarray(digests, dtype="<u8").tobytes(),
        digest_size=16).hexdigest()


class SignatureStore:
    """Content-addressed (digest -> MinHash signature) store + the last
    run's LSH state, under one directory.  Single-writer; readers see
    only manifest-committed shards.

    ``read_only=True`` opens the store as a pure reader (the pod path's
    non-owned digest ranges): probes and gathers work, but nothing on
    disk is touched — no manifest rewrites, no orphan sweep, no
    quarantine moves, no auto-compaction — so a reader can never race
    the range's single writer.  A shard that fails its frame still reads
    as absent (in-memory drop + degradation event); the owner quarantines
    it for real on its next open."""

    # graftlint atomic-swap: the probe view may only be REBOUND whole
    # (one `_IndexSnapshot` per layout change), never mutated in place.
    __publish_slots__ = ("_snap",)

    def __init__(self, directory: str, policy: dict,
                 max_bytes: int | None = None,
                 read_only: bool = False) -> None:
        self.directory = directory
        self.read_only = bool(read_only)
        os.makedirs(directory, exist_ok=True)
        self.policy = normalize_policy(policy)
        if max_bytes is None:
            mb = os.environ.get("TSE1M_SIG_STORE_MAX_MB")
            max_bytes = int(float(mb) * 2**20) if mb else None
        self.max_bytes = max_bytes
        self._manifest_path = os.path.join(directory, _MANIFEST)
        self._state_path = os.path.join(directory, _STATE)
        self._mmaps: dict[int, np.ndarray] = {}
        self._key_mmaps: dict[int, np.ndarray] = {}
        # Shards quarantined while opening THIS instance (scrub reports).
        self.quarantined_at_open: list[dict] = []
        # Serving-plane idempotency journal: request id -> the original
        # ack fields, committed with the SAME manifest write as the
        # shard append it describes — a retried ingest whose first
        # attempt already committed replays its ack instead of
        # re-absorbing (durable-once semantics across a writer restart).
        self.serve_journal: dict[str, dict] = {}
        prior = self._load_json(self._manifest_path)
        # Pre-scheme manifest: normalization defaults it to kminhash; a
        # writable open heals the manifest once so every committed
        # manifest carries the key explicitly from here on.
        heal_scheme = (prior is not None and not self.read_only
                       and "scheme" not in prior.get("policy", {}))
        if prior is not None:
            prior_policy = normalize_policy(prior.get("policy", {}))
            if prior_policy != self.policy:
                diff = {k: (prior_policy.get(k), self.policy.get(k))
                        for k in set(prior_policy) | set(self.policy)
                        if prior_policy.get(k) != self.policy.get(k)}
                raise ValueError(
                    f"signature store at {directory} was built under a "
                    "different policy — its cached signatures are wrong "
                    "for this run, every one of them; use a fresh "
                    "directory or delete it. mismatched (have, want): "
                    f"{diff}")
            self.shards = [dict(s) for s in prior.get("shards", [])]
            self._probe_gen = int(prior.get("probe_gen", 0))
            self.generation = int(prior.get("generation", 0))
            self.serve_journal = {
                str(k): dict(v)
                for k, v in prior.get("serve_journal", {}).items()}
            if prior.get("crc_algo", _CRC_ALGO) != _CRC_ALGO:
                if self.read_only:
                    # Cannot re-frame another host's shards; skip frame
                    # verification (legacy-entry semantics) rather than
                    # quarantine every shard under the wrong polynomial.
                    for entry in self.shards:
                        entry.pop("sig_crc", None)
                        entry.pop("key_crc", None)
                else:
                    self._reframe_all()
        else:
            self.shards = []
            self._probe_gen = 0
            self.generation = 0
        self._committed_fp = self._index_fingerprint()
        if prior is None or heal_scheme:
            self._write_manifest()
        self._validate_shards()
        if not self.read_only:
            self._sweep_orphans()
            if len(self.shards) >= self._compact_threshold():
                self.compact()
        self._build_index()

    @classmethod
    def open_existing(cls, directory: str,
                      max_bytes: int | None = None) -> "SignatureStore":
        """Open a store using the policy recorded in ITS OWN manifest —
        the scrub/compaction entry point, which must not require the
        caller to know the hash policy."""
        path = os.path.join(directory, _MANIFEST)
        try:
            with open(path, encoding="utf-8") as f:
                policy = json.load(f)["policy"]
        except (OSError, ValueError, KeyError) as e:
            raise FileNotFoundError(
                f"{directory} has no readable signature-store manifest "
                f"({e})") from e
        return cls(directory, policy, max_bytes=max_bytes)

    def _require_writable(self, op: str) -> None:
        if self.read_only:
            raise RuntimeError(
                f"signature store at {self.directory} is open read-only "
                f"(a non-owned pod digest range); {op}() belongs to the "
                "range's single writer")

    @staticmethod
    def _compact_threshold() -> int:
        return int(os.environ.get("TSE1M_SIG_STORE_COMPACT_SHARDS",
                                  _COMPACT_SHARDS_DEFAULT))

    @staticmethod
    def _idx_mmap_rows() -> int:
        return int(os.environ.get("TSE1M_SIG_STORE_IDX_ROWS",
                                  _IDX_MMAP_ROWS_DEFAULT))

    # -- shard files --------------------------------------------------------

    def _sig_path(self, sid: int) -> str:
        return os.path.join(self.directory, f"sig_{sid:05d}.npy")

    def _key_path(self, sid: int) -> str:
        return os.path.join(self.directory, f"key_{sid:05d}.npy")

    def _load_json(self, path: str) -> dict | None:
        if not os.path.exists(path):
            return None
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            log.warning("unreadable %s (%s); treating as absent", path, e)
            return None

    def _write_manifest(self) -> None:
        if self.read_only:
            return  # readers never publish — the range owner's job
        # The store GENERATION advances exactly when the committed shard
        # layout changes (append / evict / compact / quarantine) — never
        # for LRU probe stamps — so a concurrent reader can answer "did
        # anything I mmap'd move?" with one integer compare (`refresh`).
        fp = self._index_fingerprint()
        if fp != self._committed_fp:
            self.generation += 1
            self._committed_fp = fp
        payload = {"policy": self.policy, "crc_algo": _CRC_ALGO,
                   "probe_gen": self._probe_gen,
                   "generation": self.generation,
                   "shards": self.shards}
        if self.serve_journal:
            # Only when non-empty, so batch-plane manifests stay
            # byte-identical to the pre-journal format.
            payload["serve_journal"] = self.serve_journal
        with atomic_write(self._manifest_path) as f:
            json.dump(payload, f)

    def _reframe_all(self) -> None:
        """Recompute every frame under the current CRC algo (a store
        moved between machines with/without the crc32c wheel)."""
        for entry in self.shards:
            sid = int(entry["id"])
            for key, path in (("sig_crc", self._sig_path(sid)),
                              ("key_crc", self._key_path(sid))):
                try:
                    entry[key] = file_crc(path)
                except OSError:
                    entry.pop(key, None)
        self._write_manifest()

    def _shard_ok(self, entry: dict) -> tuple[bool, str]:
        """(ok, reason).  A shard is good when both files exist, pass
        their CRC frames (a flipped byte ANYWHERE fails here), and
        mmap-load with the shapes the manifest promises.  Anything else
        must read as 'absent' so its rows recompute — never crash a warm
        run or feed it a silently-corrupt signature."""
        sid, rows = int(entry["id"]), int(entry["rows"])
        for crc_key, path in (("sig_crc", self._sig_path(sid)),
                              ("key_crc", self._key_path(sid))):
            want = entry.get(crc_key)
            if want is None:
                continue  # legacy unframed entry; `scrub --repair` frames it
            try:
                got = file_crc(path)
            except OSError as e:
                return False, f"unreadable ({e})"
            if int(got) != int(want):
                return False, (f"CRC frame mismatch on {os.path.basename(path)} "
                               f"(stored {want}, computed {got})")
        try:
            keys = np.load(self._key_path(sid), mmap_mode="r")
            sig = np.load(self._sig_path(sid), mmap_mode="r")
        except Exception as e:  # graftlint: disable=broad-except -- a torn shard must read as absent whatever the failure mode
            return False, f"unloadable ({e})"
        if not (keys.shape == (rows, 2) and keys.dtype == np.uint64
                and sig.shape == (rows, self.policy["n_hashes"])
                and sig.dtype == np.uint32):
            return False, "shape/dtype mismatch vs manifest"
        return True, ""

    def _quarantine_file(self, path: str) -> str | None:
        """Move a corrupt artifact into quarantine/ (never delete — the
        operator may want the evidence); returns the new path."""
        if self.read_only or not os.path.exists(path):
            return None
        qdir = os.path.join(self.directory, _QUARANTINE_DIR)
        os.makedirs(qdir, exist_ok=True)
        base = os.path.basename(path)
        dest = os.path.join(qdir, base)
        k = 0
        while os.path.exists(dest):
            k += 1
            dest = os.path.join(qdir, f"{base}.{k}")
        os.replace(path, dest)
        return dest

    def _quarantine_shard(self, entry: dict, reason: str) -> None:
        sid = int(entry["id"])
        log.warning("store shard %d quarantined: %s — its %d row(s) will "
                    "probe as misses and recompute", sid, reason,
                    int(entry["rows"]))
        self._quarantine_file(self._sig_path(sid))
        self._quarantine_file(self._key_path(sid))
        self._mmaps.pop(sid, None)
        self._key_mmaps.pop(sid, None)
        event = record_degradation(
            "shard_quarantine", site="store",
            detail={"shard": sid, "rows": int(entry["rows"]),
                    "reason": reason[:200]})
        self.quarantined_at_open.append(event["detail"])

    def _validate_shards(self) -> None:
        good = []
        for entry in self.shards:
            ok, reason = self._shard_ok(entry)
            if ok:
                good.append(entry)
            else:
                self._quarantine_shard(entry, reason)
        if len(good) != len(self.shards):
            self.shards = good
            self._write_manifest()

    def _sweep_orphans(self) -> None:
        """Remove shard/temp/index files the manifest does not own —
        leftovers of a crash between file write and manifest commit
        (append OR compaction).  Runs at open, so a SIGKILL mid-
        compaction can never strand temp shards across runs."""
        owned = {self._sig_path(int(s["id"])) for s in self.shards}
        owned |= {self._key_path(int(s["id"])) for s in self.shards}
        owned |= set(self._index_paths())
        for pat in ("sig_*.npy", "key_*.npy", "*.tmp.npy", "*.tmp.npz",
                    "state_*.npz", "index_*.npy"):
            for p in glob.glob(os.path.join(self.directory, pat)):
                if p in owned or p == self._current_state_file():
                    continue
                if ".tmp." in p or pat in ("sig_*.npy", "key_*.npy",
                                           "state_*.npz", "index_*.npy"):
                    with _suppress_oserror():
                        os.remove(p)

    def _current_state_file(self) -> str | None:
        st = self._load_json(self._state_path)
        if st and st.get("file"):
            return os.path.join(self.directory, st["file"])
        return None

    # -- probe index --------------------------------------------------------

    def _index_fingerprint(self, shards: list | None = None) -> str:
        layout = [(int(s["id"]), int(s["rows"]))
                  for s in (self.shards if shards is None else shards)]
        return hashlib.blake2b(json.dumps(layout).encode(),
                               digest_size=6).hexdigest()

    def _index_paths(self) -> tuple[str, str]:
        fp = self._index_fingerprint()
        return (os.path.join(self.directory, f"index_{fp}.keys.npy"),
                os.path.join(self.directory, f"index_{fp}.loc.npy"))

    def _gather_index_arrays(self):
        keys, shard_of, row_of = [], [], []
        for s in self.shards:
            sid, rows = int(s["id"]), int(s["rows"])
            keys.append(np.asarray(np.load(self._key_path(sid),
                                           mmap_mode="r")))
            shard_of.append(np.full(rows, sid, np.int32))
            row_of.append(np.arange(rows, dtype=np.int32))
        keys2d = np.concatenate(keys)
        order = np.argsort(_as_struct(keys2d), kind="stable")
        loc = np.stack([np.concatenate(shard_of)[order],
                        np.concatenate(row_of)[order]], axis=1)
        return keys2d[order], loc

    def _delta_index_for(self, sid: int, keys2d: np.ndarray) -> "_ProbeIndex":
        """Small sorted index over ONE newly committed shard — the LSM
        delta layer.  A full `_build_index` re-sorts every key in the
        store (O(n log n), GIL-held); a serving daemon appending a batch
        per second cannot afford that per append, so fresh shards get a
        per-shard delta probed after the base index, and the base is
        re-consolidated only when deltas pile up or the shard layout
        shrinks (evict/compact/quarantine)."""
        order = np.argsort(_as_struct(keys2d), kind="stable").astype(np.int32)
        sorted2d = np.ascontiguousarray(keys2d[order])
        return _ProbeIndex("ram", _as_struct(sorted2d), sorted2d,
                           np.full(order.shape[0], sid, np.int32), order)

    @staticmethod
    def _delta_max() -> int:
        return int(os.environ.get("TSE1M_SIG_STORE_DELTA_SHARDS", 48))

    def _push_delta(self, sid: int, keys2d: np.ndarray) -> None:
        snap = self._snap
        if len(snap.deltas) >= self._delta_max():
            self._build_index()
            return
        trace_point("store.index.delta")
        # One swap: readers see the old snapshot or (base, deltas+run),
        # never a half-extended view.
        shared_access(self, "_snap", write=True, atomic=True)
        self._snap = _IndexSnapshot(
            snap.base, snap.deltas + (self._delta_index_for(sid, keys2d),))

    def _build_index(self) -> None:
        """(Re)build the sorted probe index and publish it as ONE
        snapshot object (`self._snap`: base + delta runs together) —
        `bulk_probe` reads the snapshot reference once, so a concurrent
        `refresh()` swapping in a newer generation can never hand a
        probe keys from one generation and locators from another, and a
        consolidation can never expose a cleared delta list against the
        pre-consolidation base.  Consolidates: the delta layer empties."""
        total = sum(int(s["rows"]) for s in self.shards)
        if total == 0:
            base = _ProbeIndex("ram", np.empty(0, _DIG_DT),
                               np.empty((0, 2), np.uint64),
                               np.empty(0, np.int32),
                               np.empty(0, np.int32))
        elif total < self._idx_mmap_rows():
            keys2d, loc = self._gather_index_arrays()
            base = _ProbeIndex("ram", _as_struct(keys2d), keys2d,
                               np.ascontiguousarray(loc[:, 0]),
                               np.ascontiguousarray(loc[:, 1]))
        else:
            # Bounded-memory mode: materialize the sorted index once per
            # shard-list generation, then PROBE VIA MMAP — steady-state
            # RSS is O(touched pages), not O(total keys).  Hits are
            # re-verified against the CRC-framed key shards below
            # (`_verify_hits`), so a rotted index byte downgrades to a
            # miss, never a wrong gather.
            keys_path, loc_path = self._index_paths()
            if not (os.path.exists(keys_path)
                    and os.path.exists(loc_path)):
                keys2d, loc = self._gather_index_arrays()
                for path, arr in ((keys_path, keys2d), (loc_path, loc)):
                    tmp = path + ".tmp.npy"
                    np.save(tmp, arr)
                    os.replace(tmp, path)
                del keys2d, loc
            keys2d_mm = np.load(keys_path, mmap_mode="r")
            loc_mm = np.load(loc_path, mmap_mode="r")
            base = _ProbeIndex("mmap",
                               keys2d_mm.view(_DIG_DT).reshape(-1),
                               keys2d_mm, loc_mm[:, 0], loc_mm[:, 1])
        trace_point("store.index.publish")
        shared_access(self, "_snap", write=True, atomic=True)
        self._snap = _IndexSnapshot(base)

    @property
    def n_rows(self) -> int:
        return self._snap.n_rows

    @property
    def _idx(self) -> "_ProbeIndex":
        """Base index of the current snapshot (tests/diagnostics)."""
        return self._snap.base

    @property
    def _idx_delta(self) -> list:
        """Delta runs of the current snapshot (tests/diagnostics)."""
        return list(self._snap.deltas)

    @property
    def _idx_mode(self) -> str:
        return self._snap.base.mode

    def refresh(self) -> bool:
        """Adopt shard-list changes committed by this directory's single
        writer since this handle last looked — the concurrent-reader
        half of the serving plane's reader/writer discipline.  Cheap
        when nothing changed: one manifest read and an integer
        generation compare.  When the generation moved, the committed
        shard list is re-read, shards this handle already trusted keep
        their frames (files are immutable once committed), NEW shards
        are frame-verified before use, and the probe index is rebuilt
        and swapped in as one atomic snapshot — a probe running in
        another thread keeps its old consistent view.  Returns True when
        the view changed."""
        for attempt in range(3):
            try:
                return self._refresh_once()
            except OSError as e:
                # A cross-process writer evicted/compacted between our
                # manifest read and the shard loads (found by graftrace's
                # planted pre-fix adoption schedule): re-read the
                # manifest — it now reflects the removal — rather than
                # surfacing a missing committed file to the reader.
                if not self.read_only or attempt == 2:
                    raise
                log.warning("refresh: shard vanished mid-adoption (%s); "
                            "re-reading the manifest", e)
        return False  # pragma: no cover — loop always returns/raises

    def _refresh_once(self) -> bool:
        trace_point("store.refresh")
        meta = self._load_json(self._manifest_path)
        if meta is None:
            return False
        new_shards = [dict(s) for s in meta.get("shards", [])]
        gen = int(meta.get("generation", 0))
        if (gen == self.generation
                and self._index_fingerprint(new_shards)
                == self._index_fingerprint()):
            return False
        prior_policy = normalize_policy(meta.get("policy", self.policy))
        if prior_policy != self.policy:
            raise ValueError(
                f"signature store at {self.directory} changed policy "
                f"under this reader (have {prior_policy}, want "
                f"{self.policy})")
        known = self.shard_ids()
        good = []
        added = []
        for entry in new_shards:
            if int(entry["id"]) in known:
                good.append(entry)
                continue
            ok, reason = self._shard_ok(entry)
            if ok:
                good.append(entry)
                added.append(int(entry["id"]))
            else:
                # A reader never quarantines (that is the writer's job at
                # its next open); the bad shard just reads as absent.
                log.warning("refresh: new shard %s failed verification "
                            "(%s); treating as absent", entry.get("id"),
                            reason)
        removed = known - {int(e["id"]) for e in good}
        self.shards = good
        self.generation = gen
        self._committed_fp = self._index_fingerprint()
        live = self.shard_ids()
        for cache in (self._mmaps, self._key_mmaps):
            for sid in [s for s in cache if s not in live]:
                cache.pop(sid, None)
        if removed:
            self._build_index()  # evict/compact under us: consolidate
        else:
            # Append-only delta adoption: per-shard sorted indexes, no
            # O(total) re-sort — the serving reader refreshes once per
            # ingest generation and must stay cheap at millions of rows.
            # ALL adopted runs are built first and published in ONE
            # snapshot swap: pushing per shard exposed intermediate
            # views (e.g. the newest shard without its predecessor
            # after an eviction skip) that never existed as a committed
            # manifest generation — found by the graftrace store-evict
            # schedule explorer (tests/test_trace.py).
            snap = self._snap
            runs = tuple(
                self._delta_index_for(
                    sid, np.asarray(np.load(self._key_path(sid))))
                for sid in added)
            if len(snap.deltas) + len(runs) > self._delta_max():
                self._build_index()
            else:
                trace_point("store.index.delta")
                shared_access(self, "_snap", write=True, atomic=True)
                self._snap = _IndexSnapshot(snap.base,
                                            snap.deltas + runs)
        return True

    @property
    def sig_bytes(self) -> int:
        h = self.policy["n_hashes"]
        return sum(int(s["rows"]) * h * 4 for s in self.shards)

    def shard_ids(self) -> set:
        return {int(s["id"]) for s in self.shards}

    def _key_mmap(self, sid: int) -> np.ndarray:
        mm = self._key_mmaps.get(sid)
        if mm is None:
            mm = np.load(self._key_path(sid), mmap_mode="r")
            self._key_mmaps[sid] = mm
        return mm

    def _verify_hits(self, digests: np.ndarray, hit: np.ndarray,
                     shard: np.ndarray, row: np.ndarray) -> None:
        """Mmap-index hits re-checked against the authoritative (CRC-
        framed) key shards: a corrupt index locator must downgrade to a
        miss-and-recompute, never gather another row's signature."""
        idx = np.flatnonzero(hit)
        if idx.size == 0:
            return
        d = np.ascontiguousarray(digests, dtype="<u8")
        for sid in np.unique(shard[idx]):
            sel = idx[shard[idx] == sid]
            actual = np.asarray(self._key_mmap(int(sid))[row[sel]])
            bad = sel[~np.all(actual == d[sel], axis=1)]
            if bad.size:
                log.warning("store index: %d locator(s) failed key "
                            "verification; treating as misses", bad.size)
                hit[bad] = False
                shard[bad] = -1
                row[bad] = -1

    def _touch_probed(self, shard: np.ndarray, hit: np.ndarray) -> None:
        """Stamp the shards this probe actually hit with a fresh probe
        generation (the LRU recency signal; persisted with the next
        manifest write).  Read-only handles skip it (graftrace audit):
        their stamps could never reach the manifest, and concurrent
        query-thread probes mutating the shard entries under a racing
        ``refresh()`` was the reader plane's one unlocked shared write."""
        if self.read_only or not hit.any():
            return
        self._probe_gen += 1
        hot = set(int(s) for s in np.unique(shard[hit]))
        for entry in self.shards:
            if int(entry["id"]) in hot:
                entry["probe_gen"] = self._probe_gen

    def bulk_probe(self, digests: np.ndarray
                   ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """[N, 2] digests -> (hit [N] bool, shard [N] int32, row [N] int32).
        shard/row are -1 for misses."""
        n = digests.shape[0]
        shard = np.full(n, -1, np.int32)
        row = np.full(n, -1, np.int32)
        hit = np.zeros(n, bool)
        # ONE snapshot reference read; append/refresh/consolidation swap
        # `_snap` whole, so base and deltas can never be torn apart.
        shared_access(self, "_snap", write=False, atomic=True)
        snap = self._snap
        idx = snap.base
        deltas = snap.deltas
        if n == 0 or (idx.keys.shape[0] == 0 and not deltas):
            return hit, shard, row
        d2 = np.ascontiguousarray(digests, dtype="<u8")
        q = _as_struct(digests)
        if idx.keys.shape[0]:
            pos = np.searchsorted(idx.keys, q)
            inb = pos < idx.keys.shape[0]
            hit[inb] = np.all(
                np.asarray(idx.keys2d[pos[inb]]) == d2[inb], axis=1)
            shard[hit] = idx.shard[pos[hit]]
            row[hit] = idx.row[pos[hit]]
            if idx.mode == "mmap":
                self._verify_hits(digests, hit, shard, row)
        # LSM delta layer: shards appended since the last consolidation,
        # each with its own small sorted index (no overlap with the base
        # — consolidation empties the delta list).
        for dl in deltas:
            miss = np.flatnonzero(~hit)
            if miss.size == 0:
                break
            pos = np.searchsorted(dl.keys, q[miss])
            inb = pos < dl.keys.shape[0]
            sub = np.zeros(miss.size, bool)
            sub[inb] = np.all(dl.keys2d[pos[inb]] == d2[miss][inb], axis=1)
            sel = miss[sub]
            if sel.size:
                shard[sel] = dl.shard[pos[sub]]
                row[sel] = dl.row[pos[sub]]
                hit[sel] = True
        self._touch_probed(shard, hit)
        return hit, shard, row

    def _sig_mmap(self, sid: int) -> np.ndarray:
        mm = self._mmaps.get(sid)
        if mm is None:
            mm = np.load(self._sig_path(sid), mmap_mode="r")
            self._mmaps[sid] = mm
        return mm

    def load_signatures(self, shard: np.ndarray,
                        row: np.ndarray) -> np.ndarray:
        """Gather [K, n_hashes] uint32 signatures by (shard, row) pairs.
        Rows are gathered per shard in sorted order so the mmap reads
        pages sequentially."""
        k = int(shard.shape[0])
        out = np.empty((k, self.policy["n_hashes"]), np.uint32)
        for sid in np.unique(shard):
            sel = np.flatnonzero(shard == sid)
            rows = row[sel]
            order = np.argsort(rows, kind="stable")
            out[sel[order]] = self._sig_mmap(int(sid))[rows[order]]
        return out

    def load_digests(self, shard: np.ndarray, row: np.ndarray) -> np.ndarray:
        """Gather [K, 2] uint64 digests by (shard, row) pairs — the key
        files are the authoritative row identity, so the serve ``topk``
        verb answers in digests, not store rows.  Same per-shard sorted
        gather as `load_signatures` so the mmap reads pages
        sequentially."""
        k = int(shard.shape[0])
        out = np.empty((k, 2), np.uint64)
        for sid in np.unique(shard):
            sel = np.flatnonzero(shard == sid)
            rows = row[sel]
            order = np.argsort(rows, kind="stable")
            out[sel[order]] = self._key_mmap(int(sid))[rows[order]]
        return out

    # -- append -------------------------------------------------------------

    def journal_record(self, request_id: str, entry: dict) -> None:
        """Stage one serving ack under ``request_id`` so the NEXT
        manifest write (normally the append commit the ack describes)
        makes it durable atomically with the rows themselves.  Bounded:
        the oldest entries age out past ``_JOURNAL_MAX``."""
        self._require_writable("journal_record")
        self.serve_journal[str(request_id)] = dict(entry)
        while len(self.serve_journal) > _JOURNAL_MAX:
            self.serve_journal.pop(next(iter(self.serve_journal)))

    def append(self, digests: np.ndarray, sigs: np.ndarray) -> int:
        """Append (digest, signature) rows not already stored; returns the
        number of rows actually written.  Duplicate digests within the
        batch keep their first occurrence.  The shard write is atomic,
        CRC-framed, and runs under the shared retry engine (a torn write
        — or an injected one — rewrites the temp files from scratch)."""
        self._require_writable("append")
        trace_point("store.append")
        if digests.shape[0] == 0:
            return 0
        hit, _, _ = self.bulk_probe(digests)
        fresh = np.flatnonzero(~hit)
        if fresh.size == 0:
            return 0
        d = np.ascontiguousarray(digests[fresh], dtype=np.uint64)
        s = np.ascontiguousarray(sigs[fresh], dtype=np.uint32)
        _, first = np.unique(_as_struct(d), return_index=True)
        first.sort()
        d, s = d[first], s[first]
        with span("store.append", rows=int(d.shape[0])):
            sid = 1 + max((int(e["id"]) for e in self.shards), default=-1)
            sig_path, key_path = self._sig_path(sid), self._key_path(sid)
            sig_tmp, key_tmp = sig_path + ".tmp.npy", key_path + ".tmp.npy"
            crcs = {}

            def write_shard() -> None:
                np.save(sig_tmp, s)
                np.save(key_tmp, d)
                # Frame BEFORE the rename: the checksum covers the bytes
                # the commit publishes, and a torn/injected failure
                # re-frames.
                crcs["sig"] = file_crc(sig_tmp)
                crcs["key"] = file_crc(key_tmp)
                fault_point("store.sig.save", path=sig_tmp)
                os.replace(sig_tmp, sig_path)
                os.replace(key_tmp, key_path)

            retry_call(write_shard, policy=io_retry_policy(),
                       site="store.sig.save")
            self.shards.append({"id": sid, "rows": int(d.shape[0]),
                                "sig_crc": crcs["sig"],
                                "key_crc": crcs["key"],
                                "probe_gen": self._probe_gen})
            self._write_manifest()
            n_before = len(self.shards)
            self._evict(keep_sid=sid)
            if len(self.shards) != n_before:
                self._build_index()  # layout shrank: consolidate
            else:
                self._push_delta(sid, d)
            return int(d.shape[0])

    def _evict(self, keep_sid: int) -> None:
        """LRU whole-shard eviction down to ``max_bytes`` (never the
        shard just written): the shard with the OLDEST probe generation
        goes first — a shard no warm run has gathered from in ages is
        the cheapest recompute.  Safe by construction: evicted rows
        probe as misses and recompute; a stale LSH-state locator is
        detected at load (`load_state`)."""
        if not self.max_bytes:
            return
        while self.sig_bytes > self.max_bytes and len(self.shards) > 1:
            candidates = [e for e in self.shards
                          if int(e["id"]) != keep_sid]
            if not candidates:
                break
            victim = min(candidates,
                         key=lambda e: (int(e.get("probe_gen", 0)),
                                        int(e["id"])))
            trace_point("store.evict")
            self.shards.remove(victim)
            self._write_manifest()
            self._mmaps.pop(int(victim["id"]), None)
            self._key_mmaps.pop(int(victim["id"]), None)
            log.info("store eviction (LRU): dropped shard %d (%d rows, "
                     "probe_gen %d)", victim["id"], victim["rows"],
                     victim.get("probe_gen", 0))
            record_degradation("shard_evicted", site="store",
                               detail={"shard": int(victim["id"]),
                                       "rows": int(victim["rows"])})
            for p in (self._sig_path(int(victim["id"])),
                      self._key_path(int(victim["id"]))):
                with _suppress_oserror():
                    os.remove(p)

    # -- compaction ---------------------------------------------------------

    def compact(self, min_shards: int = 2) -> int:
        """Fold every committed shard into ONE large shard (many small
        daily appends -> one sequential-gather file).  Exact: the LSH
        state's per-row locator is remapped through the concatenation
        offsets, so a warm merge right after compaction behaves exactly
        as before it.  Returns the number of shards folded (0 = nothing
        to do).  Crash-safe: the new shard commits via the manifest like
        any append; a SIGKILL mid-write leaves temps the next open
        sweeps and the old shards untouched."""
        self._require_writable("compact")
        trace_point("store.compact")
        if len(self.shards) < max(2, min_shards):
            return 0
        old = list(self.shards)
        keys = np.concatenate([np.load(self._key_path(int(e["id"])))
                               for e in old])
        sigs = np.concatenate([np.load(self._sig_path(int(e["id"])))
                               for e in old])
        offsets = {}
        base = 0
        for e in old:
            offsets[int(e["id"])] = base
            base += int(e["rows"])
        sid = 1 + max(int(e["id"]) for e in old)
        sig_path, key_path = self._sig_path(sid), self._key_path(sid)
        sig_tmp, key_tmp = sig_path + ".tmp.npy", key_path + ".tmp.npy"
        crcs = {}

        def write_compacted() -> None:
            np.save(sig_tmp, sigs)
            np.save(key_tmp, keys)
            crcs["sig"] = file_crc(sig_tmp)
            crcs["key"] = file_crc(key_tmp)
            fault_point("store.compact.save", path=sig_tmp)
            os.replace(sig_tmp, sig_path)
            os.replace(key_tmp, key_path)

        retry_call(write_compacted, policy=io_retry_policy(),
                   site="store.compact.save")
        self.shards = [{"id": sid, "rows": int(keys.shape[0]),
                        "sig_crc": crcs["sig"], "key_crc": crcs["key"],
                        "probe_gen": max(int(e.get("probe_gen", 0))
                                         for e in old)}]
        self._write_manifest()  # the commit point: old shards now orphans
        self._remap_state(offsets, sid)
        self._mmaps.clear()
        self._key_mmaps.clear()
        for e in old:
            for p in (self._sig_path(int(e["id"])),
                      self._key_path(int(e["id"]))):
                with _suppress_oserror():
                    os.remove(p)
        self._sweep_orphans()
        self._build_index()
        log.info("store compaction: %d shards -> 1 (%d rows)", len(old),
                 int(keys.shape[0]))
        return len(old)

    def _remap_state(self, offsets: dict, new_sid: int) -> None:
        """Rewrite the LSH state's (shard, row) locator through the
        compaction offsets.  A state that cannot be remapped (torn,
        references an already-evicted shard) is dropped — the next run
        falls back to the union path, labels unchanged."""
        meta = self._load_json(self._state_path)
        if meta is None:
            return
        path = os.path.join(self.directory, str(meta.get("file")))
        try:
            with np.load(path) as z:
                payload = {k: z[k].copy() for k in z.files}
        except Exception as e:  # graftlint: disable=broad-except -- a torn state must drop to the union fallback whatever the failure mode
            log.warning("LSH state unreadable during compaction (%s); "
                        "dropping it", e)
            with _suppress_oserror():
                os.remove(self._state_path)
            return
        locator = payload.get("locator")
        if locator is None or (locator.size and not all(
                int(s) in offsets for s in np.unique(locator[:, 0]))):
            log.warning("LSH state references shard(s) outside this "
                        "compaction; dropping it")
            with _suppress_oserror():
                os.remove(self._state_path)
            return
        if locator.size:
            off = np.array([offsets[int(s)] for s in locator[:, 0]],
                           np.int64)
            payload["locator"] = np.stack(
                [np.full(locator.shape[0], new_sid, np.int32),
                 (locator[:, 1].astype(np.int64) + off).astype(np.int32)],
                axis=1)
        gen = int(meta.get("gen", 0)) + 1
        fname = f"state_{gen:05d}.npz"
        new_path = os.path.join(self.directory, fname)
        tmp = new_path + ".tmp.npz"

        def write_state() -> None:
            np.savez(tmp, **payload)
            fault_point("store.state.save", path=tmp)
            os.replace(tmp, new_path)

        retry_call(write_state, policy=io_retry_policy(),
                   site="store.state.save")
        meta.update(file=fname, gen=gen, crc=file_crc(new_path))
        with atomic_write(self._state_path) as f:
            json.dump(meta, f)
        old = path
        if old != new_path:
            with _suppress_oserror():
                os.remove(old)

    # -- scrub --------------------------------------------------------------

    def scrub(self, repair: bool = False, compact: bool = False) -> dict:
        """Walk the store and report frame health (``store_scrub_*`` —
        the bench/CI key namespace).  ``repair`` re-frames legacy
        (pre-CRC) shards and sweeps orphans; ``compact`` additionally
        folds the shards.  Corruption found here (or at open) is already
        quarantined — scrub makes it visible and countable."""
        corrupt = list(self.quarantined_at_open)
        missing_crc = 0
        for entry in list(self.shards):
            ok, reason = self._shard_ok(entry)
            if not ok:
                self._quarantine_shard(entry, reason)
                self.shards.remove(entry)
                corrupt.append({"shard": int(entry["id"]),
                                "reason": reason})
                self._write_manifest()
                continue
            if entry.get("sig_crc") is None or entry.get("key_crc") is None:
                missing_crc += 1
                if repair:
                    sid = int(entry["id"])
                    entry["sig_crc"] = file_crc(self._sig_path(sid))
                    entry["key_crc"] = file_crc(self._key_path(sid))
                    self._write_manifest()
                    missing_crc -= 1
        state_ok = self._state_frame_ok()
        compacted = self.compact() if compact else 0
        if repair or compacted:
            self._sweep_orphans()
            self._build_index()
        qdir = os.path.join(self.directory, _QUARANTINE_DIR)
        quarantined = (len(os.listdir(qdir)) if os.path.isdir(qdir) else 0)
        return {
            "store_scrub_shards": len(self.shards),
            "store_scrub_rows": self.n_rows,
            "store_scrub_mb": round(self.sig_bytes / 2**20, 3),
            "store_scrub_corrupt": len(corrupt),
            "store_scrub_quarantined": quarantined,
            "store_scrub_missing_crc": missing_crc,
            "store_scrub_state_ok": bool(state_ok),
            "store_scrub_compacted": compacted,
            "store_scrub_repaired": bool(repair),
        }

    def verify_signatures(self, items: np.ndarray, sample: int = 256,
                          seed: int = 0) -> dict:
        """Sampled end-to-end recompute of stored signatures from raw
        rows (``scrub --verify-sigs``): the CRC frame only proves the
        bytes have not changed SINCE framing — corruption that happened
        before the frame was written (a flipped bit on the wire to disk,
        a bad append batch) is inherited as "correct" forever.  This
        closes that hole: digest ``items``, probe, draw a seeded sample
        of the hits, recompute their MinHash signatures on host from the
        raw ids (quantized per the store policy, so the oracle sees the
        same universe the device did) and compare elementwise.  A shard
        holding any mismatching row is quarantined — its rows probe as
        misses and recompute, the same semantics torn/corrupt shards get.
        Recompute dispatches through the scheme registry on the store's
        OWN policy scheme (a cminhash store verifies against the
        cminhash host kernel; a weighted store's caller feeds the same
        replica-expanded rows it ingests), so the check stays honest for
        every member of the kernel family.  Returns the
        ``store_scrub_verify_*`` report keys."""
        from .encode import quantize_ids
        from .schemes import make_params, scheme_host_signatures

        items = np.ascontiguousarray(items, dtype=np.uint32)
        digests = row_digests(items)
        hit, shard, row = self.bulk_probe(digests)
        idx = np.flatnonzero(hit)
        if idx.size > sample > 0:
            rng = np.random.default_rng(seed)
            idx = np.sort(rng.choice(idx, size=sample, replace=False))
        report = {"store_scrub_verify_sampled": int(idx.size),
                  "store_scrub_verify_mismatch": 0,
                  "store_scrub_verify_quarantined": 0,
                  "store_scrub_verify_ok": True}
        if idx.size == 0:
            return report
        stored = self.load_signatures(shard[idx], row[idx])
        rows = items[idx]
        qb = self.policy["quant_bits"]
        if qb:
            rows = quantize_ids(rows, qb)
        hp = make_params(self.policy["scheme"], self.policy["n_hashes"],
                         self.policy["seed"])
        want = scheme_host_signatures(rows, hp)
        bad = ~np.all(stored == want, axis=1)
        if not bad.any():
            return report
        bad_sids = {int(s) for s in np.unique(shard[idx][bad])}
        for entry in list(self.shards):
            if int(entry["id"]) in bad_sids:
                self._quarantine_shard(
                    entry, "sampled signature recompute mismatch "
                           "(pre-framing corruption)")
                self.shards.remove(entry)
        self._write_manifest()
        self._build_index()
        report.update(store_scrub_verify_mismatch=int(bad.sum()),
                      store_scrub_verify_quarantined=len(bad_sids),
                      store_scrub_verify_ok=False)
        return report

    def _state_frame_ok(self) -> bool:
        meta = self._load_json(self._state_path)
        if meta is None:
            return True  # no state is a valid (cold) store
        path = os.path.join(self.directory, str(meta.get("file")))
        if not os.path.exists(path):
            return False
        want = meta.get("crc")
        if want is None:
            return True  # legacy unframed state
        try:
            return int(file_crc(path)) == int(want)
        except OSError:
            return False

    # -- LSH run state ------------------------------------------------------

    def save_state(self, labels: np.ndarray, locator: np.ndarray,
                   tables: tuple[list, list], digests: np.ndarray,
                   n_bands: int, threshold: float) -> bool:
        """Commit the completed run's LSH state (atomically: npz first,
        then the json pointer carrying the npz's CRC frame).  Returns
        False — state intentionally not saved — when any row's signature
        is not locatable in the store (eviction raced the run); a warm
        merge must never gather from a shard that is gone."""
        self._require_writable("save_state")
        if locator.size and int(locator.min()) < 0:
            log.warning("not saving LSH state: %d row(s) have no stored "
                        "signature (store eviction?)",
                        int((locator[:, 0] < 0).sum()))
            return False
        prior = self._load_json(self._state_path) or {}
        gen = int(prior.get("gen", 0)) + 1
        fname = f"state_{gen:05d}.npz"
        path = os.path.join(self.directory, fname)
        tmp = path + ".tmp.npz"
        band_keys, band_reps = tables
        payload = {"labels": np.ascontiguousarray(labels, np.int32),
                   "locator": np.ascontiguousarray(locator, np.int32)}
        for b, (k, r) in enumerate(zip(band_keys, band_reps)):
            payload[f"bk_{b:03d}"] = np.ascontiguousarray(k, np.uint32)
            payload[f"br_{b:03d}"] = np.ascontiguousarray(r, np.int32)

        def write_state() -> None:
            np.savez(tmp, **payload)
            fault_point("store.state.save", path=tmp)
            os.replace(tmp, path)

        retry_call(write_state, policy=io_retry_policy(),
                   site="store.state.save")
        with atomic_write(self._state_path) as f:
            json.dump({"file": fname, "gen": gen,
                       "crc": file_crc(path),
                       "n_rows": int(labels.shape[0]),
                       "n_bands": int(n_bands),
                       "threshold": float(threshold),
                       "prefix_digest": digests_fingerprint(digests)}, f)
        # The probe generations stamped during this run ride along with
        # the state commit (the manifest is the LRU ledger).
        self._write_manifest()
        old = prior.get("file")
        if old and old != fname:
            with _suppress_oserror():
                os.remove(os.path.join(self.directory, old))
        return True

    def load_state(self, n_bands: int, threshold: float):
        """The last run's LSH state, or None when absent, torn, CRC-
        corrupt, built under different banding/threshold, or referencing
        evicted shards.  Unlike a sig-policy mismatch this does not
        refuse the run — the signatures are still valid; only the
        label-merge shortcut is.  A corrupt state npz is quarantined so
        the union fallback recomputes from verified signatures."""
        from .incremental import LshState

        meta = self._load_json(self._state_path)
        if meta is None:
            return None
        if (int(meta.get("n_bands", -1)) != int(n_bands)
                or float(meta.get("threshold", -1.0)) != float(threshold)):
            log.warning("LSH state at %s was built under different "
                        "banding/threshold; rebuilding", self.directory)
            return None
        path = os.path.join(self.directory, str(meta.get("file")))
        want_crc = meta.get("crc")
        if want_crc is not None and os.path.exists(path):
            try:
                got = file_crc(path)
            except OSError:
                got = None
            if got is None or int(got) != int(want_crc):
                log.warning("LSH state CRC frame mismatch; quarantining "
                            "and rebuilding via the union path")
                self._quarantine_file(path)
                with _suppress_oserror():
                    os.remove(self._state_path)
                record_degradation("state_quarantine", site="store",
                                   detail={"file": os.path.basename(path)})
                return None
        try:
            with np.load(path) as z:
                labels = z["labels"]
                locator = z["locator"]
                band_keys = [z[f"bk_{b:03d}"] for b in range(n_bands)]
                band_reps = [z[f"br_{b:03d}"] for b in range(n_bands)]
        except Exception as e:  # graftlint: disable=broad-except -- a torn state file must read as absent whatever the failure mode
            log.warning("LSH state unreadable (%s); rebuilding", e)
            return None
        if labels.shape[0] != int(meta["n_rows"]):
            return None
        if locator.size and not (set(np.unique(locator[:, 0]).tolist())
                                 <= self.shard_ids()):
            log.warning("LSH state references evicted shard(s); rebuilding")
            return None
        return LshState(n_rows=int(meta["n_rows"]),
                        labels=labels, locator=locator,
                        band_keys_sorted=band_keys, band_reps=band_reps,
                        prefix_digest=str(meta["prefix_digest"]))


class _suppress_oserror:
    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        return et is not None and issubclass(et, OSError)


# -- pod-scale sharding ------------------------------------------------------
#
# One process per host, one digest range per process: the 128-bit content
# digest space is split into ``n_ranges`` contiguous ranges by the top 32
# bits of lane ``a`` (uniform under the multilinear hash), and each range
# is a complete SignatureStore under ``range_NNNN/`` of the shared root.
# A range has exactly ONE writer — the owning process appends its novel
# rows and stamps its manifests — while every process may open every
# range read-only for the warm probe/gather, so the pod probe is complete
# without any cross-host signature traffic.  Ownership is a pure function
# of (range id, live process count): range r belongs to process
# ``r % n_processes``, so a pod resumed with fewer hosts deterministically
# reassigns the lost hosts' ranges to survivors (each reassignment fires a
# ``shard_range_reassigned`` degradation event) and rows whose appends died
# with their host simply probe as misses and recompute — the exact
# semantics torn/corrupt shards already have.

_TOPOLOGY = "pod_topology.json"


def digest_range_ids(digests: np.ndarray, n_ranges: int) -> np.ndarray:
    """[N, 2] uint64 digests -> [N] int32 owning range (contiguous split
    of the top 32 bits of lane a — stable across processes/machines)."""
    hi = np.ascontiguousarray(digests, dtype="<u8")[:, 0] >> np.uint64(32)
    return ((hi * np.uint64(n_ranges)) >> np.uint64(32)).astype(np.int32)


class ShardedSignatureStore:
    """Per-host digest-range sharded signature store (pod warm path).

    ``root`` holds ``pod_topology.json`` (range count + policy — the
    commit point, written once at creation) and one ``range_NNNN/``
    SignatureStore per range.  Ownership comes from the pod's
    ``membership`` record (resilience/coordinator.MembershipLedger —
    epoch, member set, range → owner deal) when one is passed; without
    one (legacy/scrub opens) it falls back to the pure modulo deal
    ``{r : r % n_processes == process_id}``.  All other ranges open
    read-only on first touch.  ``reassigned_ranges`` lists owned ranges
    that changed writer at this epoch (a lost host's range this process
    inherited, or a range handed back to a re-admitted host).

    With a membership record the store is **lease-fenced**: at open it
    acquires the current-epoch lease (coordinator.acquire_lease) for
    every owned range, and every :meth:`append` re-verifies tenure
    first.  A writer whose lease was superseded — a zombie that woke
    after its range was re-dealt — demotes itself to read-only and
    raises :class:`~..resilience.coordinator.LeaseSupersededError`
    (recorded as a ``lease_superseded`` degradation event) instead of
    double-writing."""

    def __init__(self, root: str, policy: dict, n_processes: int = 1,
                 process_id: int = 0, n_ranges: int | None = None,
                 max_bytes: int | None = None,
                 membership: dict | None = None) -> None:
        if os.path.exists(os.path.join(root, _MANIFEST)):
            raise ValueError(
                f"signature store at {root} is a single-host store "
                "(store_manifest.json present); a pod run needs a sharded "
                "root — point --sig-store at a fresh directory")
        self.root = root
        self.policy = normalize_policy(policy)
        self.process_id = int(process_id)
        self.n_processes = max(1, int(n_processes))
        self.max_bytes = max_bytes
        os.makedirs(root, exist_ok=True)
        topo_path = os.path.join(root, _TOPOLOGY)
        topo = None
        if os.path.exists(topo_path):
            try:
                with open(topo_path, encoding="utf-8") as f:
                    topo = json.load(f)
            except (OSError, ValueError) as e:
                log.warning("unreadable %s (%s); rewriting", topo_path, e)
        if topo is None:
            topo = {"n_ranges": int(n_ranges or self.n_processes),
                    "policy": self.policy}
            try:
                with atomic_write(topo_path) as f:
                    json.dump(topo, f)
            except OSError:
                # Every pod process races to commit the (identical)
                # topology at first open; atomic_write's fixed tmp name
                # means the loser's rename can fail — the winner's file
                # is the commit, re-read it.
                with open(topo_path, encoding="utf-8") as f:
                    topo = json.load(f)
        if normalize_policy(topo.get("policy") or {}) != self.policy:
            raise ValueError(
                f"sharded signature store at {root} was built under a "
                f"different policy (have {topo.get('policy')}, want "
                f"{self.policy}); use a fresh directory or delete it")
        self.n_ranges = int(topo["n_ranges"])
        self.epoch: int | None = None
        self.lease_nonce: str | None = None
        self.fenced = False
        if membership is not None:
            # Epoch-lease plane: ownership is the ledger's elastic deal,
            # and every owned range's current-epoch lease is taken now —
            # a process opening after its ranges were re-dealt fences
            # HERE, before it can write a byte.
            from ..resilience.coordinator import acquire_lease

            self.epoch = int(membership["epoch"])
            self.lease_nonce = str(membership.get("nonce", ""))
            owners = {int(k): int(v)
                      for k, v in membership["owners"].items()}
            self.owned = [r for r in range(self.n_ranges)
                          if owners.get(r) == self.process_id]
            moved = {int(r) for r in membership.get("moved", [])}
            self.reassigned_ranges = [r for r in self.owned if r in moved]
            for r in self.reassigned_ranges:
                record_degradation(
                    "shard_range_reassigned", site="store.pod",
                    detail={"range": int(r), "epoch": self.epoch,
                            "to_process": self.process_id})
            for r in self.owned:
                acquire_lease(root, r, self.epoch, self.process_id,
                              self.lease_nonce)
        else:
            # Legacy modulo deal (direct/scrub opens, no ledger): a
            # range whose creation-deal owner (one range per process at
            # creation: owner == range id) is no longer a live process
            # id has been inherited from a lost host.
            self.owned = [r for r in range(self.n_ranges)
                          if r % self.n_processes == self.process_id]
            self.reassigned_ranges = [r for r in self.owned
                                      if r >= self.n_processes
                                      and r < self.n_ranges]
            for r in self.reassigned_ranges:
                record_degradation(
                    "shard_range_reassigned", site="store.pod",
                    detail={"range": int(r), "from_process": int(r),
                            "to_process": self.process_id})
        self._stores: dict[int, SignatureStore] = {}

    @staticmethod
    def is_sharded_root(root: str) -> bool:
        return os.path.exists(os.path.join(root, _TOPOLOGY))

    @staticmethod
    def root_n_ranges(root: str, default: int = 1) -> int:
        """The range count recorded in an existing root's topology, or
        ``default`` for a root not yet created (the MembershipLedger
        must deal the same ranges the store will split)."""
        try:
            with open(os.path.join(root, _TOPOLOGY),
                      encoding="utf-8") as f:
                return int(json.load(f)["n_ranges"])
        except (OSError, ValueError, KeyError, TypeError):
            return int(default)

    def _range_dir(self, r: int) -> str:
        return os.path.join(self.root, f"range_{r:04d}")

    def range_store(self, r: int) -> SignatureStore:
        store = self._stores.get(r)
        if store is None:
            store = SignatureStore(self._range_dir(r), self.policy,
                                   max_bytes=self.max_bytes,
                                   read_only=r not in self.owned)
            self._stores[r] = store
        return store

    def owned_mask(self, digests: np.ndarray) -> np.ndarray:
        """Rows whose digest range THIS process writes — per the epoch
        deal when a membership record governs the store (a promoted
        survivor owns every range regardless of its pid), else the
        legacy modulo deal that self.owned already encodes."""
        rid = digest_range_ids(digests, self.n_ranges)
        return np.isin(rid, np.asarray(self.owned, dtype=np.int32))

    def probe(self, digests: np.ndarray
              ) -> tuple[np.ndarray, np.ndarray]:
        """[N, 2] digests -> (hit [N] bool, loc [N, 3] int32
        (range, shard, row); -1 rows for misses).  Reads every range —
        the pod probe is complete even though this process writes only
        its own ranges."""
        n = digests.shape[0]
        hit = np.zeros(n, bool)
        loc = np.full((n, 3), -1, np.int32)
        if n == 0:
            return hit, loc
        rid = digest_range_ids(digests, self.n_ranges)
        for r in np.unique(rid):
            sel = np.flatnonzero(rid == r)
            h, sh, rw = self.range_store(int(r)).bulk_probe(digests[sel])
            hit[sel] = h
            loc[sel, 0] = int(r)
            loc[sel, 1] = sh
            loc[sel, 2] = rw
        loc[~hit] = -1
        return hit, loc

    def load_signatures(self, loc: np.ndarray) -> np.ndarray:
        """Gather [K, n_hashes] signatures by (range, shard, row)
        triples (every row must be a probe hit)."""
        k = int(loc.shape[0])
        out = np.empty((k, self.policy["n_hashes"]), np.uint32)
        for r in np.unique(loc[:, 0]):
            sel = np.flatnonzero(loc[:, 0] == r)
            out[sel] = self.range_store(int(r)).load_signatures(
                loc[sel, 1], loc[sel, 2])
        return out

    def demote_to_read_only(self) -> None:
        """Self-fence: this process writes NOTHING from here on — every
        open range store flips read-only and the owned set empties (the
        zombie contract: a superseded writer may still read/probe, but
        its append path is gone for the rest of the process)."""
        self.fenced = True
        self.owned = []
        for store in self._stores.values():
            store.read_only = True

    def _check_lease(self, r: int) -> None:
        """Prove tenure on range ``r`` immediately before appending.
        A superseded (or unprovable) lease demotes this store to
        read-only and raises — zero rows reach the range."""
        from ..resilience.coordinator import (LeaseSupersededError,
                                              read_lease, verify_lease)

        if self.fenced:
            raise LeaseSupersededError(
                r, {"epoch": self.epoch, "owner": self.process_id,
                    "nonce": self.lease_nonce}, read_lease(self.root, r))
        if self.epoch is not None:
            try:
                verify_lease(self.root, r, self.epoch, self.process_id,
                             self.lease_nonce)
            except LeaseSupersededError as e:
                self.demote_to_read_only()
                record_degradation(
                    "lease_superseded", site="store.pod",
                    detail={"range": int(r), "held_epoch": int(self.epoch),
                            "process": self.process_id,
                            "current": e.current})
                log.warning("pod: %s", e)
                raise
            return
        # Legacy (un-leased) open against a lease-fenced root: a lease
        # file on disk means an epoch plane governs this root — a writer
        # that cannot prove tenure must fence, not append.
        cur = read_lease(self.root, r)
        if cur is not None:
            self.demote_to_read_only()
            record_degradation(
                "lease_superseded", site="store.pod",
                detail={"range": int(r), "held_epoch": None,
                        "process": self.process_id, "current": cur})
            raise LeaseSupersededError(
                r, {"epoch": None, "owner": self.process_id,
                    "nonce": None}, cur)

    def append(self, digests: np.ndarray, sigs: np.ndarray) -> int:
        """Append novel rows into their owning range stores; rows whose
        range this process does not own are skipped (their owner appends
        them from the allgathered novel tail).  Every owned range's
        current-epoch lease is verified first — a superseded writer
        self-fences (LeaseSupersededError) before touching disk."""
        if digests.shape[0] == 0:
            return 0
        rid = digest_range_ids(digests, self.n_ranges)
        written = 0
        for r in self.owned:
            sel = np.flatnonzero(rid == r)
            if sel.size:
                self._check_lease(r)
                written += self.range_store(r).append(digests[sel],
                                                      sigs[sel])
        return written

    @property
    def n_rows(self) -> int:
        return sum(self.range_store(r).n_rows
                   for r in range(self.n_ranges))

    def refresh(self) -> bool:
        """Adopt peers' committed appends in every range this process has
        opened (see SignatureStore.refresh); returns True when any range
        changed."""
        changed = False
        for store in list(self._stores.values()):
            changed |= store.refresh()
        return changed

    def scrub(self, repair: bool = False, compact: bool = False) -> dict:
        """Aggregate scrub over every range (repair/compact only on owned
        ranges — a reader must not rewrite another host's range)."""
        out: dict = {"store_scrub_ranges": self.n_ranges,
                     "store_scrub_owned_ranges": len(self.owned)}
        state_ok = True
        for r in range(self.n_ranges):
            mine = r in self.owned
            rep = self.range_store(r).scrub(repair=repair and mine,
                                            compact=compact and mine)
            for k, v in rep.items():
                if isinstance(v, bool):
                    continue
                out[k] = out.get(k, 0) + v if isinstance(v, (int, float)) \
                    else v
            state_ok = state_ok and rep.get("store_scrub_state_ok", True)
        out["store_scrub_state_ok"] = state_ok
        out["store_scrub_repaired"] = bool(repair)
        out["store_scrub_mb"] = round(out.get("store_scrub_mb", 0), 3)
        return out

    def verify_signatures(self, items: np.ndarray, sample: int = 256,
                          seed: int = 0) -> dict:
        """Sampled raw-row recompute across every range (see
        SignatureStore.verify_signatures); the sample budget splits by
        each range's share of the probed hits."""
        digests = row_digests(np.ascontiguousarray(items, np.uint32))
        rid = digest_range_ids(digests, self.n_ranges)
        out = {"store_scrub_verify_sampled": 0,
               "store_scrub_verify_mismatch": 0,
               "store_scrub_verify_quarantined": 0,
               "store_scrub_verify_ok": True}
        per = max(1, sample // self.n_ranges)
        for r in range(self.n_ranges):
            sel = np.flatnonzero(rid == r)
            if not sel.size:
                continue
            rep = self.range_store(int(r)).verify_signatures(
                items[sel], sample=per, seed=seed + r)
            for k in ("store_scrub_verify_sampled",
                      "store_scrub_verify_mismatch",
                      "store_scrub_verify_quarantined"):
                out[k] += rep[k]
            out["store_scrub_verify_ok"] &= rep["store_scrub_verify_ok"]
        return out


__all__ = ["POLICY_KEYS", "ShardedSignatureStore", "SignatureStore",
           "digest_range_ids", "digests_fingerprint", "file_crc",
           "row_digests"]
