"""Persistent content-addressed MinHash signature store (warm-path cache).

The paper's workload is *continuous* fuzzing: sessions accrete daily and
the overwhelming majority of each run's coverage vectors were already
seen the run before — yet the cluster pipeline re-encoded, re-shipped
and re-hashed every row from scratch (BENCH_r05: 10.9 s of a 15.2 s wall
was host->device wire at ~10 MB/s; compute was 1.9 s).  Signatures are
tiny, stable summaries worth persisting (the online/batch split argued
by b-bit minwise hashing, arXiv:1205.2958): a session's MinHash
signature depends only on its raw coverage-id set and the hash policy,
so it can be computed once and reused forever.

This module is the host-side store; `cluster/incremental.py` plans the
warm run and merges labels; `cluster/pipeline.py` owns every actual
device transfer (the blessed wire layer).

Layout (all writes tmp + ``os.replace`` — a SIGKILL mid-write leaves a
torn temp file that the next open sweeps, never a half-shard):

- ``store_manifest.json``: the policy key ``(n_hashes, seed,
  quant_bits)`` plus the committed shard list.  A store opened under a
  different policy REFUSES (mirrors ``cluster/checkpoint.py``'s
  ``wire_quant_bits`` handling) — signatures of a different hash family
  or quantized universe are wrong for this run, every one of them.
- ``sig_NNNNN.npy`` / ``key_NNNNN.npy``: append-only shards —
  ``[M, n_hashes] uint32`` signatures, mmap-loaded so a warm probe reads
  only the rows it gathers, and ``[M, 2] uint64`` content digests
  (`row_digests`) keying them.  A shard is visible only once the
  manifest lists it; a torn/truncated shard on disk reads as absent and
  its rows recompute (`_shard_ok`).
- ``state.json`` + ``state_NNNNN.npz``: the last completed run's LSH
  state (labels, per-band bucket tables, per-row shard locator, prefix
  digest) — what lets a warm accreted run merge labels instead of
  rebuilding band tables.  The json is the commit point.

Eviction: FIFO whole shards via ``max_bytes`` (``TSE1M_SIG_STORE_MAX_MB``
env).  Content addressing makes eviction safe — an evicted row simply
probes as a miss and recomputes; an LSH state whose locator references
an evicted shard reads as unusable and the next run rebuilds it.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os

import numpy as np

from ..resilience import fault_point, io_retry_policy, retry_call
from ..utils.atomic import atomic_write
from ..utils.logging import get_logger

log = get_logger("cluster.store")

_MANIFEST = "store_manifest.json"
_STATE = "state.json"

# The policy tuple: any of these changing invalidates every stored
# signature (different hash family / universe), so it is THE manifest key.
POLICY_KEYS = ("n_hashes", "seed", "quant_bits")


# -- content digests ---------------------------------------------------------
#
# 128-bit per-row content hash, fully vectorised: two independent
# multilinear hashes over the row's uint32 ids (mod 2^64, random odd
# per-column coefficients from a FIXED seed — digests must be stable
# across processes and machines), finalised with a splitmix64 mix.
# Pairwise collision probability is ~2^-66; a collision would silently
# reuse another row's signature, so 64 bits alone would be too thin for
# a store that lives for thousands of runs.

_DIGEST_SEED = 0x74736531  # "tse1"
_coef_cache: dict[int, np.ndarray] = {}


def _digest_coeffs(set_size: int) -> np.ndarray:
    c = _coef_cache.get(set_size)
    if c is None:
        rng = np.random.default_rng(_DIGEST_SEED)
        c = (rng.integers(1, 1 << 63, size=(2, set_size), dtype=np.uint64)
             * np.uint64(2) + np.uint64(1))
        _coef_cache[set_size] = c
    return c


def _mix64(x: np.ndarray) -> np.ndarray:
    x = x.copy()
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return x


def row_digests(items: np.ndarray) -> np.ndarray:
    """[N, S] uint32 rows -> [N, 2] uint64 content digests.

    Hashes the RAW (pre-quantization) ids: the store policy carries the
    quantization width, so the same raw row under the same policy always
    maps to the same cached signature.
    """
    items = np.ascontiguousarray(items, dtype=np.uint32)
    if items.ndim != 2:
        raise ValueError(f"expected [N, S] items, got shape {items.shape}")
    n, s = items.shape
    coef = _digest_coeffs(s)
    out = np.empty((n, 2), np.uint64)
    step = 1 << 17  # bound the [step, S] uint64 temporary to ~64 MB
    for lo in range(0, n, step):
        v = items[lo:lo + step].astype(np.uint64)
        for lane in range(2):
            acc = (v * coef[lane][None, :]).sum(axis=1, dtype=np.uint64)
            acc ^= np.uint64(s)  # rows of different widths never collide
            out[lo:lo + step, lane] = _mix64(acc)
    return out


_DIG_DT = np.dtype([("a", "<u8"), ("b", "<u8")])


def _as_struct(digests: np.ndarray) -> np.ndarray:
    """[N, 2] uint64 -> [N] structured view (lexicographically sortable
    and searchsorted-able as one 128-bit key)."""
    d = np.ascontiguousarray(digests, dtype="<u8")
    return d.view(_DIG_DT).reshape(-1)


def digests_fingerprint(digests: np.ndarray) -> str:
    """Order-sensitive fingerprint of a digest sequence — the state's
    accretion-prefix check (`LshState.prefix_digest`)."""
    return hashlib.blake2b(
        np.ascontiguousarray(digests, dtype="<u8").tobytes(),
        digest_size=16).hexdigest()


class SignatureStore:
    """Content-addressed (digest -> MinHash signature) store + the last
    run's LSH state, under one directory.  Single-writer; readers see
    only manifest-committed shards."""

    def __init__(self, directory: str, policy: dict,
                 max_bytes: int | None = None) -> None:
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.policy = {k: int(policy[k]) for k in POLICY_KEYS}
        if max_bytes is None:
            mb = os.environ.get("TSE1M_SIG_STORE_MAX_MB")
            max_bytes = int(float(mb) * 2**20) if mb else None
        self.max_bytes = max_bytes
        self._manifest_path = os.path.join(directory, _MANIFEST)
        self._state_path = os.path.join(directory, _STATE)
        self._mmaps: dict[int, np.ndarray] = {}
        prior = self._load_json(self._manifest_path)
        if prior is not None:
            prior_policy = prior.get("policy", {})
            if prior_policy != self.policy:
                diff = {k: (prior_policy.get(k), self.policy.get(k))
                        for k in set(prior_policy) | set(self.policy)
                        if prior_policy.get(k) != self.policy.get(k)}
                raise ValueError(
                    f"signature store at {directory} was built under a "
                    "different policy — its cached signatures are wrong "
                    "for this run, every one of them; use a fresh "
                    "directory or delete it. mismatched (have, want): "
                    f"{diff}")
            self.shards = [dict(s) for s in prior.get("shards", [])]
        else:
            self.shards = []
            self._write_manifest()
        self._validate_shards()
        self._sweep_orphans()
        self._build_index()

    # -- shard files --------------------------------------------------------

    def _sig_path(self, sid: int) -> str:
        return os.path.join(self.directory, f"sig_{sid:05d}.npy")

    def _key_path(self, sid: int) -> str:
        return os.path.join(self.directory, f"key_{sid:05d}.npy")

    def _load_json(self, path: str) -> dict | None:
        if not os.path.exists(path):
            return None
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            log.warning("unreadable %s (%s); treating as absent", path, e)
            return None

    def _write_manifest(self) -> None:
        with atomic_write(self._manifest_path) as f:
            json.dump({"policy": self.policy, "shards": self.shards}, f)

    def _shard_ok(self, entry: dict) -> bool:
        """True when both shard files exist AND mmap-load with the shapes
        the manifest promises — a torn/truncated file (SIGKILL between
        rename and fsync, filesystem loss) must read as 'absent' so its
        rows recompute, never crash a warm run or feed it garbage."""
        sid, rows = int(entry["id"]), int(entry["rows"])
        try:
            keys = np.load(self._key_path(sid), mmap_mode="r")
            sig = np.load(self._sig_path(sid), mmap_mode="r")
        except Exception as e:  # graftlint: disable=broad-except -- a torn shard must read as absent whatever the failure mode
            log.warning("store shard %d unreadable (%s); its rows will "
                        "recompute", sid, e)
            return False
        return (keys.shape == (rows, 2) and keys.dtype == np.uint64
                and sig.shape == (rows, self.policy["n_hashes"])
                and sig.dtype == np.uint32)

    def _validate_shards(self) -> None:
        good = [s for s in self.shards if self._shard_ok(s)]
        if len(good) != len(self.shards):
            self.shards = good
            self._write_manifest()

    def _sweep_orphans(self) -> None:
        """Remove shard/temp files the manifest does not own — leftovers
        of a crash between file write and manifest commit."""
        owned = {self._sig_path(int(s["id"])) for s in self.shards}
        owned |= {self._key_path(int(s["id"])) for s in self.shards}
        for pat in ("sig_*.npy", "key_*.npy", "*.tmp.npy", "*.tmp.npz",
                    "state_*.npz"):
            for p in glob.glob(os.path.join(self.directory, pat)):
                if p in owned or p == self._current_state_file():
                    continue
                if ".tmp." in p or pat in ("sig_*.npy", "key_*.npy",
                                           "state_*.npz"):
                    with _suppress_oserror():
                        os.remove(p)

    def _current_state_file(self) -> str | None:
        st = self._load_json(self._state_path)
        if st and st.get("file"):
            return os.path.join(self.directory, st["file"])
        return None

    # -- probe index --------------------------------------------------------

    def _build_index(self) -> None:
        if not self.shards:
            self._idx_keys = np.empty(0, _DIG_DT)
            self._idx_keys2d = np.empty((0, 2), np.uint64)
            self._idx_shard = np.empty(0, np.int32)
            self._idx_row = np.empty(0, np.int32)
            return
        keys, shard_of, row_of = [], [], []
        for s in self.shards:
            sid, rows = int(s["id"]), int(s["rows"])
            keys.append(np.asarray(np.load(self._key_path(sid),
                                           mmap_mode="r")))
            shard_of.append(np.full(rows, sid, np.int32))
            row_of.append(np.arange(rows, dtype=np.int32))
        keys2d = np.concatenate(keys)
        order = np.argsort(_as_struct(keys2d), kind="stable")
        self._idx_keys2d = keys2d[order]
        self._idx_keys = _as_struct(self._idx_keys2d)
        self._idx_shard = np.concatenate(shard_of)[order]
        self._idx_row = np.concatenate(row_of)[order]

    @property
    def n_rows(self) -> int:
        return int(self._idx_keys.shape[0])

    @property
    def sig_bytes(self) -> int:
        h = self.policy["n_hashes"]
        return sum(int(s["rows"]) * h * 4 for s in self.shards)

    def shard_ids(self) -> set:
        return {int(s["id"]) for s in self.shards}

    def bulk_probe(self, digests: np.ndarray
                   ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """[N, 2] digests -> (hit [N] bool, shard [N] int32, row [N] int32).
        shard/row are -1 for misses."""
        n = digests.shape[0]
        shard = np.full(n, -1, np.int32)
        row = np.full(n, -1, np.int32)
        if n == 0 or self.n_rows == 0:
            return np.zeros(n, bool), shard, row
        q = _as_struct(digests)
        pos = np.searchsorted(self._idx_keys, q)
        inb = pos < self._idx_keys.shape[0]
        hit = np.zeros(n, bool)
        hit[inb] = np.all(
            self._idx_keys2d[pos[inb]] == np.ascontiguousarray(
                digests, dtype="<u8")[inb], axis=1)
        shard[hit] = self._idx_shard[pos[hit]]
        row[hit] = self._idx_row[pos[hit]]
        return hit, shard, row

    def _sig_mmap(self, sid: int) -> np.ndarray:
        mm = self._mmaps.get(sid)
        if mm is None:
            mm = np.load(self._sig_path(sid), mmap_mode="r")
            self._mmaps[sid] = mm
        return mm

    def load_signatures(self, shard: np.ndarray,
                        row: np.ndarray) -> np.ndarray:
        """Gather [K, n_hashes] uint32 signatures by (shard, row) pairs.
        Rows are gathered per shard in sorted order so the mmap reads
        pages sequentially."""
        k = int(shard.shape[0])
        out = np.empty((k, self.policy["n_hashes"]), np.uint32)
        for sid in np.unique(shard):
            sel = np.flatnonzero(shard == sid)
            rows = row[sel]
            order = np.argsort(rows, kind="stable")
            out[sel[order]] = self._sig_mmap(int(sid))[rows[order]]
        return out

    # -- append -------------------------------------------------------------

    def append(self, digests: np.ndarray, sigs: np.ndarray) -> int:
        """Append (digest, signature) rows not already stored; returns the
        number of rows actually written.  Duplicate digests within the
        batch keep their first occurrence.  The shard write is atomic and
        runs under the shared retry engine (a torn write — or an injected
        one — rewrites the temp files from scratch)."""
        if digests.shape[0] == 0:
            return 0
        hit, _, _ = self.bulk_probe(digests)
        fresh = np.flatnonzero(~hit)
        if fresh.size == 0:
            return 0
        d = np.ascontiguousarray(digests[fresh], dtype=np.uint64)
        s = np.ascontiguousarray(sigs[fresh], dtype=np.uint32)
        _, first = np.unique(_as_struct(d), return_index=True)
        first.sort()
        d, s = d[first], s[first]
        sid = 1 + max((int(e["id"]) for e in self.shards), default=-1)
        sig_path, key_path = self._sig_path(sid), self._key_path(sid)
        sig_tmp, key_tmp = sig_path + ".tmp.npy", key_path + ".tmp.npy"

        def write_shard() -> None:
            np.save(sig_tmp, s)
            np.save(key_tmp, d)
            fault_point("store.sig.save", path=sig_tmp)
            os.replace(sig_tmp, sig_path)
            os.replace(key_tmp, key_path)

        retry_call(write_shard, policy=io_retry_policy(),
                   site="store.sig.save")
        self.shards.append({"id": sid, "rows": int(d.shape[0])})
        self._write_manifest()
        self._evict(keep_sid=sid)
        self._build_index()
        return int(d.shape[0])

    def _evict(self, keep_sid: int) -> None:
        """FIFO whole-shard eviction down to ``max_bytes`` (never the
        shard just written).  Safe by construction: evicted rows probe as
        misses and recompute; a stale LSH-state locator is detected at
        load (`load_state`)."""
        if not self.max_bytes:
            return
        while self.sig_bytes > self.max_bytes and len(self.shards) > 1:
            victim = self.shards[0]
            if int(victim["id"]) == keep_sid:
                break
            self.shards.pop(0)
            self._write_manifest()
            self._mmaps.pop(int(victim["id"]), None)
            log.info("store eviction: dropped shard %d (%d rows)",
                     victim["id"], victim["rows"])
            for p in (self._sig_path(int(victim["id"])),
                      self._key_path(int(victim["id"]))):
                with _suppress_oserror():
                    os.remove(p)

    # -- LSH run state ------------------------------------------------------

    def save_state(self, labels: np.ndarray, locator: np.ndarray,
                   tables: tuple[list, list], digests: np.ndarray,
                   n_bands: int, threshold: float) -> bool:
        """Commit the completed run's LSH state (atomically: npz first,
        then the json pointer).  Returns False — state intentionally not
        saved — when any row's signature is not locatable in the store
        (eviction raced the run); a warm merge must never gather from a
        shard that is gone."""
        if locator.size and int(locator.min()) < 0:
            log.warning("not saving LSH state: %d row(s) have no stored "
                        "signature (store eviction?)",
                        int((locator[:, 0] < 0).sum()))
            return False
        prior = self._load_json(self._state_path) or {}
        gen = int(prior.get("gen", 0)) + 1
        fname = f"state_{gen:05d}.npz"
        path = os.path.join(self.directory, fname)
        tmp = path + ".tmp.npz"
        band_keys, band_reps = tables
        payload = {"labels": np.ascontiguousarray(labels, np.int32),
                   "locator": np.ascontiguousarray(locator, np.int32)}
        for b, (k, r) in enumerate(zip(band_keys, band_reps)):
            payload[f"bk_{b:03d}"] = np.ascontiguousarray(k, np.uint32)
            payload[f"br_{b:03d}"] = np.ascontiguousarray(r, np.int32)

        def write_state() -> None:
            np.savez(tmp, **payload)
            fault_point("store.state.save", path=tmp)
            os.replace(tmp, path)

        retry_call(write_state, policy=io_retry_policy(),
                   site="store.state.save")
        with atomic_write(self._state_path) as f:
            json.dump({"file": fname, "gen": gen,
                       "n_rows": int(labels.shape[0]),
                       "n_bands": int(n_bands),
                       "threshold": float(threshold),
                       "prefix_digest": digests_fingerprint(digests)}, f)
        old = prior.get("file")
        if old and old != fname:
            with _suppress_oserror():
                os.remove(os.path.join(self.directory, old))
        return True

    def load_state(self, n_bands: int, threshold: float):
        """The last run's LSH state, or None when absent, torn, built
        under different banding/threshold, or referencing evicted shards.
        Unlike a sig-policy mismatch this does not refuse the run — the
        signatures are still valid; only the label-merge shortcut is."""
        from .incremental import LshState

        meta = self._load_json(self._state_path)
        if meta is None:
            return None
        if (int(meta.get("n_bands", -1)) != int(n_bands)
                or float(meta.get("threshold", -1.0)) != float(threshold)):
            log.warning("LSH state at %s was built under different "
                        "banding/threshold; rebuilding", self.directory)
            return None
        path = os.path.join(self.directory, str(meta.get("file")))
        try:
            with np.load(path) as z:
                labels = z["labels"]
                locator = z["locator"]
                band_keys = [z[f"bk_{b:03d}"] for b in range(n_bands)]
                band_reps = [z[f"br_{b:03d}"] for b in range(n_bands)]
        except Exception as e:  # graftlint: disable=broad-except -- a torn state file must read as absent whatever the failure mode
            log.warning("LSH state unreadable (%s); rebuilding", e)
            return None
        if labels.shape[0] != int(meta["n_rows"]):
            return None
        if locator.size and not (set(np.unique(locator[:, 0]).tolist())
                                 <= self.shard_ids()):
            log.warning("LSH state references evicted shard(s); rebuilding")
            return None
        return LshState(n_rows=int(meta["n_rows"]),
                        labels=labels, locator=locator,
                        band_keys_sorted=band_keys, band_reps=band_reps,
                        prefix_digest=str(meta["prefix_digest"]))


class _suppress_oserror:
    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        return et is not None and issubclass(et, OSError)


__all__ = ["POLICY_KEYS", "SignatureStore", "digests_fingerprint",
           "row_digests"]
