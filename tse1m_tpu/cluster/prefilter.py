"""Host-side one-permutation b-bit LSH prefilter (wire v3's second lever).

At the north-star operating point the cold run is link-bound 5:1: the
wire, not MinHash, is the wall.  But ~40% of the planted workload (and
the long tail of a real fuzzing corpus) is *isolated* — rows that share
no near-duplicate with anything — and an isolated row provably labels
itself under the pipeline's signature-agreement rule (its estimated
Jaccard against every elected hub is below threshold, so it gains no
verified edge and keeps its own index).  Rows we can prove-or-measure
isolated never need to cross the link at full fidelity at all.

Deciding "which rows can possibly collide" is much cheaper than MinHash
proper: b-bit minwise hashing (arXiv:1205.2958) shows a few bits of
hash remnant retain enough resemblance signal, and a C-MinHash-style
one-permutation pass (arXiv:2109.03337) gets K minwise values from ONE
element-hash pass plus K cheap multiplicative mixes instead of K full
hash passes.  Here: hash every element once (the "permutation"), then
for each of ``N_BANDS * HASHES_PER_BAND`` mixes take the LOWEST
``KEY_BITS`` bits of the row minimum (the minimum concentrates near 0;
its low bits are the uniform part — the 1205.2958 construction); a
*band key* packs ``HASHES_PER_BAND`` adjacent remnants (32-bit keys —
wide enough that chance collisions at 1M rows are a few hundred
spuriously-kept rows, not a recall loss).  A row that shares no band
key with any other row is bucketed singleton everywhere and is dropped
from the device batch; everything else ships as before.

The filter buckets the RAW ids even when the wire quantizes
(encode.quantize_ids): in a 2^8..2^10 universe per-hash buckets are
dense (nothing is singleton), while raw-space isolation still implies
no verifiable device edge — quantization lifts a random pair's Jaccard
to only ~set_size/2^b (~0.03..0.13), and a verified edge needs the
128-hash estimate to reach the threshold, exponentially unlikely from
there.  Raw-space bucketing also makes the mask independent of the
quant-drop degradation rung: a mid-stream width drop never invalidates
the kept set.

Semantics contract (pipeline.ClusterParams.prefilter = off|auto|on):
the filter is a *transfer* optimization — labels must equal the
unfiltered run's elementwise.  A false KEEP only costs wire; a false
DROP could split a cluster, so the defaults are sized for the regime
the verifier actually accepts (est >= threshold ~ 0.5): a colliding
pair at Jaccard J is missed with probability ~(1 - J^2)^20 — ~7e-11 at
the planted J~0.83, ~2e-5 even at J=0.65 — decaying exponentially in
band count.  CI asserts label parity elementwise and
``prefilter_recall`` (below) self-checks against planted truth;
threshold <= 0 disables the filter (with no verifier, every proposed
edge is accepted and isolation proves nothing).

Host-only by design: numpy, no jax import, no device — the wire layer
(cluster/pipeline.py) stays the only plane that moves bytes.
"""

from __future__ import annotations

import numpy as np

N_BANDS = 20          # prefilter bands (keys per row)
HASHES_PER_BAND = 2   # b-bit minwise values packed per band key
KEY_BITS = 16         # b-bit minwise remnant per hash (arXiv:1205.2958)

# The one-permutation pass: a fixed odd multiply-add bijection over
# uint32 (the "permutation"), then per-hash odd multiplicative mixes.
_PERM_MULT = np.uint32(0x9E3779B1)
_PERM_ADD = np.uint32(0x7F4A7C15)
_ROW_CHUNK = 1 << 16  # bound the [chunk, S] temporaries to cache-friendly


def _mix_consts(seed: int, k: int) -> np.ndarray:
    """k odd uint32 multipliers, deterministic per seed; offset from the
    device family's stream so the two stay independent."""
    rng = np.random.default_rng(seed ^ 0x5EEDB177)
    return (rng.integers(1, 1 << 32, size=k, dtype=np.uint32)
            | np.uint32(1))


def band_keys_host(items: np.ndarray, seed: int = 0) -> np.ndarray:
    """[N, S] uint32 feature sets -> [N, N_BANDS] uint32 band keys.

    One element-hash pass + K multiplicative mixes; each mix's row
    minimum contributes its lowest ``KEY_BITS`` bits, ``HASHES_PER_BAND``
    of them packed into one 32-bit band key."""
    items = np.ascontiguousarray(items, dtype=np.uint32)
    n = items.shape[0]
    k = N_BANDS * HASHES_PER_BAND
    consts = _mix_consts(seed, k)
    keys = np.zeros((n, N_BANDS), np.uint32)
    # The b-bit remnant is the LOWEST b bits of the minimum (the minimum
    # itself concentrates near 0 — its low bits are the uniform part,
    # which is the 1205.2958 construction).
    mask = np.uint32((1 << KEY_BITS) - 1)
    with np.errstate(over="ignore"):
        for lo in range(0, n, _ROW_CHUNK):
            blk = items[lo:lo + _ROW_CHUNK]
            perm = blk * _PERM_MULT + _PERM_ADD     # the one permutation
            for j in range(N_BANDS):
                key = np.zeros(blk.shape[0], np.uint32)
                for t in range(HASHES_PER_BAND):
                    c = consts[j * HASHES_PER_BAND + t]
                    mins = (perm * c).min(axis=1)   # C-MinHash-style mix
                    key = (key << np.uint32(KEY_BITS)) | (mins & mask)
                keys[lo:lo + _ROW_CHUNK, j] = key
    return keys


def collide_mask(items: np.ndarray, seed: int = 0,
                 scheme: str = "kminhash") -> np.ndarray:
    """[N] bool: True for rows sharing at least one band bucket with
    another row (the rows that can possibly collide on device).  Rows
    with False are bucketed singleton in EVERY band and skip the wire.

    ``scheme`` names the run's signature kernel family
    (cluster/schemes.py) — validated here so a typo'd policy fails at
    the filter, not three stages later.  The MASK itself is one
    implementation for the whole family, because the isolation argument
    lives in the id-set space every scheme estimates: kminhash and
    cminhash both estimate plain Jaccard of the presented rows, and
    ``weighted`` rows arrive replica-expanded (schemes.expand_weighted)
    so set isolation in replica space IS weighted-Jaccard isolation.
    A scheme-specific key derivation would change which rows ship, but
    never whether a dropped row could have gained a verified edge."""
    from .schemes import get_scheme

    get_scheme(scheme)
    n = items.shape[0]
    collide = np.zeros(n, bool)
    if n < 2:
        return collide
    keys = band_keys_host(items, seed)
    for j in range(N_BANDS):
        k = keys[:, j]
        uniq, counts = np.unique(k, return_counts=True)
        collide |= counts[np.searchsorted(uniq, k)] > 1
        if collide.all():
            break
    return collide


def prefilter_recall(keep: np.ndarray, truth: np.ndarray) -> float:
    """Self-check against planted truth: the fraction of rows belonging
    to multi-member planted clusters that the filter KEPT.  1.0 means no
    planted near-duplicate was dropped; bench asserts this."""
    truth = np.asarray(truth)
    uniq, counts = np.unique(truth, return_counts=True)
    multi = counts[np.searchsorted(uniq, truth)] > 1
    denom = int(multi.sum())
    if denom == 0:
        return 1.0
    return float(np.asarray(keep, bool)[multi].sum() / denom)
