"""End-to-end device clustering pipeline with mesh sharding.

Single-device: one jitted chain items -> signatures -> band keys -> bucket
reps -> verified edges -> propagated labels, fed over the H2D link by the
base-delta wire encoding (cluster/encode.py) when it pays.

Multi-device: MinHash + band keys stay row-sharded (embarrassingly
data-parallel); the bucket/verify/propagate tail is band-sharded with an
explicit `shard_map` kernel (cluster/sharded.py) — `all_to_all` re-shards
the keys so each device sorts only B/d bands, and label propagation
reduces across devices with `pmin`.  Labels are bit-identical to the
single-device path in both cases.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .encode import (_AUTO_MIN_BYTES, _AUTO_MIN_DELTA_FRACTION, encode_delta)
from .lsh import bucket_representatives, estimated_jaccard, propagate_labels
from .minhash import band_keys, make_hash_params, minhash_signatures
from .minhash_pallas import minhash_and_keys


@dataclass(frozen=True)
class ClusterParams:
    n_hashes: int = 128
    n_bands: int = 16
    threshold: float = 0.5       # min estimated Jaccard to accept an edge
    n_iters: int = 12            # label-propagation safety cap (propagation
    #                              converges early via its global all-done
    #                              check, see lsh.propagate_labels; 12 jumps
    #                              bound worst-case 2^12-long rep chains)
    seed: int = 0
    use_pallas: str = "auto"     # auto | never | force | interpret
    block_n: int = 512
    # H2D double-buffering: split the item axis into this many chunks and
    # device_put each one separately — jax transfers are async, so chunk
    # i+1 streams over the (slow, remote-PJRT) link while MinHash runs on
    # chunk i.  0 = auto (chunk when items exceed _CHUNK_BYTES), 1 = off.
    h2d_chunks: int = 0
    # H2D payload encoding (cluster/encode.py): 'auto' base-delta-encodes
    # large inputs when enough rows are near-duplicates (the measured win:
    # 183 -> ~104 MB on the 1M north star); 'delta' forces it; 'pack24'
    # keeps the plain packed lane.  Labels are bit-identical either way
    # (hub election is by original index — lsh.bucket_representatives).
    encoding: str = "auto"


# Observability surface for bench.py: stats of the last single-host
# cluster_sessions call (encoding chosen, lane sizes, wire bytes, host
# encode seconds).  A plain dict, overwritten per call — not an API.
last_run_info: dict = {}


def _cluster_from_sig(sig, keys, threshold: float, n_iters: int):
    reps = bucket_representatives(keys)
    est = estimated_jaccard(sig, reps)
    self_idx = jnp.arange(sig.shape[0], dtype=jnp.int32)[:, None]
    valid = (est >= threshold) & (reps != self_idx)
    return propagate_labels(reps, valid, n_iters=n_iters)


@partial(jax.jit, static_argnames=("n_bands", "threshold", "n_iters"))
def _cluster_jax(items, a, b, n_bands: int, threshold: float, n_iters: int):
    sig = minhash_signatures(items, a, b)
    keys = band_keys(sig, n_bands)
    return _cluster_from_sig(sig, keys, threshold, n_iters)


# Module-level jit wrappers: wrapping inside cluster_sessions would key the
# compile cache to a fresh function object per call and retrace every time.
_cluster_from_sig_jit = jax.jit(
    _cluster_from_sig, static_argnames=("threshold", "n_iters"))


@jax.jit
def _decode_delta_packed(full_d, rep_d, counts_d, pos_d, val3_d):
    """Delta lane -> [D, S] uint32 rows, on device.

    Gather each delta row's base from the decoded full lane, then scatter
    its (position, value) diffs.  Flat diff stream is CSR-style: per-row
    counts cumsum to offsets; each flat slot finds its row by searchsorted.
    """
    vals = _unpack24(val3_d)
    offsets = jnp.cumsum(counts_d.astype(jnp.int32))
    t = jnp.arange(pos_d.shape[0], dtype=jnp.int32)
    row = jnp.searchsorted(offsets, t, side="right").astype(jnp.int32)
    base = full_d[rep_d]
    return base.at[row, pos_d.astype(jnp.int32)].set(vals, mode="drop")


@jax.jit
def _decode_delta_raw(full_d, rep_d, counts_d, pos_d, val_d):
    offsets = jnp.cumsum(counts_d.astype(jnp.int32))
    t = jnp.arange(pos_d.shape[0], dtype=jnp.int32)
    row = jnp.searchsorted(offsets, t, side="right").astype(jnp.int32)
    base = full_d[rep_d]
    return base.at[row, pos_d.astype(jnp.int32)].set(val_d, mode="drop")


@partial(jax.jit, static_argnames=("n", "threshold", "n_iters"))
def _cluster_encoded_labels(sig, keys, mask_bytes, n: int, threshold: float,
                            n_iters: int):
    """Cluster rows that sit in lane order and return labels in ORIGINAL
    order, equal elementwise to the unencoded path's.

    ``mask_bytes`` is the encoder's 1-bit-per-row membership mask
    (little-endian); cumsums of it reconstruct both permutations, so the
    wire cost of reordering is n/8 bytes instead of 4n.  Hub election by
    original index (see bucket_representatives) keeps the verified edge
    set — and therefore the components and the min-original-index labels —
    identical to a run without the encoder.
    """
    bits = ((mask_bytes[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :])
            & 1).reshape(-1)[:n].astype(jnp.int32)  # 1 = delta lane
    n_full_dyn = n - jnp.sum(bits)
    dr = jnp.cumsum(bits) - bits          # exclusive cumsum: delta rank
    fr = jnp.cumsum(1 - bits) - (1 - bits)
    lane_of = jnp.where(bits == 1, n_full_dyn + dr, fr).astype(jnp.int32)
    orig_of = jnp.zeros(n, jnp.int32).at[lane_of].set(
        jnp.arange(n, dtype=jnp.int32))
    reps = bucket_representatives(keys, orig=orig_of, lane_of=lane_of)
    est = estimated_jaccard(sig, reps)
    self_idx = jnp.arange(n, dtype=jnp.int32)[:, None]
    valid = (est >= threshold) & (reps != self_idx)
    lab = propagate_labels(reps, valid, n_iters=n_iters)  # lane-space ids
    cmin = jnp.full(n, n, jnp.int32).at[lab].min(orig_of)
    return cmin[lab][lane_of]


def _validate_encoding(params: ClusterParams) -> None:
    if params.encoding not in ("auto", "delta", "pack24"):
        raise ValueError(f"unknown encoding {params.encoding!r}; "
                         "expected auto | delta | pack24")


def _maybe_encode(items: np.ndarray, params: ClusterParams):
    """Apply the ClusterParams.encoding policy; None = ship plain lanes."""
    _validate_encoding(params)
    if params.encoding == "pack24":
        return None
    if params.encoding == "auto" and items.nbytes < _AUTO_MIN_BYTES:
        return None
    frac = _AUTO_MIN_DELTA_FRACTION if params.encoding == "auto" else 0.0
    return encode_delta(items, min_delta_fraction=frac)


def _cluster_encoded(items: np.ndarray, enc, a, b, params: ClusterParams,
                     pack: bool) -> np.ndarray:
    """Single-host encoded path: stream the full lane chunked (retaining
    the decoded device rows), decode the delta lane against it, MinHash
    both, cluster with original-order labels.

    ``pack`` is the caller's should_pack24 decision over BOTH lanes: delta
    values can exceed 2^24 even when every full-lane row packs, and the
    wire format uses one width.
    """
    n = items.shape[0]
    kw = dict(use_pallas=params.use_pallas, block_n=params.block_n)
    full = enc.full_rows
    step, _ = _stream_plan(full, params, pack)
    chunks_d, parts = [], []
    for i in range(0, full.shape[0], step):
        cd = _put_chunk(full[i:i + step], pack)
        chunks_d.append(cd)
        parts.append(minhash_and_keys(cd, a, b, params.n_bands, **kw))
    full_d = chunks_d[0] if len(chunks_d) == 1 else jnp.concatenate(chunks_d)
    rep_d = jax.device_put(enc.rep_in_full)
    counts_d = jax.device_put(enc.counts)
    pos_d = jax.device_put(enc.pos_flat)
    if pack:
        delta_items = _decode_delta_packed(
            full_d, rep_d, counts_d, pos_d,
            jax.device_put(_pack24_host(enc.val_flat)))
    else:
        delta_items = _decode_delta_raw(full_d, rep_d, counts_d, pos_d,
                                        jax.device_put(enc.val_flat))
    dsig, dkeys = minhash_and_keys(delta_items, a, b, params.n_bands, **kw)
    sig = jnp.concatenate([p[0] for p in parts] + [dsig])
    keys = jnp.concatenate([p[1] for p in parts] + [dkeys])
    labels = _cluster_encoded_labels(sig, keys, jax.device_put(enc.mask_bits),
                                     n, params.threshold, params.n_iters)
    return np.asarray(labels)


def cluster_sessions(items, params: ClusterParams | None = None,
                     mesh: jax.sharding.Mesh | None = None,
                     axis: str = "data") -> np.ndarray:
    """Cluster [N, S] uint32 session feature sets -> [N] int32 labels.

    With a mesh, `items` is placed sharded along its first axis; the jitted
    pipeline keeps the MinHash stage sharded and lets XLA gather for the
    bucket-sort stage.
    """
    params = params or ClusterParams()
    a, b = make_hash_params(params.n_hashes, params.seed)
    a, b = jnp.asarray(a), jnp.asarray(b)

    if mesh is not None:
        # The base-delta wire encoding is a single-host H2D optimisation;
        # mesh feeding ships raw shards (multi-host rows never transit one
        # host's link), so params.encoding does not apply here — but a
        # typo'd value must still fail here, not only in local testing.
        _validate_encoding(params)
        last_run_info.clear()
        last_run_info.update(encoding="mesh-raw")
        from ..parallel.mesh import pad_to_devices

        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(axis, None))
        if isinstance(items, jax.Array):
            # Pre-sharded global array (the multi-host feeding path:
            # parallel/multihost.put_process_local — no single host holds
            # all rows, so there is nothing to pad or device_put here).
            if items.shape[0] % mesh.devices.size:
                raise ValueError(
                    "pre-sharded items must be padded to a multiple of the "
                    "mesh size — feed through parallel/multihost."
                    "put_process_local_padded and slice the labels back to "
                    "the logical row count")
            n = items.shape[0]
            items_d = items
        else:
            items = np.ascontiguousarray(items, dtype=np.uint32)
            n = items.shape[0]
            items, _ = pad_to_devices(items, mesh)
            items_d = jax.device_put(items, sharding)
        from .sharded import _sharded_cluster_kernel

        # Band-sharded tail (cluster/sharded.py): distributes the
        # bucket/verify/propagate stages, not just MinHash.
        kernel = _sharded_cluster_kernel(mesh, axis, params.n_bands,
                                         params.threshold, params.n_iters)
        labels = kernel(items_d, a, b)
        if jax.process_count() > 1:
            # Multi-host: shards live on non-addressable devices, so a
            # plain np.asarray would fail — allgather across processes
            # (rides DCN; every host gets the full label vector).
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(labels, tiled=True))[:n]
        return np.asarray(labels)[:n]
    items = np.ascontiguousarray(items, dtype=np.uint32)

    import time as _time

    t0 = _time.perf_counter()
    enc = _maybe_encode(items, params)
    pack = should_pack24(items)  # once: a full O(N*S) max scan
    last_run_info.clear()
    if enc is not None:
        last_run_info.update(
            encoding="delta", encode_s=round(_time.perf_counter() - t0, 4),
            n_full=enc.n_full, n_delta=enc.n_delta,
            wire_mb=round(enc.wire_bytes(pack) / 2**20, 1))
        return _cluster_encoded(items, enc, a, b, params, pack)

    if params.use_pallas != "never":
        last_run_info.update(
            encoding="pack24" if pack else "raw",
            wire_mb=round(items.shape[0] * items.shape[1]
                          * (3 if pack else 4) / 2**20, 1))
        sig, keys = _minhash_streamed(items, a, b, params, pack)
        labels = _cluster_from_sig_jit(sig, keys, params.threshold,
                                       params.n_iters)
        return np.asarray(labels)

    # Explicit H2D placement up front (no device argument — keeps the array
    # uncommitted so callers can still steer with jax.default_device).
    # This two-step path ships raw uint32 (no 24-bit pack) — report it so.
    last_run_info.update(encoding="raw",
                         wire_mb=round(items.nbytes / 2**20, 1))
    return np.asarray(_cluster_jax(jax.device_put(items), a, b,
                                   params.n_bands, params.threshold,
                                   params.n_iters))


# Auto-chunking threshold for H2D double-buffering: one chunk per
# _CHUNK_BYTES of items, capped at _MAX_CHUNKS.  The cap is tuned for a
# remote/tunneled PJRT link (round-4 sweep at 1M x 64: 8 chunks throttled
# the link to ~21 MB/s vs ~27 MB/s for big single puts; 4 chunks kept big-
# put bandwidth while still overlapping the ~1.8 s device compute behind
# the transfer).
_CHUNK_BYTES = 48 * 1024 * 1024
_MAX_CHUNKS = 4

# Feature ids below 2^24 (the OSS-Fuzz coverage-region universe, and the
# synth generator's default) travel as 3 packed bytes instead of a uint32
# — a 25% cut of the dominant H2D transfer.  Inputs with larger ids fall
# back to raw uint32 transparently.
_PACK_LIMIT = 1 << 24


def should_pack24(items: np.ndarray) -> bool:
    """True when `items` takes the 24-bit packed H2D encoding (feature ids
    all below _PACK_LIMIT) — THE pack decision the streamed pipeline ships;
    probes (bench.py) must use this, not re-derive it."""
    return bool(items.size) and bool(items.max() < _PACK_LIMIT)


def _stream_plan(items: np.ndarray, params: ClusterParams,
                 pack: bool | None = None) -> tuple[int, bool]:
    """(chunk step, pack?) — THE chunking policy, shared by the streamed
    and resumable paths so their chunks always align.  step >= n means
    single-shot (chunking off or input too small to double-buffer); chunks
    land on block_n boundaries so the pallas path pads at most the final
    chunk.  ``pack`` skips the O(N*S) should_pack24 max scan when the
    caller already decided it."""
    n = items.shape[0]
    n_chunks = params.h2d_chunks
    if n_chunks == 0:
        n_chunks = int(min(_MAX_CHUNKS, max(1, items.nbytes // _CHUNK_BYTES)))
    if pack is None:
        pack = should_pack24(items)
    if n_chunks <= 1 or n < 2 * params.block_n:
        return max(n, 1), pack
    step = -(-n // n_chunks)
    return -(-step // params.block_n) * params.block_n, pack


def _put_chunk(chunk: np.ndarray, pack: bool):
    """Stage one chunk on device (24-bit packed when the plan says so)."""
    if pack:
        return _unpack24(jax.device_put(_pack24_host(chunk)))
    return jax.device_put(chunk)


@jax.jit
def _unpack24(packed):
    """[n, S, 3] uint8 little-endian -> [n, S] uint32 (on device)."""
    p = packed.astype(jnp.uint32)
    return p[..., 0] | (p[..., 1] << 8) | (p[..., 2] << 16)


def _pack24_host(chunk: np.ndarray) -> np.ndarray:
    """[n, S] uint32 (< 2^24) -> contiguous [n, S, 3] uint8 byte view."""
    if chunk.dtype.byteorder == ">":  # big-endian hosts: normalize first
        chunk = chunk.astype("<u4")
    return np.ascontiguousarray(
        chunk[..., None].view(np.uint8)[..., :3])


def cluster_sessions_resumable(items, params: ClusterParams | None = None,
                               checkpoint_dir: str | None = None,
                               cleanup: bool = True) -> np.ndarray:
    """`cluster_sessions` with per-chunk checkpoint/resume (SURVEY §5 A4).

    Each streamed chunk's (signatures, band keys) shard persists under
    ``checkpoint_dir`` as it completes (`cluster/checkpoint.py`); a killed
    run re-invoked with the same directory recomputes only unfinished
    chunks, then proceeds to label propagation.  ``cleanup`` removes the
    shards after a successful run.  With no directory this is exactly
    `cluster_sessions`.  Single-host form; a pod job gives each process
    its own directory for its local row range.
    """
    params = params or ClusterParams()
    if checkpoint_dir is None:
        return cluster_sessions(items, params)
    from .checkpoint import ClusterCheckpoint

    items = np.ascontiguousarray(items, dtype=np.uint32)
    n = items.shape[0]
    if n == 0:
        return np.empty(0, np.int32)
    a, b = make_hash_params(params.n_hashes, params.seed)
    a, b = jnp.asarray(a), jnp.asarray(b)
    kw = dict(use_pallas=params.use_pallas, block_n=params.block_n)
    enc = _maybe_encode(items, params)

    if enc is None:
        step, pack = _stream_plan(items, params)  # same chunks as streamed
        ckpt = ClusterCheckpoint(checkpoint_dir, items, params, step)
        parts = []
        for idx, i in enumerate(range(0, n, step)):
            # A shard that exists but is torn (truncated npz) reads as
            # not-done and the chunk recomputes — resume must produce the
            # same labels as an uninterrupted run, never crash on it.
            shard = (ckpt.load_chunk_or_none(idx)
                     if ckpt.chunk_done(idx) else None)
            if shard is not None:
                parts.append((jax.device_put(shard[0]),
                              jax.device_put(shard[1])))
                continue
            sig, keys = minhash_and_keys(_put_chunk(items[i:i + step], pack),
                                         a, b, params.n_bands, **kw)
            # D2H for durability: the persisted shard IS the resume state.
            ckpt.save_chunk(idx, np.asarray(sig), np.asarray(keys))
            parts.append((sig, keys))
        sig = jnp.concatenate([p[0] for p in parts])
        keys = jnp.concatenate([p[1] for p in parts])
        labels = np.asarray(_cluster_from_sig_jit(sig, keys, params.threshold,
                                                  params.n_iters))
        if cleanup:
            ckpt.cleanup()
        return labels

    # Encoded layout: one shard per full-lane chunk + one delta-lane shard.
    # The lane split is part of the manifest (it decides what each shard
    # holds); a resume whose encoder drew different lanes — e.g. the native
    # grouping pass available on one machine but not the other — refuses
    # instead of concatenating mismatched shards.
    import hashlib

    full = enc.full_rows
    pack = should_pack24(items)  # one width for both lanes
    step, _ = _stream_plan(full, params, pack)
    n_full_chunks = max(1, -(-full.shape[0] // step))
    lane_fp = hashlib.blake2b(
        enc.mask_bits.tobytes() + enc.counts.tobytes(),
        digest_size=16).hexdigest()
    ckpt = ClusterCheckpoint(checkpoint_dir, items, params, step,
                             extra={"encoding": "delta",
                                    "lane_fingerprint": lane_fp},
                             n_chunks=n_full_chunks + 1)
    parts = []
    chunks_d: list = [None] * n_full_chunks
    for idx, i in enumerate(range(0, full.shape[0], step)):
        shard = (ckpt.load_chunk_or_none(idx)
                 if ckpt.chunk_done(idx) else None)
        if shard is not None:
            parts.append((jax.device_put(shard[0]),
                          jax.device_put(shard[1])))
            continue
        cd = _put_chunk(full[i:i + step], pack)
        chunks_d[idx] = cd
        sig, keys = minhash_and_keys(cd, a, b, params.n_bands, **kw)
        ckpt.save_chunk(idx, np.asarray(sig), np.asarray(keys))
        parts.append((sig, keys))
    didx = n_full_chunks
    dshard = ckpt.load_chunk_or_none(didx) if ckpt.chunk_done(didx) else None
    if dshard is not None:
        dpart = (jax.device_put(dshard[0]), jax.device_put(dshard[1]))
    else:
        # Delta decode needs the full lane device-resident; chunks whose
        # shards were loaded from disk never shipped their rows this run,
        # so put them now (raw rows only — their signatures are done).
        for idx, i in enumerate(range(0, full.shape[0], step)):
            if chunks_d[idx] is None:
                chunks_d[idx] = _put_chunk(full[i:i + step], pack)
        full_d = (chunks_d[0] if len(chunks_d) == 1
                  else jnp.concatenate(chunks_d))
        rep_d = jax.device_put(enc.rep_in_full)
        counts_d = jax.device_put(enc.counts)
        pos_d = jax.device_put(enc.pos_flat)
        if pack:
            delta_items = _decode_delta_packed(
                full_d, rep_d, counts_d, pos_d,
                jax.device_put(_pack24_host(enc.val_flat)))
        else:
            delta_items = _decode_delta_raw(full_d, rep_d, counts_d, pos_d,
                                            jax.device_put(enc.val_flat))
        dsig, dkeys = minhash_and_keys(delta_items, a, b, params.n_bands,
                                       **kw)
        ckpt.save_chunk(didx, np.asarray(dsig), np.asarray(dkeys))
        dpart = (dsig, dkeys)
    sig = jnp.concatenate([p[0] for p in parts] + [dpart[0]])
    keys = jnp.concatenate([p[1] for p in parts] + [dpart[1]])
    labels = np.asarray(_cluster_encoded_labels(
        sig, keys, jax.device_put(enc.mask_bits), n, params.threshold,
        params.n_iters))
    if cleanup:
        ckpt.cleanup()
    return labels


def _minhash_streamed(items: np.ndarray, a, b, params: ClusterParams,
                      pack: bool | None = None):
    """items -> (signatures, band keys), overlapping H2D with compute.

    The ~N*S*4-byte items transfer is the dominant wall-time cost on a
    remote/tunneled PJRT backend, while MinHash itself is cheap.  jax's
    device_put and kernel dispatch are both async, so transferring the item
    axis in chunks lets chunk i+1 stream while chunk i computes.  Chunks are
    equal-sized (the last may be short), so at most two kernel shapes are
    compiled.  Results are concatenated on device; labels are unchanged vs
    the unchunked path because MinHash is row-independent.
    """
    n = items.shape[0]
    step, pack = _stream_plan(items, params, pack)
    kw = dict(use_pallas=params.use_pallas, block_n=params.block_n)
    if step >= n:
        return minhash_and_keys(_put_chunk(items, pack), a, b,
                                params.n_bands, **kw)
    parts = []
    for i in range(0, n, step):
        parts.append(minhash_and_keys(_put_chunk(items[i:i + step], pack),
                                      a, b, params.n_bands, **kw))
    sig = jnp.concatenate([p[0] for p in parts])
    keys = jnp.concatenate([p[1] for p in parts])
    return sig, keys
