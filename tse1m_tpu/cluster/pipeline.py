"""End-to-end device clustering pipeline with mesh sharding.

Single-device: one jitted chain items -> signatures -> band keys -> bucket
reps -> verified edges -> propagated labels.

Multi-device: the FLOP/bandwidth-heavy stage (MinHash + band keys) is
sharded over the item axis of a `jax.sharding.Mesh` via sharding
constraints under jit — XLA's SPMD partitioner runs it collective-free
(embarrassingly data-parallel) and inserts the all-gather where the
clustering stage's global sort needs full visibility.  This mirrors the
scaling-book recipe: annotate shardings, let XLA place collectives on ICI.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .lsh import bucket_representatives, estimated_jaccard, propagate_labels
from .minhash import band_keys, make_hash_params, minhash_signatures
from .minhash_pallas import minhash_and_keys


@dataclass(frozen=True)
class ClusterParams:
    n_hashes: int = 128
    n_bands: int = 16
    threshold: float = 0.5       # min estimated Jaccard to accept an edge
    n_iters: int = 12            # label-propagation safety cap (propagation
    #                              converges early via its global all-done
    #                              check, see lsh.propagate_labels; 12 jumps
    #                              bound worst-case 2^12-long rep chains)
    seed: int = 0
    use_pallas: str = "auto"     # auto | never | force | interpret
    block_n: int = 512
    # H2D double-buffering: split the item axis into this many chunks and
    # device_put each one separately — jax transfers are async, so chunk
    # i+1 streams over the (slow, remote-PJRT) link while MinHash runs on
    # chunk i.  0 = auto (chunk when items exceed _CHUNK_BYTES), 1 = off.
    h2d_chunks: int = 0


def _cluster_from_sig(sig, keys, threshold: float, n_iters: int):
    reps = bucket_representatives(keys)
    est = estimated_jaccard(sig, reps)
    self_idx = jnp.arange(sig.shape[0], dtype=jnp.int32)[:, None]
    valid = (est >= threshold) & (reps != self_idx)
    return propagate_labels(reps, valid, n_iters=n_iters)


@partial(jax.jit, static_argnames=("n_bands", "threshold", "n_iters"))
def _cluster_jax(items, a, b, n_bands: int, threshold: float, n_iters: int):
    sig = minhash_signatures(items, a, b)
    keys = band_keys(sig, n_bands)
    return _cluster_from_sig(sig, keys, threshold, n_iters)


# Module-level jit wrappers: wrapping inside cluster_sessions would key the
# compile cache to a fresh function object per call and retrace every time.
_cluster_from_sig_jit = jax.jit(
    _cluster_from_sig, static_argnames=("threshold", "n_iters"))


@partial(jax.jit, static_argnames=("sharding", "n_bands", "threshold", "n_iters"))
def _cluster_sharded(items_d, a, b, sharding, n_bands: int, threshold: float,
                     n_iters: int):
    items_d = jax.lax.with_sharding_constraint(items_d, sharding)
    sig = minhash_signatures(items_d, a, b)
    keys = band_keys(sig, n_bands)
    return _cluster_from_sig(sig, keys, threshold, n_iters)


def cluster_sessions(items, params: ClusterParams | None = None,
                     mesh: jax.sharding.Mesh | None = None,
                     axis: str = "data") -> np.ndarray:
    """Cluster [N, S] uint32 session feature sets -> [N] int32 labels.

    With a mesh, `items` is placed sharded along its first axis; the jitted
    pipeline keeps the MinHash stage sharded and lets XLA gather for the
    bucket-sort stage.
    """
    params = params or ClusterParams()
    a, b = make_hash_params(params.n_hashes, params.seed)
    a, b = jnp.asarray(a), jnp.asarray(b)

    if mesh is not None:
        from ..parallel.mesh import pad_to_devices

        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(axis, None))
        if isinstance(items, jax.Array):
            # Pre-sharded global array (the multi-host feeding path:
            # parallel/multihost.put_process_local — no single host holds
            # all rows, so there is nothing to pad or device_put here).
            if items.shape[0] % mesh.devices.size:
                raise ValueError(
                    "pre-sharded items must be padded to a multiple of the "
                    "mesh size — feed through parallel/multihost."
                    "put_process_local_padded and slice the labels back to "
                    "the logical row count")
            n = items.shape[0]
            items_d = items
        else:
            items = np.ascontiguousarray(items, dtype=np.uint32)
            n = items.shape[0]
            items, _ = pad_to_devices(items, mesh)
            items_d = jax.device_put(items, sharding)
        labels = _cluster_sharded(items_d, a, b, sharding, params.n_bands,
                                  params.threshold, params.n_iters)
        if jax.process_count() > 1:
            # Multi-host: shards live on non-addressable devices, so a
            # plain np.asarray would fail — allgather across processes
            # (rides DCN; every host gets the full label vector).
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(labels, tiled=True))[:n]
        return np.asarray(labels)[:n]
    items = np.ascontiguousarray(items, dtype=np.uint32)

    if params.use_pallas != "never":
        sig, keys = _minhash_streamed(items, a, b, params)
        labels = _cluster_from_sig_jit(sig, keys, params.threshold,
                                       params.n_iters)
        return np.asarray(labels)

    # Explicit H2D placement up front (no device argument — keeps the array
    # uncommitted so callers can still steer with jax.default_device).
    return np.asarray(_cluster_jax(jax.device_put(items), a, b,
                                   params.n_bands, params.threshold,
                                   params.n_iters))


# Auto-chunking threshold for H2D double-buffering: one chunk per
# _CHUNK_BYTES of items, capped at _MAX_CHUNKS.  The cap is tuned for a
# remote/tunneled PJRT link (round-4 sweep at 1M x 64: 8 chunks throttled
# the link to ~21 MB/s vs ~27 MB/s for big single puts; 4 chunks kept big-
# put bandwidth while still overlapping the ~1.8 s device compute behind
# the transfer).
_CHUNK_BYTES = 48 * 1024 * 1024
_MAX_CHUNKS = 4

# Feature ids below 2^24 (the OSS-Fuzz coverage-region universe, and the
# synth generator's default) travel as 3 packed bytes instead of a uint32
# — a 25% cut of the dominant H2D transfer.  Inputs with larger ids fall
# back to raw uint32 transparently.
_PACK_LIMIT = 1 << 24


def should_pack24(items: np.ndarray) -> bool:
    """True when `items` takes the 24-bit packed H2D encoding (feature ids
    all below _PACK_LIMIT) — THE pack decision the streamed pipeline ships;
    probes (bench.py) must use this, not re-derive it."""
    return bool(items.size) and bool(items.max() < _PACK_LIMIT)


def _stream_plan(items: np.ndarray, params: ClusterParams) -> tuple[int, bool]:
    """(chunk step, pack?) — THE chunking policy, shared by the streamed
    and resumable paths so their chunks always align.  step >= n means
    single-shot (chunking off or input too small to double-buffer); chunks
    land on block_n boundaries so the pallas path pads at most the final
    chunk."""
    n = items.shape[0]
    n_chunks = params.h2d_chunks
    if n_chunks == 0:
        n_chunks = int(min(_MAX_CHUNKS, max(1, items.nbytes // _CHUNK_BYTES)))
    pack = should_pack24(items)
    if n_chunks <= 1 or n < 2 * params.block_n:
        return max(n, 1), pack
    step = -(-n // n_chunks)
    return -(-step // params.block_n) * params.block_n, pack


def _put_chunk(chunk: np.ndarray, pack: bool):
    """Stage one chunk on device (24-bit packed when the plan says so)."""
    if pack:
        return _unpack24(jax.device_put(_pack24_host(chunk)))
    return jax.device_put(chunk)


@jax.jit
def _unpack24(packed):
    """[n, S, 3] uint8 little-endian -> [n, S] uint32 (on device)."""
    p = packed.astype(jnp.uint32)
    return p[..., 0] | (p[..., 1] << 8) | (p[..., 2] << 16)


def _pack24_host(chunk: np.ndarray) -> np.ndarray:
    """[n, S] uint32 (< 2^24) -> contiguous [n, S, 3] uint8 byte view."""
    if chunk.dtype.byteorder == ">":  # big-endian hosts: normalize first
        chunk = chunk.astype("<u4")
    return np.ascontiguousarray(
        chunk[..., None].view(np.uint8)[..., :3])


def cluster_sessions_resumable(items, params: ClusterParams | None = None,
                               checkpoint_dir: str | None = None,
                               cleanup: bool = True) -> np.ndarray:
    """`cluster_sessions` with per-chunk checkpoint/resume (SURVEY §5 A4).

    Each streamed chunk's (signatures, band keys) shard persists under
    ``checkpoint_dir`` as it completes (`cluster/checkpoint.py`); a killed
    run re-invoked with the same directory recomputes only unfinished
    chunks, then proceeds to label propagation.  ``cleanup`` removes the
    shards after a successful run.  With no directory this is exactly
    `cluster_sessions`.  Single-host form; a pod job gives each process
    its own directory for its local row range.
    """
    params = params or ClusterParams()
    if checkpoint_dir is None:
        return cluster_sessions(items, params)
    from .checkpoint import ClusterCheckpoint

    items = np.ascontiguousarray(items, dtype=np.uint32)
    n = items.shape[0]
    if n == 0:
        return np.empty(0, np.int32)
    a, b = make_hash_params(params.n_hashes, params.seed)
    a, b = jnp.asarray(a), jnp.asarray(b)
    step, pack = _stream_plan(items, params)  # same chunks as streamed path
    ckpt = ClusterCheckpoint(checkpoint_dir, items, params, step)
    kw = dict(use_pallas=params.use_pallas, block_n=params.block_n)

    parts = []
    for idx, i in enumerate(range(0, n, step)):
        if ckpt.chunk_done(idx):
            sig_h, keys_h = ckpt.load_chunk(idx)
            parts.append((jax.device_put(sig_h), jax.device_put(keys_h)))
            continue
        sig, keys = minhash_and_keys(_put_chunk(items[i:i + step], pack),
                                     a, b, params.n_bands, **kw)
        # D2H for durability: the persisted shard IS the resume state.
        ckpt.save_chunk(idx, np.asarray(sig), np.asarray(keys))
        parts.append((sig, keys))
    sig = jnp.concatenate([p[0] for p in parts])
    keys = jnp.concatenate([p[1] for p in parts])
    labels = np.asarray(_cluster_from_sig_jit(sig, keys, params.threshold,
                                              params.n_iters))
    if cleanup:
        ckpt.cleanup()
    return labels


def _minhash_streamed(items: np.ndarray, a, b, params: ClusterParams):
    """items -> (signatures, band keys), overlapping H2D with compute.

    The ~N*S*4-byte items transfer is the dominant wall-time cost on a
    remote/tunneled PJRT backend, while MinHash itself is cheap.  jax's
    device_put and kernel dispatch are both async, so transferring the item
    axis in chunks lets chunk i+1 stream while chunk i computes.  Chunks are
    equal-sized (the last may be short), so at most two kernel shapes are
    compiled.  Results are concatenated on device; labels are unchanged vs
    the unchunked path because MinHash is row-independent.
    """
    n = items.shape[0]
    step, pack = _stream_plan(items, params)
    kw = dict(use_pallas=params.use_pallas, block_n=params.block_n)
    if step >= n:
        return minhash_and_keys(_put_chunk(items, pack), a, b,
                                params.n_bands, **kw)
    parts = []
    for i in range(0, n, step):
        parts.append(minhash_and_keys(_put_chunk(items[i:i + step], pack),
                                      a, b, params.n_bands, **kw))
    sig = jnp.concatenate([p[0] for p in parts])
    keys = jnp.concatenate([p[1] for p in parts])
    return sig, keys
