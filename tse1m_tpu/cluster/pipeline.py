"""End-to-end device clustering pipeline with mesh sharding.

Single-device: one jitted chain items -> signatures -> band keys -> bucket
reps -> verified edges -> propagated labels.

Multi-device: the FLOP/bandwidth-heavy stage (MinHash + band keys) is
sharded over the item axis of a `jax.sharding.Mesh` via sharding
constraints under jit — XLA's SPMD partitioner runs it collective-free
(embarrassingly data-parallel) and inserts the all-gather where the
clustering stage's global sort needs full visibility.  This mirrors the
scaling-book recipe: annotate shardings, let XLA place collectives on ICI.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .lsh import bucket_representatives, estimated_jaccard, propagate_labels
from .minhash import band_keys, make_hash_params, minhash_signatures
from .minhash_pallas import minhash_and_keys


@dataclass(frozen=True)
class ClusterParams:
    n_hashes: int = 128
    n_bands: int = 16
    threshold: float = 0.5       # min estimated Jaccard to accept an edge
    n_iters: int = 12            # label-propagation jumps (2^12 chain cover)
    seed: int = 0
    use_pallas: str = "auto"     # auto | never | force | interpret
    block_n: int = 512


def _cluster_from_sig(sig, keys, threshold: float, n_iters: int):
    reps = bucket_representatives(keys)
    est = estimated_jaccard(sig, reps)
    self_idx = jnp.arange(sig.shape[0], dtype=jnp.int32)[:, None]
    valid = (est >= threshold) & (reps != self_idx)
    return propagate_labels(reps, valid, n_iters=n_iters)


@partial(jax.jit, static_argnames=("n_bands", "threshold", "n_iters"))
def _cluster_jax(items, a, b, n_bands: int, threshold: float, n_iters: int):
    sig = minhash_signatures(items, a, b)
    keys = band_keys(sig, n_bands)
    return _cluster_from_sig(sig, keys, threshold, n_iters)


# Module-level jit wrappers: wrapping inside cluster_sessions would key the
# compile cache to a fresh function object per call and retrace every time.
_cluster_from_sig_jit = jax.jit(
    _cluster_from_sig, static_argnames=("threshold", "n_iters"))


@partial(jax.jit, static_argnames=("sharding", "n_bands", "threshold", "n_iters"))
def _cluster_sharded(items_d, a, b, sharding, n_bands: int, threshold: float,
                     n_iters: int):
    items_d = jax.lax.with_sharding_constraint(items_d, sharding)
    sig = minhash_signatures(items_d, a, b)
    keys = band_keys(sig, n_bands)
    return _cluster_from_sig(sig, keys, threshold, n_iters)


def cluster_sessions(items, params: ClusterParams | None = None,
                     mesh: jax.sharding.Mesh | None = None,
                     axis: str = "data") -> np.ndarray:
    """Cluster [N, S] uint32 session feature sets -> [N] int32 labels.

    With a mesh, `items` is placed sharded along its first axis; the jitted
    pipeline keeps the MinHash stage sharded and lets XLA gather for the
    bucket-sort stage.
    """
    params = params or ClusterParams()
    a, b = make_hash_params(params.n_hashes, params.seed)
    a, b = jnp.asarray(a), jnp.asarray(b)
    items = np.ascontiguousarray(items, dtype=np.uint32)

    if mesh is not None:
        from ..parallel.mesh import pad_to_devices

        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(axis, None))
        n = items.shape[0]
        items, _ = pad_to_devices(items, mesh)
        items_d = jax.device_put(items, sharding)
        labels = _cluster_sharded(items_d, a, b, sharding, params.n_bands,
                                  params.threshold, params.n_iters)
        return np.asarray(labels)[:n]

    # Explicit H2D placement up front: the ~256MB items transfer is the
    # dominant cost on a remote/tunneled PJRT backend, so put it on device
    # once here rather than letting each kernel re-stage the host array.
    # No device argument — keeps the array uncommitted so callers can still
    # steer placement with jax.default_device.
    items_d = jax.device_put(items)

    if params.use_pallas != "never":
        sig, keys = minhash_and_keys(items_d, a, b, params.n_bands,
                                     use_pallas=params.use_pallas,
                                     block_n=params.block_n)
        labels = _cluster_from_sig_jit(sig, keys, params.threshold,
                                       params.n_iters)
        return np.asarray(labels)

    return np.asarray(_cluster_jax(items_d, a, b, params.n_bands,
                                   params.threshold, params.n_iters))
